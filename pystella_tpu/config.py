"""Central environment-variable registry.

Every ``PYSTELLA_*`` / ``BENCH_*`` knob the package or its drivers read
is declared here — name, default, type, and a one-line description —
and read through :func:`getenv` / the typed getters. The source-tier
lint (:mod:`pystella_tpu.lint.source`) enforces the contract: an
``os.environ`` read of a project-prefixed variable anywhere else in
``pystella_tpu/`` fails CI unless the site carries an explicit
``# env-registry: NAME`` pragma naming a variable registered here (the
escape hatch for the stdlib-only modules that must stay loadable BY
FILE in a jax-free supervisor and therefore cannot import this module
through the package).

The table in ``doc/observability.md`` ("Environment variables") is the
human rendering; the lint's ``env-doc`` check fails when a registered
variable is missing from it, so registry and doc cannot drift.

This module is stdlib-only and free of package-relative imports, so a
supervisor that must not import jax can load it by file (the same trick
``bench.py`` uses for ``obs/events.py``)::

    spec = importlib.util.spec_from_file_location(
        "_cfg", ".../pystella_tpu/config.py")

Reads are LIVE (no import-time caching): sweep harnesses vary knobs
like ``PYSTELLA_VMEM_LIMIT_MB`` between kernel builds in one process.
"""

from __future__ import annotations

import dataclasses
import os

__all__ = ["EnvVar", "register", "registered", "getenv", "get_int",
           "get_float", "get_bool", "snapshot"]

_UNSET = object()


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One registered environment variable."""

    name: str
    default: str | None
    help: str
    kind: str = "str"        # str | int | float | bool | path
    #: where it is consumed: "package" (pystella_tpu/ runtime),
    #: "driver" (bench/example scripts), "test" (suite config), or
    #: "external" (not ours — documented because reports fingerprint it)
    scope: str = "package"


#: name -> EnvVar, in registration order
_REGISTRY: dict[str, EnvVar] = {}


def register(name, default=None, help="", kind="str", scope="package"):
    """Register a variable (idempotent for identical declarations);
    returns ``name``. Conflicting re-registration raises — two call
    sites disagreeing about a default is exactly the config drift the
    registry exists to prevent."""
    var = EnvVar(name=str(name), default=default, help=help, kind=kind,
                 scope=scope)
    existing = _REGISTRY.get(var.name)
    if existing is not None and existing != var:
        raise ValueError(
            f"env var {name!r} already registered with a different "
            f"declaration: {existing} vs {var}")
    _REGISTRY[var.name] = var
    return var.name


def registered():
    """The registry as a name -> :class:`EnvVar` dict (copy)."""
    return dict(_REGISTRY)


def getenv(name, default=_UNSET):
    """The raw string value of a REGISTERED variable (the registered
    default — or ``default`` when given — when unset). Reading an
    unregistered name raises ``KeyError``: register it first."""
    var = _REGISTRY.get(name)
    if var is None:
        raise KeyError(
            f"env var {name!r} is not registered in pystella_tpu.config "
            "— declare it there (with a default and description) before "
            "reading it")
    fallback = var.default if default is _UNSET else default
    val = os.environ.get(name)
    return fallback if val is None else val


def get_int(name, default=_UNSET):
    val = getenv(name, default)
    return None if val is None else int(float(val))


def get_float(name, default=_UNSET):
    val = getenv(name, default)
    return None if val is None else float(val)


#: accepted spellings for boolean variables (everything else is False,
#: matching ``parallel.overlap.env_setting``'s tolerant parse)
_TRUE = ("1", "true", "on", "yes")


def get_bool(name, default=_UNSET):
    val = getenv(name, default)
    if val is None:
        return None
    return str(val).strip().lower() in _TRUE


def snapshot():
    """``{name: raw value}`` for every registered variable currently
    set in the process environment (no defaults) — the config side of a
    forensic/environment fingerprint."""
    return {name: os.environ[name] for name in _REGISTRY
            if name in os.environ}


# ---------------------------------------------------------------------------
# the registry: package runtime knobs
# ---------------------------------------------------------------------------

register("PYSTELLA_EVENT_LOG", default=None, kind="path",
         help="JSONL run-event log path picked up by obs.events.get_log() "
              "when no explicit obs.configure() call was made; unset "
              "disables implicit event logging")
register("PYSTELLA_EVENT_ROTATE_MB", default=None, kind="float",
         help="size-triggered event-log rollover in MiB: when the live "
              "JSONL file reaches this size, obs.events.EventLog "
              "renames it to <stem>.<n>.jsonl and opens a fresh file, "
              "so a persistent server cannot grow one unbounded log; "
              "ledger ingestion reads the whole rotated family; unset "
              "disables rotation")
register("PYSTELLA_HALO_OVERLAP", default="auto", kind="bool",
         help="halo-exchange/compute overlap policy for sharded stencils: "
              "1/0 force on/off, unset/'auto' enables exactly when the "
              "mesh shards a lattice axis (parallel.overlap.enabled)")
register("PYSTELLA_VMEM_LIMIT_MB", default="100", kind="float",
         help="per-kernel Mosaic scoped-VMEM request in MiB "
              "(ops.pallas_stencil.vmem_limit_bytes); read at each "
              "kernel build so sweeps can vary it in-process")
register("PYSTELLA_BLOCK_BUDGET_MB", default="24", kind="float",
         help="VMEM budget in MiB that ops.pallas_stencil.choose_blocks "
              "fits the streaming window ring into")
register("PYSTELLA_COMPILE_CACHE_DIR", default="bench_results/xla_cache",
         kind="path",
         help="persistent XLA compilation-cache directory wired by "
              "obs.memory.ensure_compilation_cache (drivers call it "
              "before dispatching); relative paths anchor at the "
              "repository root, not the cwd; ''/'0'/'off'/'none' "
              "disables (un-wiring any already-set cache) — a "
              "re-dialed process then pays every backend compile again")
register("PYSTELLA_WARMSTART_DIR", default=None, kind="path",
         help="default artifact directory for the AOT warm-start "
              "store (obs.warmstart): the export/verify CLI and "
              "bench.py's warm-start leg persist and load matching "
              "artifacts there, skipping trace+compile for them — "
              "fingerprint mismatches fall back to the jit path and "
              "are recorded as warmstart_mismatch events")
register("PYSTELLA_ENSEMBLE_SIZE", default="8", kind="int",
         help="default member count for ensemble (batched-scenario) "
              "runs: bench.py's smoke ensemble payload and "
              "EnsembleDriver use it when no explicit size is given")
register("PYSTELLA_ENSEMBLE_AXIS", default="ensemble",
         help="name of the leading device-mesh axis the ensemble tier "
              "packs members along (parallel.decomp.ensemble_mesh); "
              "the lattice axes keep their x/y/z names after it")
register("PYSTELLA_ENSEMBLE_MAX_EVICTIONS", default="16", kind="int",
         help="evict-and-resample budget per ensemble run: beyond this "
              "many member evictions the EnsembleMonitor declares the "
              "whole batch diverged (SimulationDiverged) instead of "
              "resampling forever — a configuration producing that "
              "many bad draws is itself broken")
register("PYSTELLA_ENSEMBLE_RESAMPLE", default="1", kind="bool",
         help="eviction policy: 1 (default) resamples an evicted "
              "member's slot from its scenario's sampler (fresh seed), "
              "0 masks the slot out for the rest of the run instead")
register("PYSTELLA_RESILIENCE_CHECKPOINT_EVERY", default="50", kind="int",
         help="default checkpoint interval in steps for the elastic "
              "Supervisor (resilience.supervisor) — also the bound on "
              "replayed steps after a fault: recovery restores the "
              "durable last-good checkpoint and replays at most one "
              "interval")
register("PYSTELLA_RESILIENCE_MAX_RECOVERIES", default="4", kind="int",
         help="incident budget per supervised run: beyond this many "
              "recovered faults the Supervisor raises RecoveryFailed "
              "instead of replaying forever — an environment producing "
              "that many incidents needs an operator, not a retry loop")
register("PYSTELLA_RESILIENCE_BACKOFF_BASE_S", default="1.0", kind="float",
         help="first recovery-attempt backoff in seconds (jittered "
              "exponential, factor 2) for the Supervisor's per-incident "
              "retry loop (resilience.retry)")
register("PYSTELLA_RESILIENCE_BACKOFF_MAX_S", default="60", kind="float",
         help="recovery-attempt backoff ceiling in seconds")
register("PYSTELLA_RESILIENCE_RETRY_BUDGET_S", default="600",
         kind="float",
         help="wall budget in seconds for ONE incident's recovery "
              "attempts (re-dial + restore retries); exhausting it "
              "raises RecoveryFailed with the last underlying error")
register("PYSTELLA_FAULT_DEVICE_SUBSET", default=None,
         help="arm a DeviceSubsetFault from the environment "
              "(resilience.FaultInjector.from_env, consumed by drivers "
              "that opt in, e.g. the remesh drills): '<step>:<count>' "
              "loses the last <count> devices of the state's device "
              "set entering <step>; unset disables")
register("PYSTELLA_FAULT_DEVICE_SUBSET_PERSIST", default="1", kind="bool",
         help="persistence of the env-armed device-subset fault: 1 "
              "(default) models real hardware — lost devices STAY "
              "lost, and only a re-meshed program that no longer "
              "touches them replays through cleanly; 0 makes it a "
              "one-shot transient like the other fault kinds")
register("PYSTELLA_SERVICE_SLOTS", default="4", kind="int",
         help="batch slots per scenario-service lease "
              "(service.ScenarioService): each scheduler dispatch "
              "leases up to this many shape-compatible requests to one "
              "batched EnsembleStepper program")
register("PYSTELLA_SERVICE_CHUNK", default="2", kind="int",
         help="steps per batched dispatch inside a scenario-service "
              "lease; preemption and checkpointing happen at chunk "
              "boundaries, so this is also the preemption-latency "
              "granularity")
register("PYSTELLA_SERVICE_COLD_POLICY", default="compile",
         help="admission policy for a request whose (model, lattice, "
              "mesh) signature has no warm-pool entry "
              "(service.AdmissionController): 'compile' admits it "
              "queued behind the build+compile of a fresh pool entry "
              "(its time-to-first-step then pays the compile), "
              "'reject' refuses it with a typed ColdSignature verdict")
register("PYSTELLA_SERVICE_QUOTA", default="64", kind="int",
         help="per-tenant admission quota of the scenario service's "
              "fair-share scheduler: submissions beyond this many "
              "queued requests for one tenant are rejected "
              "(service_reject event, reason 'quota') instead of "
              "letting one tenant starve the others")
register("PYSTELLA_SERVICE_PREEMPT", default="1", kind="bool",
         help="priority preemption in the scenario service: 1 "
              "(default) lets a pending request of a strictly higher "
              "priority class preempt a running lease at the next "
              "chunk boundary (drain -> durable checkpoint -> "
              "requeue, no work lost); 0 runs every lease to "
              "completion")
register("PYSTELLA_LIVE_PORT", default="0", kind="int",
         help="TCP port of the opt-in in-process live telemetry "
              "endpoint (obs.live: /metrics Prometheus exposition, "
              "/healthz liveness+readiness, /slo burn-rate state), "
              "bound to 127.0.0.1 on a daemon thread around "
              "ScenarioService.serve(); 0 (default) or unset disables "
              "the live plane entirely — emit paths and event logs "
              "are then byte-identical to a build without it")
register("PYSTELLA_SLO_FAST_WINDOW_S", default="60", kind="float",
         help="fast window in seconds of the live SLO burn-rate "
              "monitor (obs.slo.SLOMonitor): an alert fires only when "
              "the windowed metric breaches its bar over BOTH the "
              "fast window (it is still happening) and the slow "
              "window (it is sustained), and resolves when the fast "
              "window recovers or empties")
register("PYSTELLA_SLO_SLOW_WINDOW_S", default="300", kind="float",
         help="slow window in seconds of the live SLO burn-rate "
              "monitor — the sustained-breach half of the fast/slow "
              "multi-window alert rule")
register("PYSTELLA_SLO_MIN_SAMPLES", default="1", kind="int",
         help="minimum samples the fast window must hold before a "
              "percentile/rate SLO leg may fire (count-kind legs are "
              "exempt — their value IS the sample count); raise it on "
              "a busy service so a single outlier dispatch cannot "
              "page")
register("PYSTELLA_PERF", default="1", kind="bool",
         help="continuous-performance plane master switch (obs.perf): "
              "1 (default) lets StepTimer and the scenario service's "
              "dispatch loop feed the process-default step-time "
              "digest + CUSUM change-point detector; 0 disables the "
              "plane entirely — observe() is a no-op and the default "
              "monitor is never constructed")
register("PYSTELLA_PERF_WINDOW", default="64", kind="int",
         help="healthy-baseline reference window (samples) of the "
              "continuous-performance CUSUM detector "
              "(obs.perf.CusumDetector): location/scale are the "
              "median/MAD over the last this-many healthy samples "
              "per program signature; the window freezes while an "
              "anomaly is open so the baseline cannot absorb the "
              "regression it is reporting")
register("PYSTELLA_PERF_MIN_SAMPLES", default="16", kind="int",
         help="samples the reference window must hold before the "
              "continuous-performance detector may fire — warmup and "
              "short runs stay quiet")
register("PYSTELLA_PERF_CUSUM_K", default="0.5", kind="float",
         help="CUSUM slack in sigmas (obs.perf): a sample only "
              "accumulates drift when it exceeds baseline + k*sigma; "
              "also the recovery band — perf_recovered needs the "
              "recent samples back below that bar")
register("PYSTELLA_PERF_CUSUM_H", default="8.0", kind="float",
         help="CUSUM fire threshold in accumulated clipped sigmas "
              "(obs.perf): per-sample increments are clipped at 4 "
              "sigma, so with the default 8.0 a single spike cannot "
              "fire — only >= 2 consecutive far-outliers (or a longer "
              "run of modest ones) accumulate past it")
register("PYSTELLA_PERF_RECOVER_N", default="5", kind="int",
         help="consecutive in-band samples (below baseline + k*sigma) "
              "after which an open perf anomaly emits perf_recovered "
              "and the CUSUM accumulator resets")
register("PYSTELLA_PERF_CAPTURE_DIR", default=None, kind="path",
         help="artifact root of the anomaly-triggered flight recorder "
              "(obs.perf.FlightRecorder): when set, a fired "
              "perf_anomaly starts a rate-limited jax.profiler "
              "capture of the next PYSTELLA_PERF_CAPTURE_STEPS steps "
              "and writes the Perfetto trace under this directory "
              "(perf_capture event carries the path); unset (default) "
              "disables automatic capture — anomalies still fire, "
              "nothing is profiled")
register("PYSTELLA_PERF_CAPTURE_STEPS", default="8", kind="int",
         help="steps the anomaly-triggered flight recorder keeps the "
              "profiler running before closing the capture and "
              "emitting perf_capture")
register("PYSTELLA_PERF_CAPTURE_COOLDOWN_S", default="600", kind="float",
         help="minimum seconds between anomaly-triggered profiler "
              "capture starts — the rate limit: an anomaly storm "
              "produces at most one trace per cooldown plus a "
              "suppression count, not a disk full of traces")
register("PYSTELLA_FLEET_DIR", default=None, kind="path",
         help="shared replica-registry directory of the fleet "
              "observability plane (service.registry / obs.fleet): "
              "when set, ScenarioService.serve() announces a "
              "heartbeated JSON record there (replica id, live URL, "
              "stack fingerprint, warm-pool fingerprints, queue "
              "depth) and withdraws it on exit; unset (default) "
              "disables the fleet plane entirely")
register("PYSTELLA_FLEET_HEARTBEAT_S", default="2.0", kind="float",
         help="cadence in seconds at which a fleet replica rewrites "
              "its registry record (service.registry.ReplicaRegistry); "
              "each beat refreshes the dynamic fields (queue depth, "
              "serving state, warm fingerprints); <= 0 announces once "
              "and never beats (tests)")
register("PYSTELLA_FLEET_EXPIRE_S", default="10", kind="float",
         help="heartbeat age in seconds past which registry readers "
              "(obs.fleet.FleetAggregator, service status --fleet) "
              "treat a replica record as stale/dead — a crashed "
              "replica cannot tombstone itself, so expiry is how the "
              "fleet notices; keep it several heartbeats wide")
register("PYSTELLA_FLEET_SCRAPE_TIMEOUT_S", default="2.0", kind="float",
         help="per-endpoint HTTP timeout in seconds for one fleet "
              "scrape of a replica's /metrics, /slo, /healthz "
              "(obs.fleet.FleetAggregator); a replica slower than "
              "this counts as a scrape failure, not a hang of the "
              "whole aggregation pass")
register("PYSTELLA_TRACE_SERVICE", default="1", kind="bool",
         help="request-scoped distributed tracing in the scenario "
              "service: 1 (default) allocates a trace id per "
              "ScenarioRequest and threads trace/span/parent fields "
              "(event schema v2) through submission, dispatch, the "
              "supervised lease loop, and retire, so obs.spans can "
              "assemble per-request critical-path latency; 0 emits "
              "v1-shaped events with no trace context")
register("PYSTELLA_TRACE_EXPORT", default=None, kind="path",
         help="default Perfetto output path for the assembled service "
              "span timeline: `python -m pystella_tpu.obs.spans` "
              "writes the request-timeline trace file there when no "
              "explicit --perfetto is given, and bench.py --smoke "
              "mirrors its service_trace.json export to it; unset "
              "skips the extra copy")
register("PYSTELLA_AUTOTUNE", default="1", kind="bool",
         help="persistent-autotuner consult policy for fused Pallas "
              "kernel builds (ops.autotune): 1 (default) consults "
              "bench_results/autotune_<device-kind>.json before the "
              "choose_blocks heuristic (stale entries are refused with "
              "an autotune_mismatch event, exactly like stale AOT "
              "warm-start artifacts); 0 skips the table entirely — the "
              "tier-1 suite pins 0 so ambient builds stay hermetic")
register("PYSTELLA_AUTOTUNE_DIR", default="bench_results", kind="path",
         help="directory of the persistent autotune winner tables "
              "(autotune_<device-kind>.json, one per device kind); "
              "relative paths anchor at the repository root; the sweep "
              "CLI (python -m pystella_tpu.ops.autotune) writes there "
              "and kernel builds read back through the same store")
register("PYSTELLA_CHUNK_STAGES", default="0", kind="int",
         help="default temporal-blocking chunk depth for the fused "
              "steppers when no chunk_stages= argument and no autotune "
              "table entry decides it: an even number >= 4 of RK "
              "stages advanced per resident whole-RK-chunk kernel "
              "(VMEM-window halo widens by h per stage pair; "
              "infeasible shapes degrade to pair kernels with a "
              "kernel_fallback event); 0 (default) keeps the "
              "pair-stage tier")
register("PYSTELLA_FORCE_BLOCKS", default=None,
         help="'bx,by' override for the fused steppers' streaming-"
              "kernel blocking — beats both the autotune table and the "
              "choose_blocks heuristic (sweep harness escape hatch; "
              "the block_choice event records source='override')")
register("PYSTELLA_FFT_SCHEME", default="auto",
         help="distributed-FFT scheme the planner (fourier.plan."
              "make_dft) and the spectra/projector/Poisson consumers "
              "select: 'auto' (the shard_map pencil tier whenever the "
              "grid x/y axes divide the total device count, else the "
              "DFT reshard/partial/replicate chain), 'pencil' (force "
              "the shard_map tier; infeasible shapes raise), or 'dft' "
              "(force the legacy declarative-reshard tiering)")
register("PYSTELLA_FFT_REPLICATE_LIMIT", default="1073741824",
         kind="float",
         help="replicate-fallback size limit in bytes for transforms "
              "no distributed scheme serves: above it DFT construction "
              "raises instead of silently replicating the k-space "
              "array on every device (override per-instance with "
              "replicate_limit=/allow_replicate=)")
register("PYSTELLA_FFT_STENCIL", default="auto",
         help="FFT-stencil fast-path policy (ops.fft_stencil."
              "use_fft_stencil): 1/0 force the k-space/direct path, "
              "unset/'auto' decides by the flops crossover model "
              "(direct tap cost vs 2 x 5 N log2 N transform cost)")
register("PYSTELLA_FFT_STENCIL_CROSSOVER", default="1.5", kind="float",
         help="direct-to-FFT flops ratio the auto FFT-stencil policy "
              "requires before taking the k-space path (margin for the "
              "transpose traffic the flops model does not see)")
register("PYSTELLA_CAPACITY_HEADROOM", default="0.9", kind="float",
         help="memory-aware admission budget as a fraction of device "
              "HBM capacity (obs.capacity.CapacityMonitor): resident "
              "warm-pool programs + the candidate lease's predicted "
              "footprint must fit capacity x this, else the request is "
              "rejected with a typed CapacityExceeded verdict")
register("PYSTELLA_CAPACITY_POLICY", default="reject",
         help="what memory-aware admission does on overcommit: "
              "'reject' (default) refuses the request outright "
              "(capacity_reject event), 'evict' first drops idle "
              "warm-pool entries not backing queued work "
              "(capacity_evict events) and re-checks — "
              "queue-behind-eviction")
register("PYSTELLA_CAPACITY_BYTES", default=None, kind="int",
         help="device-capacity override in bytes for the admission "
              "budget; unset uses the allocator's bytes_limit from "
              "device.memory_stats(), and where neither exists (CPU) "
              "the capacity check skips honestly (decision reason "
              "'no-capacity-limit') instead of guessing")
register("PYSTELLA_CAPACITY_DIR",
         help="persistence directory for predicted HBM footprints "
              "(obs.capacity.FootprintLedger, *.footprint.json beside "
              "the warm-start artifacts); unset falls back to "
              "PYSTELLA_WARMSTART_DIR, and with neither set the "
              "ledger stays in-memory")

# ---------------------------------------------------------------------------
# driver knobs (bench.py / bench_scaling.py / examples)
# ---------------------------------------------------------------------------

register("PYSTELLA_BENCH_PLATFORM", default="cpu", scope="driver",
         help="platform for the benchmark scripts and test-file "
              "__main__ blocks: 'cpu' (default; forces the virtual CPU "
              "mesh) or 'tpu' (leaves the remote-TPU plugin registered)")
register("PYSTELLA_LINT_PLATFORM", default="cpu", scope="driver",
         help="platform the lint CLI lowers the audited step functions "
              "on: 'cpu' (default; static analysis needs no hardware) "
              "or 'tpu'")
register("PYSTELLA_GATE_COMM_EXCESS_PCT", default="25", kind="float",
         scope="driver",
         help="gate threshold for the modeled-vs-measured comm check: "
              "measured collective traffic exceeding the dataflow "
              "lint tier's static model by more than this percentage "
              "fails the gate (the model is an upper bound — measured "
              "above it means unattributed traffic)")
register("BENCH_EVENT_LOG", default=None, kind="path", scope="driver",
         help="override for bench.py's run-event JSONL path (default "
              "bench_results/run_events.jsonl)")
register("BENCH_NO_CACHE", default="0", kind="bool", scope="driver",
         help="1 ignores bench_results/tpu_lines.jsonl (persisted "
              "hardware lines) when re-emitting cached metrics")
register("BENCH_PROFILE", default=None, kind="path", scope="driver",
         help="log dir: wrap each preheat timing window in a "
              "jax.profiler capture; per-scope durations land in the "
              "event log as trace_summary events")
register("BENCH_GRIDS", default="128,256,512", scope="driver",
         help="comma-separated cube edge sizes the bench payload runs "
              "smallest-first")
register("BENCH_DIAL_BUDGET", default="1800", kind="float", scope="driver",
         help="seconds allowed per TPU-payload device dial")
register("BENCH_CONFIG_BUDGET", default="300", kind="float", scope="driver",
         help="seconds allowed per config once the device is up")
register("BENCH_TOTAL_BUDGET", default=None, kind="float", scope="driver",
         help="seconds for the whole bench run (default 1500 when "
              "cached hardware lines exist, else 2400)")
register("BENCH_EXTRAS", default="1", kind="bool", scope="driver",
         help="0 skips the secondary config matrix (wave equation, "
              "GW+spectra, multigrid, coupled)")
register("BENCH_FORCE_CPU", default="0", kind="bool", scope="driver",
         help="1 skips TPU attempts entirely")
register("BENCH_CPU_FIRST", default="1", kind="bool", scope="driver",
         help="0 skips the labeled CPU insurance number captured before "
              "the TPU attempts")
register("BENCH_SUFFIX_EXTRA", default="", scope="driver",
         help="extra text appended to bench metric names (sweep "
              "harness labeling)")
register("BENCH_WAVE_N", default="64", kind="int", scope="driver",
         help="wave-equation config grid edge")
register("BENCH_SPECTRA_N", default=None, kind="int", scope="driver",
         help="GW+spectra config grid edge (default: 64 on cpu, 256 on "
              "tpu)")
register("BENCH_MG_N", default=None, kind="int", scope="driver",
         help="multigrid config grid edge (default: 64 on cpu, 512 on "
              "tpu)")
register("BENCH_GW_N", default="256", kind="int", scope="driver",
         help="GW-stepper config grid edge")
register("BENCH_GW_BF16C", default="1", kind="bool", scope="driver",
         help="0 skips the bf16-compute GW config")
register("BENCH_GW_BF16C_N", default="512", kind="int", scope="driver",
         help="bf16-compute GW config grid edge")
register("BENCH_COUPLED_N", default="512", kind="int", scope="driver",
         help="coupled-expansion chunk config grid edge")

# ---------------------------------------------------------------------------
# test-suite knobs (read by tests/conftest.py and tests/common.py, which
# run before the package imports — registered for the doc table)
# ---------------------------------------------------------------------------

register("PYSTELLA_TEST_PLATFORM", default="cpu", scope="test",
         help="pytest suite platform: 'tpu' runs the suite on hardware "
              "(Pallas kernels Mosaic-compiled); default is the virtual "
              "8-device CPU mesh")

# ---------------------------------------------------------------------------
# external variables we read or set (not project-prefixed; documented
# because perf-report fingerprints and the gate's flag-mismatch warning
# depend on them)
# ---------------------------------------------------------------------------

register("XLA_FLAGS", default=None, scope="external",
         help="XLA compiler/runtime flags; scheduler-relevant entries "
              "are fingerprinted into perf reports "
              "(obs.ledger.xla_flag_fingerprint)")
register("LIBTPU_INIT_ARGS", default=None, scope="external",
         help="libtpu init flags; parallel.overlap.ensure_scheduler_flags "
              "appends the async-collective/latency-hiding-scheduler "
              "set before the TPU backend dials")
register("JAX_PLATFORMS", default=None, scope="external",
         help="jax backend selection; tests force 'cpu'")
register("JAX_ENABLE_X64", default=None, scope="external",
         help="jax 64-bit mode; the test suite enables it for "
              "reference-parity f64 tolerances")
