"""Lattice specification for 3-D periodic grids.

TPU-native analog of the implicit grid bookkeeping scattered through the
reference (grid_shape/rank_shape/dx/dk kwargs, e.g. /root/reference/examples/
scalar_preheating.py:74-90 and /root/reference/pystella/decomp.py:306-337).
Here the lattice is a single first-class object; arrays are *unpadded* global
``jax.Array``s sharded over a device mesh (no halo padding leaks into user
shapes, unlike the reference's ``pencil_shape``).
"""

from __future__ import annotations

import dataclasses
import math
from functools import cached_property

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class Lattice:
    """A 3-D periodic lattice.

    :arg grid_shape: number of points per axis, e.g. ``(64, 64, 64)``.
    :arg box_dim: physical side lengths; defaults to unit box per axis.
    :arg dtype: real dtype of fields living on this lattice.
    """

    grid_shape: tuple[int, ...]
    box_dim: tuple[float, ...] = None
    dtype: np.dtype = np.float32

    def __post_init__(self):
        object.__setattr__(self, "grid_shape",
                           tuple(int(n) for n in self.grid_shape))
        if self.box_dim is None:
            object.__setattr__(self, "box_dim",
                               tuple(1.0 for _ in self.grid_shape))
        else:
            object.__setattr__(self, "box_dim",
                               tuple(float(b) for b in self.box_dim))
        if len(self.box_dim) != len(self.grid_shape):
            raise ValueError("box_dim and grid_shape must have equal length")

    @property
    def dim(self) -> int:
        return len(self.grid_shape)

    @cached_property
    def dx(self) -> tuple[float, ...]:
        return tuple(b / n for b, n in zip(self.box_dim, self.grid_shape))

    @cached_property
    def dk(self) -> tuple[float, ...]:
        return tuple(2 * math.pi / b for b in self.box_dim)

    @property
    def grid_size(self) -> int:
        return int(np.prod(self.grid_shape))

    @property
    def volume(self) -> float:
        return float(np.prod(self.box_dim))

    @property
    def dV(self) -> float:
        return float(np.prod(self.dx))

    def coords(self, axis: int) -> jnp.ndarray:
        """Coordinate values along ``axis`` (length ``grid_shape[axis]``)."""
        n = self.grid_shape[axis]
        return jnp.arange(n, dtype=self.dtype) * self.dx[axis]

    def mode_numbers(self, axis: int, real_last: bool = True) -> np.ndarray:
        """Integer FFT mode numbers along ``axis``.

        Nyquist mode is returned *positive*, matching the reference's
        ``pfftfreq`` convention (/root/reference/pystella/fourier/dft.py:327-332).
        If ``real_last`` and ``axis`` is the final axis, returns the r2c
        half-spectrum ``0..n//2``.
        """
        n = self.grid_shape[axis]
        if real_last and axis == self.dim - 1:
            return np.arange(n // 2 + 1)
        freqs = np.fft.fftfreq(n, 1 / n)
        freqs[n // 2] = abs(freqs[n // 2])  # positive Nyquist
        return freqs

    def __repr__(self):
        return (f"Lattice(grid_shape={self.grid_shape}, box_dim={self.box_dim}, "
                f"dtype={np.dtype(self.dtype).name})")
