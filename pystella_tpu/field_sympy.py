"""Sympy interoperation for the symbolic field layer.

TPU-native analog of the reference's sympy bridge
(/root/reference/pystella/field/sympy.py:40-176): the reference round-trips
pymbolic expressions through :mod:`sympy` (retaining ``Field``s via a
``SympyField(sym.Indexed)`` subclass) so users can apply sympy's full
simplification machinery to PDE right-hand sides before code generation.

Here the same service is provided for :class:`pystella_tpu.Field`
expressions: :func:`to_sympy` / :func:`from_sympy` convert losslessly
(fields and indexed fields survive the round trip), and :func:`simplify`
runs an expression through ``sympy.simplify``.

Import is lazy and optional — the module degrades to a clear error if sympy
is unavailable (it is not a hard dependency of the framework).
"""

from __future__ import annotations

import numbers

from pystella_tpu.field import (
    Call, Constant, Field, Indexed, Power, Product,
    Quotient, Shifted, Sum, Var, _wrap,
)

__all__ = ["to_sympy", "from_sympy", "simplify", "SympyField",
           "reset_field_registry"]


def _sympy():
    try:
        import sympy
    except ImportError as err:  # pragma: no cover
        raise ImportError(
            "sympy is required for pystella_tpu.field_sympy") from err
    return sympy


#: maps symbol names created by :func:`to_sympy` back to their Fields so
#: :func:`from_sympy` can restore them. Process-global by necessity (sympy
#: symbols carry only a name); :func:`simplify` scopes its own additions,
#: and :func:`reset_field_registry` clears the map for long-lived processes
#: doing many unrelated conversions.
_FIELD_REGISTRY: dict = {}


def reset_field_registry():
    """Clear the symbol→Field registry used by the sympy round trip.

    After a reset, sympy expressions produced by *earlier* ``to_sympy``
    calls can no longer be converted back with field restoration (their
    symbols fall back to plain :class:`~pystella_tpu.field.Var`)."""
    _FIELD_REGISTRY.clear()


def SympyField(field, index=(), shift=()):
    """A sympy leaf that remembers the originating :class:`Field`.

    The reference subclasses ``sym.Indexed`` (sympy.py:40-56); here a plain
    ``sympy.Symbol`` with a registry entry suffices — sympy's simplification
    treats it atomically, and :func:`from_sympy` restores the Field (and
    its index / lattice shift) from the registry.
    """
    sym = _sympy()
    name = field.name
    if index:
        name += "__idx__" + "_".join(map(str, index))
    if shift and any(shift):
        name += "__sft__" + "_".join(
            f"m{-s}" if s < 0 else str(s) for s in shift)
    s = sym.Symbol(name)
    prior = _FIELD_REGISTRY.get(name)
    if prior is not None and prior[0]._key() != field._key():
        raise ValueError(
            f"sympy round-trip name collision: two distinct Fields both "
            f"map to symbol {name!r} ({prior[0]!r} vs {field!r}); rename "
            f"one of them")
    _FIELD_REGISTRY[name] = (field, tuple(index), tuple(shift))
    return s


# math-function mapping, cf. reference sympy.py:58-96 (which maps e.g.
# sympy.Abs → fabs and sympy.sign → copysign for OpenCL); here both
# directions map by name onto the field layer's Call functions
_TO_SYMPY_FUNCS = {
    "exp": "exp", "log": "log", "sin": "sin", "cos": "cos", "tan": "tan",
    "sinh": "sinh", "cosh": "cosh", "tanh": "tanh", "sqrt": "sqrt",
    "fabs": "Abs", "sign": "sign", "arcsin": "asin", "arccos": "acos",
    "arctan": "atan",
}
_FROM_SYMPY_FUNCS = {v: k for k, v in _TO_SYMPY_FUNCS.items()}


def to_sympy(expr):
    """Convert a field-layer expression to a sympy expression.

    Analog of reference ``pymbolic_to_sympy`` (sympy.py:98-120).
    """
    sym = _sympy()
    expr = _wrap(expr)

    if isinstance(expr, Constant):
        if isinstance(expr.value, numbers.Number):
            return sym.sympify(expr.value)
        raise TypeError("cannot convert array-valued Constant to sympy")
    if isinstance(expr, Indexed):
        return SympyField(expr.field, expr.index)
    if isinstance(expr, Field):
        return SympyField(expr)
    if isinstance(expr, Shifted):
        child = expr.child
        if isinstance(child, Indexed):
            return SympyField(child.field, child.index, expr.shift)
        if isinstance(child, Field):
            return SympyField(child, (), expr.shift)
        raise TypeError(
            "only shifted Field/Indexed leaves convert to sympy")
    if isinstance(expr, Var):
        return sym.Symbol(expr.name)
    if isinstance(expr, Sum):
        return sym.Add(*(to_sympy(c) for c in expr.children))
    if isinstance(expr, Product):
        return sym.Mul(*(to_sympy(c) for c in expr.children))
    if isinstance(expr, Quotient):
        return to_sympy(expr.num) / to_sympy(expr.den)
    if isinstance(expr, Power):
        return sym.Pow(to_sympy(expr.base), to_sympy(expr.exponent))
    if isinstance(expr, Call):
        fn = getattr(sym, _TO_SYMPY_FUNCS[expr.func])
        return fn(*(to_sympy(a) for a in expr.args))
    raise TypeError(f"cannot convert {type(expr)} to sympy")


def from_sympy(s_expr):
    """Convert a sympy expression back to the field layer.

    Analog of reference ``sympy_to_pymbolic`` (sympy.py:122-157). Fields
    created by :func:`to_sympy` are restored exactly (same ``Field``
    instance semantics, including indices).
    """
    sym = _sympy()

    if isinstance(s_expr, sym.Symbol):
        entry = _FIELD_REGISTRY.get(s_expr.name)
        if entry is not None:
            field, index, shift = entry
            out = field[index] if index else field
            if shift and any(shift):
                out = Shifted(out, shift)
            return out
        return Var(s_expr.name)
    if isinstance(s_expr, (sym.Integer, int)):
        return Constant(int(s_expr))
    if isinstance(s_expr, sym.Rational):
        return Quotient(Constant(int(s_expr.p)), Constant(int(s_expr.q)))
    if isinstance(s_expr, (sym.Float, float)):
        return Constant(float(s_expr))
    if s_expr is sym.pi:
        import math
        return Constant(math.pi)
    if isinstance(s_expr, sym.Add):
        return Sum.make(*(from_sympy(a) for a in s_expr.args))
    if isinstance(s_expr, sym.Mul):
        return Product.make(*(from_sympy(a) for a in s_expr.args))
    if isinstance(s_expr, sym.Pow):
        return Power(from_sympy(s_expr.base), from_sympy(s_expr.exp))
    if isinstance(s_expr, sym.Function):
        name = type(s_expr).__name__
        if name in _FROM_SYMPY_FUNCS:
            args = tuple(from_sympy(a) for a in s_expr.args)
            return Call(_FROM_SYMPY_FUNCS[name], args)
        raise ValueError(f"no mapping for sympy function {name}")
    if s_expr.is_number:
        return Constant(float(s_expr))
    raise TypeError(f"cannot convert {type(s_expr)} from sympy")


def simplify(expr, sympify=None):
    """Simplify an expression via sympy (reference sympy.py:160-176).

    :arg sympify: optional callable applied to the sympy form (defaults to
        ``sympy.simplify``); pass e.g. ``sympy.expand`` or
        ``sympy.factor`` for a different canonicalization.
    """
    sym = _sympy()
    fn = sympify if sympify is not None else sym.simplify
    # scope this call's registry additions: the round trip completes inside
    # the call, so its temporary symbol→Field entries need not outlive it
    before = set(_FIELD_REGISTRY)
    try:
        return from_sympy(fn(to_sympy(expr)))
    finally:
        for name in set(_FIELD_REGISTRY) - before:
            del _FIELD_REGISTRY[name]
