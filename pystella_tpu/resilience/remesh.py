"""The re-mesh library: degraded-mesh continuation after device loss.

PR 8's :class:`~pystella_tpu.resilience.Supervisor` made device loss
survivable but stopped at the edge of the real problem: its ``remesh``
hook handed the caller the unsolved job of rebuilding a valid mesh,
resharding state, and reconstructing a step function from the
survivors. This module is that job, as a library — the
decomposition-mapping decision the MPI-X/Mapple line of work solves
statically (PAPERS.md arxiv 2312.13094 / 2507.17087), re-solved at
runtime against whatever hardware is still alive:

1. **Solve** — :func:`feasible_proc_shapes` enumerates every mesh over
   the surviving device set and applies the feasibility rules the
   kernel tiers actually enforce (grid divisibility per sharded axis,
   halo width within the local block, pencil-FFT transpose
   divisibility when spectra are in play); the best feasible candidate
   wins (most devices, then least halo surface, then an unsharded z —
   the production layout preference), and every rejected candidate is
   recorded WITH its reason so the ``remesh_plan`` event is an
   auditable decision, not an oracle. Ensemble decompositions
   (:func:`~pystella_tpu.ensemble_mesh`) instead shrink the member
   axis: the per-member lattice sharding is kept and the ensemble
   device extent drops to the largest survivor-fitting divisor of the
   member count (E members over D' devices repack as E/D' per slice).
2. **Reshard** — the last durable checkpoint is restored straight onto
   the degraded mesh via :meth:`pystella_tpu.Checkpointer.restore`'s
   ``mesh=`` template path: orbax reads each device's shard directly
   from disk, so the full state is NEVER materialized on one device
   (the failure mode that would OOM exactly when the fleet is already
   on fire).
3. **Rebuild** — the step function is reconstructed through the same
   constructors that built the original program: the planner carries a
   declarative ``build_step(decomp) -> step_fn`` factory (a closure
   over :class:`~pystella_tpu.Stepper` / ``FusedScalarStepper`` /
   :class:`~pystella_tpu.ensemble.EnsembleStepper` construction), so
   the generic, fused, batched, and step-with-health tiers all come
   back on the new mesh and sentinel/monitor/forensics keep working
   unchanged.

Wired in as the Supervisor's **default** remesh policy (pass
``planner=``; the legacy ``remesh=`` hook becomes an override), a
supervised run that loses devices mid-flight completes on the degraded
mesh with no caller-provided recovery code::

    planner = RemeshPlanner(decomp, grid_shape, build_step)
    sup = Supervisor(step_fn, ck, nsteps, monitor=mon, planner=planner)
    report = sup.run(state)       # 8 devices -> fault -> 4 devices

Survivor resolution, in priority order: an explicit ``devices_fn``;
the fault injector's lost-device registry
(:meth:`~pystella_tpu.resilience.faults.FaultInjector.lost_devices`
— the deterministic tier-1 drills); the post-re-dial device probe
(:func:`pystella_tpu.parallel.multihost.live_devices` — real
hardware, where a re-dialed smaller cluster simply reports fewer
devices).

Every invocation emits a ``remesh_plan`` run event naming old -> new
mesh, survivors, and the rejected candidates; the ledger folds it into
the ``resilience`` report section's ``degraded`` block and the gate
refuses reports that claim full-mesh throughput from a degraded run
(``doc/resilience.md`` "Re-mesh and degraded continuation").
"""

from __future__ import annotations

import numpy as np

from pystella_tpu.obs import events as _events

__all__ = ["RemeshPlan", "RemeshPlanner", "feasible_proc_shapes",
           "proc_shape_candidates"]


def proc_shape_candidates(ndev):
    """Every ordered 3-axis factorization ``(px, py, pz)`` with
    ``px * py * pz == ndev``, deterministically ordered."""
    ndev = int(ndev)
    out = []
    for px in range(1, ndev + 1):
        if ndev % px:
            continue
        rest = ndev // px
        for py in range(1, rest + 1):
            if rest % py:
                continue
            out.append((px, py, rest // py))
    return out


def _halo_surface(grid_shape, proc_shape, halo):
    """Per-step exchanged halo sites of a candidate mesh — the
    surface-to-volume score the solver minimizes (two ``halo[d]``-wide
    slabs per sharded axis; unsharded axes wrap locally for free)."""
    total = 0
    for d in range(3):
        if proc_shape[d] > 1 and halo[d] > 0:
            slab = 2 * halo[d]
            for a in range(3):
                if a != d:
                    slab *= grid_shape[a] // proc_shape[a]
            total += slab * int(np.prod(proc_shape))
    return total


def feasible_proc_shapes(grid_shape, ndev, halo=(0, 0, 0),
                         pencil=False):
    """``(feasible, rejected)`` for every 3-axis mesh over exactly
    ``ndev`` devices: ``feasible`` is best-first (least halo surface,
    then unsharded-z preferred, then lexicographic), ``rejected`` is a
    list of ``{"proc_shape", "reason"}`` records naming why each
    infeasible candidate was turned down — the audit trail the
    ``remesh_plan`` event carries.

    Rules (exactly what the kernel tiers enforce at construction):

    - every sharded axis must divide its grid extent
      (:meth:`~pystella_tpu.DomainDecomposition.rank_shape`);
    - the halo width must fit within the local block
      (``halo[d] <= grid[d] // p[d]`` — the ``pad_with_halos`` guard);
    - with ``pencil=True``, the grid's x and y extents must divide the
      TOTAL device count (the pencil-FFT transpose stages redistribute
      those axes over all devices —
      :func:`pystella_tpu.fourier.pencil.pencil_feasible`).
    """
    grid_shape = tuple(int(n) for n in grid_shape)
    if np.isscalar(halo):
        halo = (halo,) * 3
    halo = tuple(int(h) for h in halo)
    feasible, rejected = [], []
    for cand in proc_shape_candidates(ndev):
        reason = None
        for d in range(3):
            if grid_shape[d] % cand[d]:
                reason = (f"grid axis {d} ({grid_shape[d]}) not "
                          f"divisible by mesh axis {cand[d]}")
                break
            # the pad_with_halos guard holds for unsharded axes too
            # (the local periodic wrap slices halo[d] rows)
            if halo[d] > grid_shape[d] // cand[d]:
                reason = (f"halo {halo[d]} exceeds the local block "
                          f"{grid_shape[d] // cand[d]} along axis {d}")
                break
        if reason is None and pencil and ndev > 1:
            for d, label in ((0, "x"), (1, "y")):
                if grid_shape[d] % ndev:
                    reason = (f"pencil FFT: grid {label}="
                              f"{grid_shape[d]} not divisible by the "
                              f"total device count {ndev}")
                    break
        if reason is None:
            feasible.append(cand)
        else:
            rejected.append({"proc_shape": list(cand), "reason": reason})
    feasible.sort(key=lambda p: (_halo_surface(grid_shape, p, halo),
                                 p[2] > 1, p))
    return feasible, rejected


class RemeshPlan:
    """One solved degraded-mesh decision (JSON-safe via
    :meth:`describe`). ``changed`` is False when every old device
    survived — a transport blip, not a loss — in which case the
    supervisor keeps the original program."""

    def __init__(self, *, old_proc_shape, new_proc_shape, devices,
                 survivors, lost, rejected, changed,
                 old_ensemble=None, new_ensemble=None, members=None,
                 pencil=False):
        self.old_proc_shape = tuple(old_proc_shape)
        self.new_proc_shape = (tuple(new_proc_shape)
                               if new_proc_shape is not None else None)
        #: the survivor subset the new mesh actually uses (ordered)
        self.devices = list(devices)
        self.survivors = list(survivors)
        self.lost = list(lost)
        self.rejected = list(rejected)
        self.changed = bool(changed)
        self.old_ensemble = old_ensemble
        self.new_ensemble = new_ensemble
        self.members = members
        self.pencil = bool(pencil)

    @property
    def feasible(self):
        return self.new_proc_shape is not None

    @staticmethod
    def _ids(devices):
        return [int(getattr(d, "id", d)) for d in devices]

    def describe(self):
        """The ``remesh_plan`` event payload: old -> new mesh,
        survivors, and the rejected candidates."""
        out = {
            "old_proc_shape": list(self.old_proc_shape),
            "new_proc_shape": (list(self.new_proc_shape)
                               if self.new_proc_shape else None),
            "devices": self._ids(self.devices),
            "survivors": self._ids(self.survivors),
            "lost": self._ids(self.lost),
            "n_rejected": len(self.rejected),
            "rejected": self.rejected[:8],
            "changed": self.changed,
            "feasible": self.feasible,
        }
        if self.old_ensemble is not None:
            out["ensemble"] = {"old": self.old_ensemble,
                               "new": self.new_ensemble,
                               "members": self.members}
        if self.pencil:
            out["pencil"] = True
        return out


class RemeshPlanner:
    """Solve + reshard + rebuild after device loss (module docstring).

    :arg decomp: the CURRENT
        :class:`~pystella_tpu.DomainDecomposition` (spatial or
        ensemble); carries the mesh, halo widths, and axis names the
        degraded decomposition inherits.
    :arg grid_shape: the 3-D lattice extents feasibility is solved
        against (one member's lattice for an ensemble decomposition).
    :arg build_step: ``build_step(new_decomp) -> step_fn`` — the
        declarative program factory, a closure over the SAME
        constructors that built the original program (stepper, fused
        kernels, :class:`~pystella_tpu.ensemble.EnsembleStepper`, the
        step-with-health tier...); called once per realized plan. May
        also return a dict (any subset of ``step_fn`` / ``restore_fn``
        / ``monitor`` / ``note``) for callers that rebuild more than
        the step callable.
    :arg halo: halo widths for the feasibility rule (default: the
        decomposition's ``halo_shape``).
    :arg needs_pencil_fft: require pencil-FFT transpose divisibility of
        every candidate (set when the run computes spectra through the
        pencil tier — a degraded mesh that breaks the transform is not
        a continuation).
    :arg members: ensemble member count (enables the member-axis
        shrink rule: the new ensemble extent must divide it so E
        members repack as E/D' per slice).
    :arg devices_fn: optional zero-arg callable returning the surviving
        devices (overrides the injector/probe resolution).
    :arg label: tag carried on emitted events.
    """

    def __init__(self, decomp, grid_shape, build_step, *, halo=None,
                 needs_pencil_fft=False, members=None, devices_fn=None,
                 label=""):
        self.decomp = decomp
        self.grid_shape = tuple(int(n) for n in grid_shape)
        self.build_step = build_step
        if halo is None:
            halo = getattr(decomp, "halo_shape", (0, 0, 0))
        if np.isscalar(halo):
            halo = (halo,) * 3
        self.halo = tuple(int(h) for h in halo)
        self.needs_pencil_fft = bool(needs_pencil_fft)
        self.members = None if members is None else int(members)
        self.devices_fn = devices_fn
        self.label = label
        #: the last realized plan (None before any remesh)
        self.last_plan = None

    # -- survivor resolution ------------------------------------------------

    def mesh_devices(self):
        """The current mesh's devices, flat, in mesh order."""
        return list(self.decomp.mesh.devices.flat)

    def survivors(self, faults=None):
        """The surviving device list: ``devices_fn`` > the injector's
        lost-device registry (deterministic drills) > the post-re-dial
        probe (:func:`~pystella_tpu.parallel.multihost.live_devices`),
        intersected with the old mesh's device set."""
        old = self.mesh_devices()
        if self.devices_fn is not None:
            return list(self.devices_fn())
        lost = set()
        if faults is not None:
            getter = getattr(faults, "lost_devices", None)
            if getter is not None:
                lost = set(getter())
        if lost:
            return [d for d in old if d not in lost]
        from pystella_tpu.parallel import multihost
        live = set(multihost.live_devices())
        return [d for d in old if d in live]

    # -- the solver ----------------------------------------------------------

    def plan(self, survivors):
        """Solve for the best feasible degraded mesh over
        ``survivors``; returns a :class:`RemeshPlan` (``feasible``
        False when no candidate works at any usable device count)."""
        old = self.mesh_devices()
        survivors = list(survivors)
        surv_set = set(survivors)
        lost = [d for d in old if d not in surv_set]
        if self.decomp.ensemble_axis is not None:
            return self._plan_ensemble(old, survivors, lost)
        old_shape = tuple(self.decomp.proc_shape)
        if not lost:
            return RemeshPlan(
                old_proc_shape=old_shape, new_proc_shape=old_shape,
                devices=old, survivors=survivors, lost=[], rejected=[],
                changed=False, pencil=self.needs_pencil_fft)
        rejected = []
        for ndev in range(len(survivors), 0, -1):
            feasible, rej = feasible_proc_shapes(
                self.grid_shape, ndev, halo=self.halo,
                pencil=self.needs_pencil_fft)
            rejected.extend(rej)
            if feasible:
                best = feasible[0]
                return RemeshPlan(
                    old_proc_shape=old_shape, new_proc_shape=best,
                    devices=survivors[:ndev], survivors=survivors,
                    lost=lost, rejected=rejected, changed=True,
                    pencil=self.needs_pencil_fft)
        return RemeshPlan(
            old_proc_shape=old_shape, new_proc_shape=None,
            devices=[], survivors=survivors, lost=lost,
            rejected=rejected, changed=True,
            pencil=self.needs_pencil_fft)

    def _plan_ensemble(self, old, survivors, lost):
        """The member-axis shrink rule: spatial sharding per member is
        kept; the ensemble extent drops to the largest
        survivor-fitting value that divides the member count."""
        spatial_shape = tuple(self.decomp.proc_shape)
        spatial = int(np.prod(spatial_shape))
        old_ens = self.decomp.ensemble_devices
        if not lost:
            return RemeshPlan(
                old_proc_shape=spatial_shape,
                new_proc_shape=spatial_shape, devices=old,
                survivors=survivors, lost=[], rejected=[],
                changed=False, old_ensemble=old_ens,
                new_ensemble=old_ens, members=self.members)
        rejected = []
        best = None
        for d in range(len(survivors) // spatial, 0, -1):
            if self.members is not None and self.members % d:
                rejected.append({
                    "proc_shape": [d, *spatial_shape],
                    "reason": f"ensemble extent {d} does not divide "
                              f"the member count {self.members}"})
                continue
            best = d
            break
        if best is None:
            return RemeshPlan(
                old_proc_shape=spatial_shape, new_proc_shape=None,
                devices=[], survivors=survivors, lost=lost,
                rejected=rejected, changed=True,
                old_ensemble=old_ens, new_ensemble=None,
                members=self.members)
        return RemeshPlan(
            old_proc_shape=spatial_shape, new_proc_shape=spatial_shape,
            devices=survivors[:best * spatial], survivors=survivors,
            lost=lost, rejected=rejected, changed=True,
            old_ensemble=old_ens, new_ensemble=best,
            members=self.members)

    # -- realization ---------------------------------------------------------

    def make_decomp(self, plan):
        """The degraded :class:`~pystella_tpu.DomainDecomposition` a
        feasible plan names (same halo widths and axis names, over the
        survivor subset)."""
        from pystella_tpu.parallel.decomp import (
            DomainDecomposition, ensemble_mesh)
        if not plan.feasible:
            raise ValueError(
                "no feasible degraded mesh: "
                + "; ".join(r["reason"] for r in plan.rejected[:4]))
        if self.decomp.ensemble_axis is not None:
            mesh = ensemble_mesh(
                plan.new_proc_shape,
                ensemble_devices=plan.new_ensemble,
                axis_names=self.decomp.axis_names,
                ensemble_axis=self.decomp.ensemble_axis,
                devices=plan.devices)
            return DomainDecomposition(
                mesh=mesh, halo_shape=self.decomp.halo_shape,
                ensemble_axis=self.decomp.ensemble_axis)
        return self.decomp.with_devices(plan.devices,
                                        plan.new_proc_shape)

    def realize(self, plan):
        """Build the swap for a feasible plan: the degraded decomp, the
        rebuilt step function, and the placement half of the resume.
        Returns the supervisor swap dict (``step_fn`` / ``restore_fn``
        / ``decomp`` / ``plan`` / ``note``)."""
        new_decomp = self.make_decomp(plan)
        built = self.build_step(new_decomp)
        swap = {}
        if isinstance(built, dict):
            swap.update(built)
        else:
            swap["step_fn"] = built
        swap.setdefault(
            "restore_fn",
            new_decomp.shard_members
            if new_decomp.ensemble_axis is not None else new_decomp.shard)
        swap.setdefault("decomp", new_decomp)
        swap["plan"] = plan
        ens = (f", ensemble {plan.old_ensemble}->{plan.new_ensemble}"
               if plan.old_ensemble is not None else "")
        swap.setdefault(
            "note",
            f"re-meshed {list(plan.old_proc_shape)} -> "
            f"{list(plan.new_proc_shape)}{ens} over "
            f"{len(plan.devices)} of {len(plan.devices) + len(plan.lost)}"
            " devices")
        self.last_plan = plan
        self.decomp = new_decomp
        return swap

    # -- the supervisor's default policy -------------------------------------

    def __call__(self, error, attempt, *, faults=None, step=None):
        """One remesh decision during device-loss recovery (what the
        supervisor invokes when no ``remesh`` hook overrides it).
        Emits ``remesh_plan``; returns the swap dict, or ``None`` when
        every old device survived (transport blip — keep the program).
        An infeasible plan raises ``RuntimeError`` (deterministic:
        counted against the recovery budget, never retried into)."""
        survivors = self.survivors(faults=faults)
        plan = self.plan(survivors)
        _events.emit("remesh_plan", step=step, label=self.label,
                     attempt=int(attempt),
                     error=f"{type(error).__name__}: {error}",
                     **plan.describe())
        if not plan.changed:
            return None
        if not plan.feasible:
            raise RuntimeError(
                "remesh infeasible: no degraded mesh serves grid "
                f"{self.grid_shape} on {len(survivors)} surviving "
                "device(s): "
                + "; ".join(r["reason"] for r in plan.rejected[:4]))
        return self.realize(plan)
