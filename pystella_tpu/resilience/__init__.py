"""Elastic runtime: retry/backoff, fault injection, and the supervisor.

The production environment for this stack loses device links mid-run
(rounds 3 and 5: 18 dial attempts over 9.5 h, all UNAVAILABLE). This
package is the recovery layer that treats that as weather, not
catastrophe:

- :mod:`pystella_tpu.resilience.retry` — budget-aware jittered
  exponential backoff with transient-vs-deterministic triage
  (:func:`classify_exception`), promoted out of ``bench.py``'s
  orchestrator, which now consumes it. Stdlib-only and loadable by
  file, like ``config.py``.
- :mod:`pystella_tpu.resilience.faults` — a deterministic
  fault-injection harness (:class:`FaultInjector`: raise-at-step /
  simulated device loss / NaN corruption / SIGTERM preemption) so
  every recovery path is testable on the CPU mesh in tier-1.
- :mod:`pystella_tpu.resilience.supervisor` — :class:`Supervisor`,
  the driver wrapper: health-checked async durable checkpoints off the
  step path, fault detection, re-dial/re-mesh, restore from the
  durable last-good checkpoint, bounded replay, clean SIGTERM
  preemption, and the incident telemetry
  (``fault_detected``/``recovery_attempt``/``run_resumed``/
  ``run_degraded``) the ledger's ``resilience`` report section and the
  gate's degraded-annotation verdicts are built from.
- :mod:`pystella_tpu.resilience.remesh` — :class:`RemeshPlanner`, the
  supervisor's DEFAULT remesh policy: solve the best feasible degraded
  mesh over the surviving devices (halo/grid/pencil-FFT feasibility,
  ensemble member-axis shrink), reshard the last durable checkpoint
  straight onto it (``Checkpointer.restore(mesh=...)`` — never
  materialized on one device), rebuild the step function through the
  original constructors, and emit the auditable ``remesh_plan``
  record. Device loss becomes a measured, gated degradation instead of
  an abort.

See ``doc/resilience.md`` for the supervisor contract, the fault
taxonomy, replay semantics, and degraded-mesh continuation.
"""

from pystella_tpu.resilience.retry import (
    Retrier, RetryPolicy, classify_exception, retry_call)
from pystella_tpu.resilience.faults import (
    DeviceSubsetFault, Fault, FaultInjector, NaNFault, RaiseFault,
    SigtermFault, device_loss_error)
from pystella_tpu.resilience.remesh import (
    RemeshPlan, RemeshPlanner, feasible_proc_shapes,
    proc_shape_candidates)
from pystella_tpu.resilience.supervisor import RecoveryFailed, Supervisor

__all__ = [
    "Retrier", "RetryPolicy", "classify_exception", "retry_call",
    "DeviceSubsetFault", "Fault", "FaultInjector", "NaNFault",
    "RaiseFault", "SigtermFault", "device_loss_error",
    "RemeshPlan", "RemeshPlanner", "feasible_proc_shapes",
    "proc_shape_candidates",
    "RecoveryFailed", "Supervisor",
]
