"""Budget-aware retry/backoff with transient-vs-deterministic triage.

Promoted out of ``bench.py``'s orchestrator, where the policy grew up
the hard way: rounds 3 and 5 lost their TPU windows to transport
outages (18 dial attempts over 9.5 h, all UNAVAILABLE), and the loop
that survived them encodes three rules this module turns into a tested
library:

- **deterministic failures must not be retried** — a payload that
  dialed fine and then failed every config (rc=3), a ``ValueError``, an
  ``INVALID_ARGUMENT`` from the runtime: re-running it burns the budget
  to fail identically;
- **fast failures are deterministic in disguise** — an "attempt" that
  dies in seconds never reached the slow transport; a tight crash loop
  (plugin misconfig, import error) must trip a consecutive-fast-failure
  limit instead of eating the whole window;
- **slow transient failures are worth retrying for as long as the
  budget lasts** — a 25-minute dial timeout on a wedged tunnel is the
  expected production environment, not an anomaly.

Pieces:

- :func:`classify_exception` — ``"transient"`` or ``"deterministic"``
  for an exception, by type and by the status markers transport errors
  carry (``UNAVAILABLE``, ``DEADLINE_EXCEEDED``, connection resets,
  ...). Unknown errors classify **deterministic**: retrying an
  unrecognized failure mode is how budgets disappear.
- :class:`RetryPolicy` / :class:`Retrier` — jittered exponential
  backoff under attempt/wall budgets, with the fast-failure counter.
  The :class:`Retrier` is outcome-driven (``note_failure`` returns a
  retry/stop decision) so callers that deal in subprocess return codes
  (the bench orchestrator) and callers that deal in exceptions (the
  supervisor) share one policy engine.
- :func:`retry_call` — the exception-driven wrapper:
  ``retry_call(dial, policy=...)`` retries transients with backoff and
  re-raises deterministics immediately.

This module is **stdlib-only and free of package imports** so a
jax-free supervisor process (``bench.py``'s orchestrator) can load it
by file, exactly like ``pystella_tpu/config.py`` and ``obs/events.py``.
Event emission is therefore dependency-injected: pass ``emit=`` (an
``obs.events.emit``-shaped callable) to get ``retry_wait`` /
``retry_stop`` telemetry; the default is silent.
"""

from __future__ import annotations

import dataclasses
import random
import time

__all__ = ["RetryPolicy", "Retrier", "classify_exception", "retry_call",
           "TRANSIENT_MARKERS", "DETERMINISTIC_MARKERS"]


#: substrings (upper-cased comparison) that mark an error message as a
#: transport/availability failure worth retrying. The gRPC/absl status
#: names cover XlaRuntimeError from a dying device link; the rest are
#: socket-level spellings observed in the round-3/round-5 outage logs.
TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "DEADLINE EXCEEDED", "ABORTED",
    "CANCELLED", "CONNECTION RESET", "CONNECTION REFUSED",
    "CONNECTION CLOSED", "SOCKET CLOSED", "BROKEN PIPE",
    "FAILED TO CONNECT", "UNREACHABLE", "TRANSPORT", "PREEMPT",
    "DEVICE OR RESOURCE BUSY", "TEMPORARILY", "TIMED OUT", "TIMEOUT",
    "HEARTBEAT", "DATA_LOSS", "DATA LOSS",
)

#: markers that force the deterministic verdict even when a transient
#: marker also matches (e.g. "timeout" appearing inside an argument
#: dump of an INVALID_ARGUMENT error)
DETERMINISTIC_MARKERS = (
    "INVALID_ARGUMENT", "INVALID ARGUMENT", "NOT_FOUND", "NOT FOUND",
    "UNIMPLEMENTED", "FAILED_PRECONDITION", "FAILED PRECONDITION",
    "PERMISSION_DENIED", "OUT_OF_RANGE", "ALREADY_EXISTS",
)

#: exception type names that are transient by construction (name-based
#: so jax/grpc need not be importable here)
_TRANSIENT_TYPE_NAMES = frozenset({
    "TimeoutError", "ConnectionError", "ConnectionResetError",
    "ConnectionRefusedError", "ConnectionAbortedError", "BrokenPipeError",
    "InterruptedError", "RpcError", "AioRpcError",
})

#: exception type names whose MESSAGE decides (runtime errors carry the
#: status string; a bare RuntimeError with no marker is deterministic)
_MESSAGE_TYPE_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "RuntimeError", "OSError",
    "IOError", "InternalError", "FatalError", "DeviceLossError",
})

#: exception types that are always deterministic: program bugs, not
#: environment weather
_DETERMINISTIC_TYPES = (ValueError, TypeError, KeyError, IndexError,
                        AttributeError, AssertionError, NotImplementedError,
                        ArithmeticError, ImportError, SyntaxError)


def classify_exception(exc):
    """``"transient"`` (worth retrying) or ``"deterministic"`` (must not
    be retried) for an exception instance.

    Classification order: hard-deterministic python types first (a
    ``ValueError`` stays deterministic whatever its message), then
    deterministic status markers (``INVALID_ARGUMENT`` beats an
    incidental ``timeout`` in the same message), then transient types
    (``TimeoutError``, connection errors), then transient markers in
    the message of runtime/OS error types. Anything unrecognized is
    **deterministic** — the round-5 lesson is that optimistic retries
    of unknown failures eat whole hardware windows.
    """
    if isinstance(exc, _DETERMINISTIC_TYPES):
        return "deterministic"
    names = {t.__name__ for t in type(exc).__mro__}
    msg = str(exc).upper()
    if any(m in msg for m in DETERMINISTIC_MARKERS):
        return "deterministic"
    if names & _TRANSIENT_TYPE_NAMES:
        return "transient"
    if names & _MESSAGE_TYPE_NAMES or isinstance(exc, Exception):
        if any(m in msg for m in TRANSIENT_MARKERS):
            return "transient"
    return "deterministic"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff/budget parameters for a :class:`Retrier`.

    :arg base_s: first backoff in seconds.
    :arg factor: exponential growth per failure (1.0 = constant).
    :arg max_s: backoff ceiling.
    :arg jitter: symmetric jitter as a fraction of the computed backoff
        (0.1 -> +-10%); decorrelates a fleet of retriers hammering one
        coordinator.
    :arg max_attempts: attempt ceiling (``None`` = unbounded; the wall
        budget still applies).
    :arg budget_s: total wall budget across attempts and backoffs
        (``None`` = unbounded). The retrier stops when the NEXT backoff
        would land beyond it — it never sleeps into a dead budget.
    :arg fast_failure_s: attempts failing faster than this count as
        *fast* (they never reached the slow transport).
    :arg max_fast_failures: consecutive fast failures allowed before
        the retrier stops (a tight crash loop is deterministic in
        disguise); a slow failure resets the streak.
    """

    base_s: float = 1.0
    factor: float = 2.0
    max_s: float = 60.0
    jitter: float = 0.1
    max_attempts: int | None = None
    budget_s: float | None = None
    fast_failure_s: float | None = None
    max_fast_failures: int | None = 3


class Retrier:
    """Outcome-driven retry engine: callers report each failure with
    :meth:`note_failure` and get a ``("retry" | "stop", reason)``
    decision back; :meth:`wait` sleeps the jittered backoff.

    :arg policy: a :class:`RetryPolicy`.
    :arg clock: monotonic-seconds callable (injectable for tests).
    :arg sleep: sleep callable (injectable for tests).
    :arg rng: ``random.Random`` for jitter (seedable for tests).
    :arg emit: optional ``obs.events.emit``-shaped callable receiving
        ``retry_wait`` / ``retry_stop`` events.
    :arg label: caller tag carried on emitted events.
    """

    def __init__(self, policy=None, clock=time.monotonic,
                 sleep=time.sleep, rng=None, emit=None, label=""):
        self.policy = policy or RetryPolicy()
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._emit = emit
        self.label = label
        self.failures = 0
        self.consecutive_fast = 0
        self.started = clock()
        #: reason the retrier stopped ("" while it is still willing)
        self.stop_reason = ""

    # -- derived state -----------------------------------------------------

    def elapsed_s(self):
        return self._clock() - self.started

    def backoff_s(self):
        """The next backoff (jittered, clipped): grows from ``base_s``
        by ``factor`` per recorded failure."""
        p = self.policy
        raw = p.base_s * (p.factor ** max(0, self.failures - 1))
        raw = min(raw, p.max_s)
        if p.jitter:
            raw *= 1.0 + p.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, raw)

    # -- the decision ------------------------------------------------------

    def note_failure(self, kind="transient", duration_s=None, error=None):
        """Record one failed attempt; returns ``(decision, reason)``
        where ``decision`` is ``"retry"`` or ``"stop"``.

        :arg kind: ``"transient"`` or ``"deterministic"`` (use
            :func:`classify_exception`, an rc mapping, ...).
        :arg duration_s: how long the attempt ran (feeds the
            fast-failure streak).
        :arg error: the failure itself, for telemetry only.
        """
        p = self.policy
        self.failures += 1
        if str(kind) != "transient":
            return self._stop(f"{kind} failure: not retryable "
                              f"({_err_str(error)})")
        if duration_s is not None and p.fast_failure_s is not None:
            if duration_s < p.fast_failure_s:
                self.consecutive_fast += 1
                if (p.max_fast_failures is not None
                        and self.consecutive_fast >= p.max_fast_failures):
                    return self._stop(
                        f"{self.consecutive_fast} consecutive fast "
                        f"failures (< {p.fast_failure_s:.0f}s each) — "
                        "deterministic in disguise")
            else:
                self.consecutive_fast = 0
        if p.max_attempts is not None and self.failures >= p.max_attempts:
            return self._stop(f"attempt budget exhausted "
                              f"({self.failures}/{p.max_attempts})")
        if p.budget_s is not None \
                and self.elapsed_s() + self.backoff_s() > p.budget_s:
            return self._stop(
                f"wall budget exhausted ({self.elapsed_s():.1f}s of "
                f"{p.budget_s:.1f}s spent after {self.failures} "
                "failure(s))")
        return "retry", ""

    def _stop(self, reason):
        self.stop_reason = reason
        if self._emit is not None:
            try:
                self._emit("retry_stop", label=self.label, reason=reason,
                           failures=self.failures)
            except Exception:
                pass
        return "stop", reason

    def wait(self):
        """Sleep the current jittered backoff; returns the seconds
        slept. Emits a ``retry_wait`` event when wired."""
        delay = self.backoff_s()
        if self._emit is not None:
            try:
                self._emit("retry_wait", label=self.label,
                           backoff_s=round(delay, 3),
                           failures=self.failures)
            except Exception:
                pass
        if delay > 0:
            self._sleep(delay)
        return delay


def retry_call(fn, args=(), kwargs=None, policy=None,
               classify=classify_exception, clock=time.monotonic,
               sleep=time.sleep, rng=None, emit=None, label="",
               on_failure=None):
    """Call ``fn(*args, **kwargs)``, retrying transient failures with
    backoff under the policy's budgets.

    Deterministic failures (per ``classify``) re-raise immediately —
    "deterministic failure => no retry" is the whole point. When the
    budget runs out the LAST exception re-raises unchanged, so callers
    see the real failure, not a wrapper. ``on_failure(exc, retrier)``
    (optional) observes each failed attempt before the decision.
    """
    r = Retrier(policy, clock=clock, sleep=sleep, rng=rng, emit=emit,
                label=label)
    while True:
        t0 = clock()
        try:
            return fn(*args, **(kwargs or {}))
        except BaseException as e:  # noqa: B036 — re-raised below
            if on_failure is not None:
                try:
                    on_failure(e, r)
                except Exception:
                    pass
            decision, _ = r.note_failure(kind=classify(e),
                                         duration_s=clock() - t0, error=e)
            if decision == "stop":
                raise
            r.wait()


def _err_str(error):
    if error is None:
        return "no detail"
    if isinstance(error, BaseException):
        return f"{type(error).__name__}: {error}"
    return str(error)
