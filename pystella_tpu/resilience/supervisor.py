"""The elastic run supervisor: survive faults, resume from last good.

``Supervisor`` wraps any per-step driver loop (a
:class:`~pystella_tpu.Stepper`, a fused chunk dispatch, an ensemble
driver tick — anything shaped ``step_fn(state, step) -> state``) with
the recovery machinery the ROADMAP's pod-scale item calls for:

- **health-checked periodic checkpoints, async and durable** — every
  ``checkpoint_every`` steps the monitor is flushed and synchronously
  checked (a diverged state is never checkpointed), then the
  :class:`~pystella_tpu.Checkpointer` *schedules* the write and the
  loop moves on; the durability barrier for each save runs one interval
  later, off the step path, and only then does ``last_good`` advance
  (:meth:`Checkpointer.finalize`).
- **fault detection and triage** — a sentinel trip
  (:class:`~pystella_tpu.SimulationDiverged`) is a *numerics* fault;
  any other exception is triaged by
  :func:`~pystella_tpu.resilience.retry.classify_exception`:
  transient (``UNAVAILABLE``, transport drops, device loss) enters
  recovery, deterministic re-raises immediately — replaying a program
  bug burns the budget to fail identically.
- **recovery** — under a jittered-backoff
  :class:`~pystella_tpu.resilience.retry.Retrier`: re-dial the
  multi-controller runtime (:func:`pystella_tpu.parallel.multihost.
  reinit` — no longer a one-way latch), re-mesh to the surviving
  devices — by default through the
  :class:`~pystella_tpu.resilience.remesh.RemeshPlanner` given as
  ``planner=`` (solve a feasible degraded mesh, rebuild the step
  function through the original constructors, emit ``remesh_plan`` +
  ``run_degraded``), with the legacy ``remesh`` hook as an override —
  finalize pending checkpoint writes, restore from the durable
  last-good checkpoint (walking back past a torn newest one; a
  re-meshed run restores STRAIGHT onto the degraded mesh through
  :meth:`Checkpointer.restore`'s ``mesh=`` template path, never
  materializing the state on one device), and **replay at most one
  checkpoint interval** of steps. A swap also refreshes the monitor's
  decomposition-derived state (:meth:`HealthMonitor.reset`) so
  sentinel field specs and checkpoint sharding track the new mesh.
- **preemption** — SIGTERM sets a flag; at the next step boundary the
  supervisor drains the monitor, takes a synchronous durable
  checkpoint, emits ``run_preempted``, and returns cleanly so a
  restarted process resumes exactly there (``run(resume="auto")``).

Every incident is telemetry: ``fault_detected`` -> ``recovery_attempt``
(xN) -> ``run_resumed`` (with measured MTTR and replayed-step count),
plus ``run_degraded`` / ``run_preempted`` / ``supervisor_done``. The
perf ledger folds these into the report's ``resilience`` section and
the gate annotates — rather than refuses — evidence measured across a
recorded incident (``doc/resilience.md``).

Deterministic testing: pass a
:class:`~pystella_tpu.resilience.faults.FaultInjector` and every one of
these paths runs on the 8-device CPU mesh in tier-1.
"""

from __future__ import annotations

import signal
import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs.scope import trace_scope
from pystella_tpu.obs.sentinel import SimulationDiverged
from pystella_tpu.resilience.retry import (
    Retrier, RetryPolicy, classify_exception)

__all__ = ["Supervisor", "RecoveryFailed"]


class RecoveryFailed(RuntimeError):
    """Raised when recovery itself gives up: the per-incident retry
    budget ran out, the incident budget (``max_recoveries``) was
    exceeded, or the same fault recurred at the same step after a
    restore (a deterministic failure wearing a transient's clothes).
    ``last_error`` carries the underlying failure."""

    def __init__(self, message, last_error=None):
        super().__init__(message)
        self.last_error = last_error


def _default_retry_policy():
    return RetryPolicy(
        base_s=_config.get_float("PYSTELLA_RESILIENCE_BACKOFF_BASE_S"),
        factor=2.0,
        max_s=_config.get_float("PYSTELLA_RESILIENCE_BACKOFF_MAX_S"),
        jitter=0.1,
        budget_s=_config.get_float("PYSTELLA_RESILIENCE_RETRY_BUDGET_S"))


class Supervisor:
    """Drive ``step_fn`` for ``nsteps`` steps under fault supervision.

    :arg step_fn: ``step_fn(state, step) -> state`` — one simulation
        step; ``step`` is the 0-based index of the step being taken.
        Donation is the caller's business, but note the supervisor may
        re-dispatch from a restored state after a fault.
    :arg checkpointer: a :class:`~pystella_tpu.Checkpointer`; the
        supervisor drives its schedule/finalize split and reads its
        durable ``last_good``.
    :arg nsteps: total steps the run is complete at.
    :arg monitor: optional :class:`~pystella_tpu.HealthMonitor` (or any
        object with ``observe``/``poll``/``flush``/``discard`` and
        ``check_now``/``check_sync``): observed every step, flushed +
        synchronously checked before every checkpoint save.
    :arg checkpoint_every: checkpoint interval in steps (default: the
        ``PYSTELLA_RESILIENCE_CHECKPOINT_EVERY`` registry value). The
        replay bound after a fault is exactly this interval.
    :arg restore_fn: optional per-leaf callable applied to restored
        host arrays (e.g. ``decomp.shard``) — the placement half of a
        resume.
    :arg faults: optional :class:`~pystella_tpu.resilience.faults.
        FaultInjector`, consulted entering every step (tests, drills).
    :arg retry: :class:`~pystella_tpu.resilience.retry.RetryPolicy`
        for recovery attempts within one incident (default: the
        ``PYSTELLA_RESILIENCE_*`` registry values).
    :arg max_recoveries: incident budget for the whole run (default:
        ``PYSTELLA_RESILIENCE_MAX_RECOVERIES``); one more fault raises
        :class:`RecoveryFailed`.
    :arg remesh: optional hook ``remesh(error, attempt) -> None | dict``
        called during device-loss recovery; returning
        ``{"step_fn": ..., "restore_fn": ..., "decomp": ...,
        "monitor": ..., "note": ...}`` (any subset) swaps in a
        re-meshed program for the surviving devices and emits
        ``run_degraded``. When set it OVERRIDES ``planner``.
    :arg planner: optional
        :class:`~pystella_tpu.resilience.remesh.RemeshPlanner` — the
        DEFAULT remesh policy: on device-loss recovery (and no
        ``remesh`` hook) it resolves the survivors, solves the best
        feasible degraded mesh (emitting ``remesh_plan``), rebuilds
        the step function through the original constructors, and the
        restore lands straight on the new mesh.
    :arg redial: re-initialize the multi-controller runtime during
        device-loss recovery (default ``True``; a single-process run's
        re-dial is a no-op). A CALLABLE replaces the default
        ``multihost.reinit()`` — e.g. a multi-process drill re-dialing
        as a smaller cluster with explicit coordinator arguments.
    :arg metadata_fn: optional ``metadata_fn(step, state) -> dict``
        merged into every checkpoint's metadata.
    :arg keep_initial: keep a host-side copy of the initial state so a
        fault *before the first checkpoint* can restart from step 0
        instead of failing the run (default ``True``; skipped
        automatically for non-fully-addressable multi-host arrays —
        costs one host copy of the state).
    :arg install_sigterm: install the SIGTERM preemption handler for
        the duration of :meth:`run` (main thread only; elsewhere the
        flag can be set manually via :meth:`request_preemption`).
    :arg label: tag carried on every emitted event.
    """

    def __init__(self, step_fn, checkpointer, nsteps, *, monitor=None,
                 checkpoint_every=None, restore_fn=None, faults=None,
                 retry=None, max_recoveries=None, remesh=None,
                 planner=None, redial=True, metadata_fn=None,
                 keep_initial=True, install_sigterm=True, label=""):
        self.step_fn = step_fn
        self.checkpointer = checkpointer
        self.nsteps = int(nsteps)
        self.monitor = monitor
        self.checkpoint_every = int(
            checkpoint_every if checkpoint_every is not None
            else _config.get_int("PYSTELLA_RESILIENCE_CHECKPOINT_EVERY"))
        if self.checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        self.restore_fn = restore_fn
        self.faults = faults
        self.retry_policy = retry or _default_retry_policy()
        self.max_recoveries = int(
            max_recoveries if max_recoveries is not None
            else _config.get_int("PYSTELLA_RESILIENCE_MAX_RECOVERIES"))
        self.remesh = remesh
        self.planner = planner
        #: set by a re-mesh swap: restores then land straight on this
        #: decomposition's mesh (the Checkpointer mesh= template path)
        self.restore_decomp = None
        self.redial = redial if callable(redial) else bool(redial)
        self.metadata_fn = metadata_fn
        self.keep_initial = bool(keep_initial)
        self.install_sigterm = bool(install_sigterm)
        self.label = label
        #: incident records of the last :meth:`run` (newest last)
        self.incidents = []
        self._preempt_signum = None
        self._initial = None            # (step, host-copied state)
        self._last_incident_key = None

    # -- preemption --------------------------------------------------------

    def request_preemption(self, signum=signal.SIGTERM):
        """Flag the run for a drain + durable checkpoint + clean return
        at the next step boundary (what the SIGTERM handler does)."""
        self._preempt_signum = int(signum)

    def _handler(self, signum, frame):
        self.request_preemption(signum)

    # -- the run -----------------------------------------------------------

    def run(self, state=None, start_step=0, resume="auto"):
        """Drive the run to completion (or a clean preemption point).

        :arg state: the initial state pytree; may be ``None`` when
            resuming from an existing checkpoint.
        :arg start_step: steps already completed in ``state``.
        :arg resume: ``"auto"`` restores from the newest durable
            checkpoint when one exists (walking back past a corrupt
            one) and falls back to ``state`` otherwise; ``True``
            requires a checkpoint; ``False`` ignores checkpoints.

        Returns a report dict: ``state`` (final), ``completed``,
        ``preempted``, ``final_step``, ``steps_run``,
        ``steps_replayed``, ``incidents``, ``wall_s``.
        """
        t_run0 = time.monotonic()
        self.incidents = []
        self._last_incident_key = None
        self._preempt_signum = None

        step = int(start_step)
        if resume and self.checkpointer.all_steps():
            step, state, _meta = self._restore()
            _events.emit("run_resumed", step=step, label=self.label,
                         source="restart", incident=False)
        elif resume is True:
            raise FileNotFoundError(
                f"resume=True but no checkpoints under "
                f"{self.checkpointer.directory}")
        if state is None:
            raise ValueError("no initial state and nothing to resume")
        self._snapshot_initial(step, state)

        _events.emit("supervisor_start", step=step, label=self.label,
                     nsteps=self.nsteps,
                     checkpoint_every=self.checkpoint_every,
                     max_recoveries=self.max_recoveries)

        prev_handler = None
        handler_installed = False
        if self.install_sigterm:
            try:
                prev_handler = signal.signal(signal.SIGTERM, self._handler)
                handler_installed = True
            except ValueError:
                pass  # not the main thread: preemption flag only
        steps_run = 0
        try:
            while step < self.nsteps:
                try:
                    # the preemption drain runs INSIDE fault triage: its
                    # pre-save health check can legitimately trip (NaN
                    # entered within the sentinel's maturity lag before
                    # SIGTERM arrived) — recovery then restores a clean
                    # state and the still-set flag drains THAT instead
                    # of durably checkpointing a diverged state
                    if self._preempt_signum is not None:
                        return self._preempt(step, state, steps_run,
                                             t_run0)
                    if self.faults is not None:
                        state = self.faults.apply(step, state)
                    with trace_scope("supervised_step"):
                        state = self.step_fn(state, step)
                    step += 1
                    steps_run += 1
                    if self.monitor is not None:
                        self.monitor.observe(step, state)
                        self.monitor.poll()
                    if step % self.checkpoint_every == 0 \
                            or step == self.nsteps:
                        self._checkpoint(step, state)
                except SimulationDiverged as e:
                    step, state = self._recover("numerics", e, step, state)
                except Exception as e:  # noqa: BLE001 — triaged below
                    if classify_exception(e) != "transient":
                        _events.emit(
                            "fault_detected", step=step, label=self.label,
                            fault_kind="deterministic", action="reraise",
                            error=f"{type(e).__name__}: {e}")
                        raise
                    step, state = self._recover("device_loss", e, step,
                                                state)
            if self.monitor is not None:
                self.monitor.flush()
            self.checkpointer.finalize()
            report = self._report(state, step, steps_run, t_run0,
                                  completed=True, preempted=False)
            _events.emit("supervisor_done", step=step, label=self.label,
                         **{k: v for k, v in report.items()
                            if k not in ("state", "label",
                                         "incident_records")})
            return report
        finally:
            if handler_installed:
                signal.signal(signal.SIGTERM, prev_handler)

    # -- pieces ------------------------------------------------------------

    def _snapshot_initial(self, step, state):
        if not self.keep_initial:
            return
        import jax
        import numpy as np
        leaves = jax.tree_util.tree_leaves(state)
        if any(getattr(x, "is_fully_addressable", True) is False
               for x in leaves):
            self.keep_initial = False  # multi-host: no host copy exists
            return
        self._initial = (int(step),
                         jax.tree_util.tree_map(np.array, state))

    def _metadata(self, step, state):
        meta = {"step": int(step), "label": self.label}
        if self.metadata_fn is not None:
            meta.update(self.metadata_fn(step, state) or {})
        return meta

    def _checkpoint(self, step, state):
        # a diverged state must never be checkpointed: drain the async
        # queue (trips report their true step) and check the state
        # about to be saved synchronously
        if self.monitor is not None:
            self.monitor.flush()
            check = getattr(self.monitor, "check_now", None)
            if check is not None:
                check(state, step=step)
            else:
                self.monitor.check_sync(step, state)
        # durability barrier for the PREVIOUS interval's save — it has
        # had a whole interval to land, so this is (nearly) free and
        # keeps the write itself off the step path
        self.checkpointer.finalize()
        # once something durable exists on disk, the initial-state
        # snapshot can never be needed again: release the host copy (a
        # production state is gigabytes)
        if self._initial is not None \
                and self.checkpointer.last_good is not None:
            self._initial = None
        self.checkpointer.save(step, state,
                               metadata=self._metadata(step, state))
        if step == self.nsteps:
            self.checkpointer.finalize()

    def _restore(self):
        if self.restore_decomp is not None:
            # a re-meshed run: restore straight onto the degraded mesh
            # (orbax reads each device's shard directly — the state is
            # never materialized on one device)
            step, state, meta = self.checkpointer.restore(
                mesh=self.restore_decomp)
        else:
            step, state, meta = self.checkpointer.restore(
                sharding_fn=self.restore_fn)
        return int(step), state, meta

    def _restore_or_restart(self):
        """Restore from the newest durable checkpoint, or — when no
        checkpoint exists yet, or when every on-disk checkpoint turns
        out to be torn (listed but unrestorable: a crash mid-first-
        write) — restart from the initial-state snapshot. A fault
        before the first DURABLE checkpoint must not be fatal when the
        run can simply start over; the snapshot is only released once
        something durable exists, so this fallback and the release
        policy cover each other exactly."""
        if self.checkpointer.all_steps():
            try:
                return self._restore()
            except Exception:
                if self._initial is None:
                    raise
                _events.emit("checkpoint_fallback", step=None,
                             label=self.label,
                             error="every on-disk checkpoint failed to "
                                   "restore; restarting from the "
                                   "initial-state snapshot")
        if self._initial is not None:
            import jax
            step0, host_state = self._initial
            place = self.restore_fn or (lambda x: x)
            return (step0,
                    jax.tree_util.tree_map(place, host_state), None)
        raise FileNotFoundError(
            "no checkpoint to restore and no initial-state snapshot "
            "(keep_initial=False)")

    def _redial(self):
        if callable(self.redial):
            self.redial()
            return
        from pystella_tpu.parallel import multihost
        multihost.reinit()

    def _apply_swap(self, swap, at_step):
        """Install a re-meshed program (from the ``remesh`` hook or the
        planner): swap the step function, point restores at the new
        mesh, and refresh the monitor's decomposition-derived state —
        a swapped mesh must not leave the monitor checking vectors
        (or the checkpointer placing shards) against the old
        sharding."""
        self.step_fn = swap.get("step_fn", self.step_fn)
        self.restore_fn = swap.get("restore_fn", self.restore_fn)
        if swap.get("decomp") is not None:
            self.restore_decomp = swap["decomp"]
        if "monitor" in swap:
            self.monitor = swap["monitor"]
        elif self.monitor is not None:
            reset = getattr(self.monitor, "reset", None)
            if reset is not None:
                reset()
        _events.emit("run_degraded", step=at_step, label=self.label,
                     note=swap.get("note", "re-meshed to surviving "
                                   "devices"))

    def _finalize_bounded(self, timeout_s):
        """The durability barrier, with a wall bound — ONLY for the
        recovery path. ``Checkpointer.finalize()`` blocks in orbax's
        ``wait_until_finished``; a device dying mid-async-write can
        leave that wait stuck forever, and a blocked call never raises,
        so the per-incident retry budget would never fire. Run it in a
        daemon thread and convert a timeout into a ``TimeoutError``
        (classified transient -> counted against the retry budget). On
        timeout the thread stays blocked in orbax — leaked by design;
        the process is mid-disaster-recovery and about to give up or
        re-dial anyway."""
        import threading
        box = {}
        done = threading.Event()

        def _run():
            try:
                box["ok"] = self.checkpointer.finalize()
            except BaseException as e:  # noqa: B036 — rethrown below
                box["error"] = e
            finally:
                done.set()

        th = threading.Thread(target=_run, daemon=True,
                              name="ckpt-finalize")
        th.start()
        if not done.wait(timeout_s):
            raise TimeoutError(
                f"checkpoint durability barrier timed out after "
                f"{timeout_s:.0f}s (async write wedged mid-recovery)")
        if "error" in box:
            raise box["error"]
        return box.get("ok")

    def _recover(self, kind, error, at_step, state):
        """One incident: triage happened, now re-dial / re-mesh /
        restore / bound the replay. Returns ``(step, state)`` to resume
        the loop from; raises :class:`RecoveryFailed` when recovery
        itself gives up."""
        t0 = time.monotonic()
        err_str = f"{type(error).__name__}: {error}"
        trip_step = getattr(error, "step", at_step)
        # one causal span per incident (schema v2): every recovery
        # event below shares it, with the enclosing context (a service
        # lease span, when the supervisor runs under one) recorded as
        # its parent — the span assembler attributes the whole MTTR to
        # the lease's recovery-replay phase through that link. Outside
        # any tracing context (a standalone supervised run, or the
        # service with PYSTELLA_TRACE_SERVICE=0) the events stay
        # v1-shaped: an orphan span id would attach to nothing
        if _events.current_trace() is not None:
            with _events.tracing(span=_events.new_span_id()):
                return self._recover_traced(kind, error, err_str,
                                            at_step, trip_step, state,
                                            t0)
        return self._recover_traced(kind, error, err_str, at_step,
                                    trip_step, state, t0)

    def _recover_traced(self, kind, error, err_str, at_step, trip_step,
                        state, t0):
        _events.emit("fault_detected", step=at_step, label=self.label,
                     fault_kind=kind, error=err_str, trip_step=trip_step)

        if len(self.incidents) >= self.max_recoveries:
            _events.emit("recovery_failed", step=at_step, label=self.label,
                         fault_kind=kind, reason="incident budget exhausted",
                         incidents=len(self.incidents))
            raise RecoveryFailed(
                f"incident budget exhausted ({len(self.incidents)} "
                f"recoveries already this run); latest: {err_str}",
                last_error=error) from error
        key = (kind, int(trip_step))
        if key == self._last_incident_key:
            # the same fault at the same step straight after a restore:
            # deterministic recurrence, replaying again cannot help
            _events.emit("recovery_failed", step=at_step, label=self.label,
                         fault_kind=kind, reason="fault recurred at the same "
                         "step after restore", trip_step=trip_step)
            raise RecoveryFailed(
                f"{kind} fault recurred at step {trip_step} after a "
                f"restore — deterministic, not retrying: {err_str}",
                last_error=error) from error

        if self.monitor is not None:
            # pending health vectors describe the corrupted trajectory
            self.monitor.discard()

        retrier = Retrier(self.retry_policy, emit=_events.emit,
                          label=self.label or "supervisor")
        attempt = 0
        while True:
            attempt += 1
            _events.emit("recovery_attempt", step=at_step,
                         label=self.label, fault_kind=kind, attempt=attempt)
            try:
                if kind == "device_loss":
                    if self.redial:
                        self._redial()
                    swap = None
                    if self.remesh is not None:       # hook overrides
                        swap = self.remesh(error, attempt)
                    elif self.planner is not None:    # default policy
                        swap = self.planner(error, attempt,
                                            faults=self.faults,
                                            step=at_step)
                    if swap:
                        self._apply_swap(swap, at_step)
                # scheduled-but-unconfirmed writes must settle before a
                # read; a torn one is walked back over by restore().
                # Bounded: a barrier wedged by the very device loss
                # being recovered from must count against the retry
                # budget, not hang recovery forever
                budget = self.retry_policy.budget_s or 600.0
                self._finalize_bounded(max(10.0, budget / 4.0))
                step, state, _meta = self._restore_or_restart()
                break
            except Exception as e2:  # noqa: BLE001 — budgeted below
                decision, reason = retrier.note_failure(
                    kind=classify_exception(e2), error=e2)
                if decision == "stop":
                    _events.emit("recovery_failed", step=at_step,
                                 label=self.label, fault_kind=kind,
                                 reason=reason, attempt=attempt,
                                 error=f"{type(e2).__name__}: {e2}")
                    raise RecoveryFailed(
                        f"recovery gave up after {attempt} attempt(s) "
                        f"({reason}); last error: "
                        f"{type(e2).__name__}: {e2}",
                        last_error=e2) from e2
                retrier.wait()

        mttr_s = time.monotonic() - t0
        steps_replayed = max(0, at_step - step)
        incident = {
            "kind": kind, "step": int(trip_step),
            "detected_at_step": int(at_step),
            "restored_step": int(step),
            "steps_replayed": int(steps_replayed),
            "attempts": int(attempt),
            "mttr_s": float(mttr_s),
            "error": err_str,
        }
        self.incidents.append(incident)
        self._last_incident_key = key
        _events.emit("run_resumed", step=step, label=self.label,
                     source="recovery", incident=True, fault_kind=kind,
                     from_step=at_step, mttr_s=round(mttr_s, 4),
                     steps_replayed=steps_replayed, attempts=attempt)
        return step, state

    def _preempt(self, step, state, steps_run, t_run0):
        """SIGTERM drain: flush + check, durable checkpoint, clean
        return — the restarted process resumes exactly here. Runs
        inside the run loop's fault triage: a trip here (corrupt state
        caught by the drain's own health check) recovers first, then
        the still-set preemption flag drains the restored state. The
        drain's wall cost lands on ``run_preempted`` as ``drain_s`` —
        the span assembler's preempt-drain phase is measured, not
        inferred."""
        t_drain0 = time.monotonic()
        if self.monitor is not None:
            # same contract as _checkpoint: a diverged state must
            # never be checkpointed — not even by a preemption drain
            self.monitor.flush()
            check = getattr(self.monitor, "check_now", None)
            if check is not None:
                check(state, step=step)
            else:
                self.monitor.check_sync(step, state)
        self.checkpointer.finalize()
        if self.checkpointer.latest_step != step:
            self.checkpointer.save(step, state,
                                   metadata=self._metadata(step, state))
        self.checkpointer.finalize()
        _events.emit("run_preempted", step=step, label=self.label,
                     signum=self._preempt_signum,
                     checkpoint_step=step,
                     drain_s=round(time.monotonic() - t_drain0, 6))
        report = self._report(state, step, steps_run, t_run0,
                              completed=False, preempted=True)
        _events.emit("supervisor_done", step=step, label=self.label,
                     **{k: v for k, v in report.items()
                        if k not in ("state", "label",
                                     "incident_records")})
        return report

    def _report(self, state, step, steps_run, t_run0, completed,
                preempted):
        return {
            "state": state,
            "completed": bool(completed),
            "preempted": bool(preempted),
            "final_step": int(step),
            "steps_run": int(steps_run),
            "steps_replayed": int(sum(i["steps_replayed"]
                                      for i in self.incidents)),
            "incidents": len(self.incidents),
            "incident_records": list(self.incidents),
            "wall_s": float(time.monotonic() - t_run0),
            "last_good": self.checkpointer.last_good,
            "label": self.label,
        }
