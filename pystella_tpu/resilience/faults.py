"""Deterministic fault injection: make every recovery path testable.

The production environment for this stack is demonstrably hostile
(rounds 3 and 5 lost their entire TPU windows to transport outages),
but hostile environments are a terrible test harness: they fail rarely,
irreproducibly, and never in CI. This module is the controlled
replacement — a :class:`FaultInjector` the
:class:`~pystella_tpu.resilience.Supervisor` consults at every step
boundary, firing scripted faults at exact step numbers so the recovery
machinery (restore-from-last-good, bounded replay, preemption drain)
runs end to end on the 8-device CPU mesh in tier-1.

Fault taxonomy (``doc/resilience.md``):

- :class:`RaiseFault` — raise an arbitrary exception entering step N.
  With :func:`device_loss_error` it simulates the signature failure
  mode: an ``XlaRuntimeError`` whose message carries ``UNAVAILABLE``
  (the real class when jaxlib is present, a stand-in subclass named the
  same otherwise — :func:`~pystella_tpu.resilience.retry.
  classify_exception` keys on type name + message, so both classify
  transient).
- :class:`NaNFault` — corrupt one element of a named state field to
  NaN entering step N: the silent-numerics failure the sentinel
  (:mod:`pystella_tpu.obs.sentinel`) exists to catch. The corruption
  round-trips through host and is re-placed with the leaf's own
  sharding, so sharded states work unchanged.
- :class:`SigtermFault` — send this process SIGTERM entering step N:
  the preemption notice a managed TPU VM gets. The supervisor's
  handler drains, checkpoints durably, and exits clean.

Every fault is **one-shot by default** (``once=True``): after a
recovery rolls the run back past the fault step, replaying through it
must not re-fire — that is exactly the transient-fault contract. Pass
``once=False`` to model a persistent (deterministic) fault and test
the give-up path instead.

Each firing emits a ``fault_injected`` run event, so a supervised run's
event log records what the harness did to it alongside what the
recovery machinery did about it.
"""

from __future__ import annotations

import os
import signal

import numpy as np

from pystella_tpu.obs import events as _events

__all__ = ["Fault", "RaiseFault", "NaNFault", "SigtermFault",
           "FaultInjector", "device_loss_error"]


def device_loss_error(detail="injected device loss (fault harness)"):
    """An exception instance indistinguishable from a mid-run device
    loss as far as classification goes: the real ``XlaRuntimeError``
    when jaxlib exposes it, else a local ``RuntimeError`` subclass of
    the same name; either way the message leads with ``UNAVAILABLE``,
    so :func:`~pystella_tpu.resilience.retry.classify_exception` says
    transient — the verdict a dying transport earns."""
    msg = f"UNAVAILABLE: {detail}"
    try:
        from jax._src.lib import xla_client
        return xla_client.XlaRuntimeError(msg)
    except Exception:
        cls = type("XlaRuntimeError", (RuntimeError,), {})
        return cls(msg)


class Fault:
    """One scripted fault, armed for a step number.

    :arg step: the (0-based) step index the fault fires *entering* —
        i.e. before the step computation runs.
    :arg once: disarm after the first firing (the transient contract);
        ``False`` keeps it armed across replays (a persistent fault).
    """

    kind = "fault"

    def __init__(self, step, once=True):
        self.step = int(step)
        self.once = bool(once)
        self.fired = 0

    def should_fire(self, step):
        if self.once and self.fired:
            return False
        return int(step) == self.step

    def fire(self, state):
        """Apply the fault; returns the (possibly replaced) state or
        raises. Subclasses implement :meth:`_fire`."""
        self.fired += 1
        return self._fire(state)

    def _fire(self, state):
        raise NotImplementedError

    def describe(self):
        return {"kind": self.kind, "step": self.step, "once": self.once}


class RaiseFault(Fault):
    """Raise ``error`` (an instance or zero-arg factory) at the step —
    device loss, transport drop, or any exception under test."""

    kind = "raise"

    def __init__(self, step, error=None, once=True):
        super().__init__(step, once=once)
        self._error = error

    def _fire(self, state):
        err = self._error
        if callable(err):
            err = err()
        if err is None:
            err = device_loss_error()
        raise err

    def describe(self):
        err = self._error if not callable(self._error) else None
        return {**super().describe(),
                "error": None if err is None else
                f"{type(err).__name__}: {err}"}


class NaNFault(Fault):
    """Overwrite one element of state field ``field`` with NaN.

    :arg field: dotted leaf name (top-level dict key covers the common
        case).
    :arg index: flat index into the raveled leaf (default 0).
    """

    kind = "nan"

    def __init__(self, step, field, index=0, once=True):
        super().__init__(step, once=once)
        self.field = str(field)
        self.index = int(index)

    def _fire(self, state):
        import jax
        from pystella_tpu.obs.sentinel import named_leaves
        leaves = named_leaves(state)
        if self.field not in leaves:
            raise KeyError(
                f"NaNFault field {self.field!r} not in state leaves "
                f"{sorted(leaves)}")
        leaf = leaves[self.field]
        host = np.array(leaf)  # host copy; the original stays intact
        host.ravel()[self.index] = np.nan
        sharding = getattr(leaf, "sharding", None)
        corrupted = (jax.device_put(host, sharding)
                     if sharding is not None else host)

        def swap(path, x):
            from pystella_tpu.obs.sentinel import _leaf_name
            return corrupted if _leaf_name(path) == self.field else x

        return jax.tree_util.tree_map_with_path(swap, state)

    def describe(self):
        return {**super().describe(), "field": self.field,
                "index": self.index}


class SigtermFault(Fault):
    """Deliver SIGTERM to this very process at the step — the
    preemption notice. The state passes through untouched; the
    supervisor's installed handler turns the signal into a drain +
    durable checkpoint + clean exit at the next step boundary."""

    kind = "sigterm"

    def _fire(self, state):
        os.kill(os.getpid(), signal.SIGTERM)
        return state


class FaultInjector:
    """A schedule of :class:`Fault`\\ s consulted once per step.

    The supervisor calls :meth:`apply(step, state)` entering every
    step; each armed fault whose step matches fires (emitting a
    ``fault_injected`` event first, so the record survives even when
    the fault raises). Convenience constructors cover the taxonomy::

        FaultInjector.device_loss(step=9)
        FaultInjector.nan(step=6, field="f")
        FaultInjector.sigterm(step=5)

    and compose: ``FaultInjector([RaiseFault(3), NaNFault(7, "f")])``.
    """

    def __init__(self, faults=(), label=""):
        self.faults = list(faults)
        self.label = label

    # -- convenience constructors ------------------------------------------

    @classmethod
    def device_loss(cls, step, detail=None, once=True, label=""):
        err = (device_loss_error if detail is None
               else (lambda: device_loss_error(detail)))
        return cls([RaiseFault(step, err, once=once)], label=label)

    @classmethod
    def nan(cls, step, field, index=0, once=True, label=""):
        return cls([NaNFault(step, field, index=index, once=once)],
                   label=label)

    @classmethod
    def sigterm(cls, step, label=""):
        return cls([SigtermFault(step)], label=label)

    @classmethod
    def raise_at(cls, step, error, once=True, label=""):
        return cls([RaiseFault(step, error, once=once)], label=label)

    # -- the injection point -----------------------------------------------

    def apply(self, step, state):
        """Fire every armed fault scheduled for ``step``; returns the
        (possibly corrupted) state, or raises what a raising fault
        raised."""
        for fault in self.faults:
            if fault.should_fire(step):
                desc = fault.describe()
                # "kind"/"step" collide with emit()'s own parameters
                desc["fault_kind"] = desc.pop("kind")
                desc.pop("step", None)
                _events.emit("fault_injected", step=step,
                             label=self.label, **desc)
                state = fault.fire(state)
        return state

    @property
    def fired(self):
        """Total firings so far across all scheduled faults."""
        return sum(f.fired for f in self.faults)
