"""Deterministic fault injection: make every recovery path testable.

The production environment for this stack is demonstrably hostile
(rounds 3 and 5 lost their entire TPU windows to transport outages),
but hostile environments are a terrible test harness: they fail rarely,
irreproducibly, and never in CI. This module is the controlled
replacement — a :class:`FaultInjector` the
:class:`~pystella_tpu.resilience.Supervisor` consults at every step
boundary, firing scripted faults at exact step numbers so the recovery
machinery (restore-from-last-good, bounded replay, preemption drain)
runs end to end on the 8-device CPU mesh in tier-1.

Fault taxonomy (``doc/resilience.md``):

- :class:`RaiseFault` — raise an arbitrary exception entering step N.
  With :func:`device_loss_error` it simulates the signature failure
  mode: an ``XlaRuntimeError`` whose message carries ``UNAVAILABLE``
  (the real class when jaxlib is present, a stand-in subclass named the
  same otherwise — :func:`~pystella_tpu.resilience.retry.
  classify_exception` keys on type name + message, so both classify
  transient).
- :class:`NaNFault` — corrupt one element of a named state field to
  NaN entering step N: the silent-numerics failure the sentinel
  (:mod:`pystella_tpu.obs.sentinel`) exists to catch. The corruption
  round-trips through host and is re-placed with the leaf's own
  sharding, so sharded states work unchanged.
- :class:`SigtermFault` — send this process SIGTERM entering step N:
  the preemption notice a managed TPU VM gets. The supervisor's
  handler drains, checkpoints durably, and exits clean.
- :class:`DeviceSubsetFault` — targeted loss of a named device subset
  (explicit ids, the last ``count`` devices, or a mesh-axis slice):
  the re-mesh drill's fault. **Persistent by default**
  (``once=False``) because lost hardware stays lost — but after a
  firing it only re-raises while the state still TOUCHES a lost
  device, so a correct re-mesh (the program rebuilt over the
  survivors) sails through the replay while a broken one re-trips
  into the deterministic-recurrence give-up path. The fired devices
  land in :meth:`FaultInjector.lost_devices`, which the
  :class:`~pystella_tpu.resilience.remesh.RemeshPlanner` consults as
  its survivor probe in deterministic single-process drills.

Every raising/corrupting fault is **one-shot by default**
(``once=True``): after a recovery rolls the run back past the fault
step, replaying through it must not re-fire — that is exactly the
transient-fault contract. Pass ``once=False`` to model a persistent
(deterministic) fault and test the give-up path instead
(:class:`DeviceSubsetFault` inverts the default, as above).

Each firing emits a ``fault_injected`` run event, so a supervised run's
event log records what the harness did to it alongside what the
recovery machinery did about it.
"""

from __future__ import annotations

import os
import signal

import numpy as np

from pystella_tpu.obs import events as _events

__all__ = ["Fault", "RaiseFault", "NaNFault", "SigtermFault",
           "DeviceSubsetFault", "FaultInjector", "device_loss_error"]


def device_loss_error(detail="injected device loss (fault harness)"):
    """An exception instance indistinguishable from a mid-run device
    loss as far as classification goes: the real ``XlaRuntimeError``
    when jaxlib exposes it, else a local ``RuntimeError`` subclass of
    the same name; either way the message leads with ``UNAVAILABLE``,
    so :func:`~pystella_tpu.resilience.retry.classify_exception` says
    transient — the verdict a dying transport earns."""
    msg = f"UNAVAILABLE: {detail}"
    try:
        from jax._src.lib import xla_client
        return xla_client.XlaRuntimeError(msg)
    except Exception:
        cls = type("XlaRuntimeError", (RuntimeError,), {})
        return cls(msg)


class Fault:
    """One scripted fault, armed for a step number.

    :arg step: the (0-based) step index the fault fires *entering* —
        i.e. before the step computation runs.
    :arg once: disarm after the first firing (the transient contract);
        ``False`` keeps it armed across replays (a persistent fault).
    """

    kind = "fault"

    def __init__(self, step, once=True):
        self.step = int(step)
        self.once = bool(once)
        self.fired = 0

    def should_fire(self, step):
        if self.once and self.fired:
            return False
        return int(step) == self.step

    def fire(self, state):
        """Apply the fault; returns the (possibly replaced) state or
        raises. Subclasses implement :meth:`_fire`."""
        self.fired += 1
        return self._fire(state)

    def _fire(self, state):
        raise NotImplementedError

    def describe(self):
        return {"kind": self.kind, "step": self.step, "once": self.once}


class RaiseFault(Fault):
    """Raise ``error`` (an instance or zero-arg factory) at the step —
    device loss, transport drop, or any exception under test."""

    kind = "raise"

    def __init__(self, step, error=None, once=True):
        super().__init__(step, once=once)
        self._error = error

    def _fire(self, state):
        err = self._error
        if callable(err):
            err = err()
        if err is None:
            err = device_loss_error()
        raise err

    def describe(self):
        err = self._error if not callable(self._error) else None
        return {**super().describe(),
                "error": None if err is None else
                f"{type(err).__name__}: {err}"}


class NaNFault(Fault):
    """Overwrite one element of state field ``field`` with NaN.

    :arg field: dotted leaf name (top-level dict key covers the common
        case).
    :arg index: flat index into the raveled leaf (default 0).
    """

    kind = "nan"

    def __init__(self, step, field, index=0, once=True):
        super().__init__(step, once=once)
        self.field = str(field)
        self.index = int(index)

    def _fire(self, state):
        import jax
        from pystella_tpu.obs.sentinel import named_leaves
        leaves = named_leaves(state)
        if self.field not in leaves:
            raise KeyError(
                f"NaNFault field {self.field!r} not in state leaves "
                f"{sorted(leaves)}")
        leaf = leaves[self.field]
        host = np.array(leaf)  # host copy; the original stays intact
        host.ravel()[self.index] = np.nan
        sharding = getattr(leaf, "sharding", None)
        corrupted = (jax.device_put(host, sharding)
                     if sharding is not None else host)

        def swap(path, x):
            from pystella_tpu.obs.sentinel import _leaf_name
            return corrupted if _leaf_name(path) == self.field else x

        return jax.tree_util.tree_map_with_path(swap, state)

    def describe(self):
        return {**super().describe(), "field": self.field,
                "index": self.index}


def state_devices(state):
    """Every device the leaves of ``state`` are committed to, sorted
    by id — the "what does the program still touch" probe behind
    :class:`DeviceSubsetFault`'s persistence semantics."""
    import jax
    devs = set()
    for leaf in jax.tree_util.tree_leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                devs.update(sharding.device_set)
            except Exception:
                pass
    return sorted(devs, key=lambda d: getattr(d, "id", 0))


class DeviceSubsetFault(Fault):
    """Lose a named subset of the devices the state lives on.

    :arg step: first step the loss is visible at (it persists after).
    :arg device_ids: explicit device ids to lose, or
    :arg count: lose the LAST ``count`` devices of the state's sorted
        device set (the common "one host's chips died" drill), or
    :arg mesh: + :arg axis: + :arg index: lose mesh-axis slice
        ``index`` of ``axis`` of ``mesh`` (a named topology slice —
        e.g. ``axis="x", index=1`` on a (2,2,2) mesh loses 4 devices).
    :arg once: default **False** — lost hardware stays lost. After the
        first firing the fault re-raises only while the state still
        touches a lost device, so a remeshed program replays through
        cleanly and the lost set stays queryable via
        :meth:`FaultInjector.lost_devices`.

    Env spec (``PYSTELLA_FAULT_DEVICE_SUBSET``, parsed by
    :meth:`from_spec`): ``"<step>:<count>"`` — e.g. ``"9:4"`` loses
    the last 4 devices entering step 9.
    """

    kind = "device_subset"

    def __init__(self, step, device_ids=None, count=None, mesh=None,
                 axis=None, index=None, once=False):
        super().__init__(step, once=once)
        if device_ids is None and count is None and axis is None:
            raise ValueError("DeviceSubsetFault needs device_ids=, "
                             "count=, or mesh=/axis=/index=")
        self.device_ids = (None if device_ids is None
                           else sorted(int(i) for i in device_ids))
        self.count = None if count is None else int(count)
        if axis is not None:
            if mesh is None or index is None:
                raise ValueError("axis= needs mesh= and index=")
            sliced = np.take(mesh.devices,
                             int(index), axis=mesh.axis_names.index(axis))
            self.device_ids = sorted(
                int(d.id) for d in np.asarray(sliced).flat)
        #: the concrete lost devices, resolved at first firing
        self.lost = []

    @classmethod
    def from_spec(cls, spec, **kwargs):
        """Parse the env-knob spelling ``"<step>:<count>"``."""
        step, _, count = str(spec).partition(":")
        return cls(int(step), count=int(count or 1), **kwargs)

    def should_fire(self, step):
        if self.once and self.fired:
            return False
        # persistent: armed from its step ON — lost hardware stays lost
        return int(step) >= self.step

    def still_applies(self, state):
        """After the first firing, only a program still touching a
        lost device faults again — the probe that makes a correct
        re-mesh provable by the replay NOT re-raising."""
        if not self.fired:
            return True
        lost = set(self.lost)
        return any(d in lost for d in state_devices(state))

    def _fire(self, state):
        if not self.lost:
            devs = state_devices(state)
            if self.device_ids is not None:
                ids = set(self.device_ids)
                self.lost = [d for d in devs
                             if int(getattr(d, "id", -1)) in ids]
            else:
                self.lost = devs[len(devs) - min(self.count, len(devs)):]
        ids = [int(getattr(d, "id", -1)) for d in self.lost]
        raise device_loss_error(
            f"device(s) {ids} lost (device-subset fault)")

    def describe(self):
        return {**super().describe(),
                "device_ids": self.device_ids, "count": self.count,
                "lost": [int(getattr(d, "id", -1)) for d in self.lost]}


class SigtermFault(Fault):
    """Deliver SIGTERM to this very process at the step — the
    preemption notice. The state passes through untouched; the
    supervisor's installed handler turns the signal into a drain +
    durable checkpoint + clean exit at the next step boundary."""

    kind = "sigterm"

    def _fire(self, state):
        os.kill(os.getpid(), signal.SIGTERM)
        return state


class FaultInjector:
    """A schedule of :class:`Fault`\\ s consulted once per step.

    The supervisor calls :meth:`apply(step, state)` entering every
    step; each armed fault whose step matches fires (emitting a
    ``fault_injected`` event first, so the record survives even when
    the fault raises). Convenience constructors cover the taxonomy::

        FaultInjector.device_loss(step=9)
        FaultInjector.nan(step=6, field="f")
        FaultInjector.sigterm(step=5)

    and compose: ``FaultInjector([RaiseFault(3), NaNFault(7, "f")])``.
    """

    def __init__(self, faults=(), label=""):
        self.faults = list(faults)
        self.label = label

    # -- convenience constructors ------------------------------------------

    @classmethod
    def device_loss(cls, step, detail=None, once=True, label=""):
        err = (device_loss_error if detail is None
               else (lambda: device_loss_error(detail)))
        return cls([RaiseFault(step, err, once=once)], label=label)

    @classmethod
    def nan(cls, step, field, index=0, once=True, label=""):
        return cls([NaNFault(step, field, index=index, once=once)],
                   label=label)

    @classmethod
    def sigterm(cls, step, label=""):
        return cls([SigtermFault(step)], label=label)

    @classmethod
    def raise_at(cls, step, error, once=True, label=""):
        return cls([RaiseFault(step, error, once=once)], label=label)

    @classmethod
    def device_subset(cls, step, device_ids=None, count=None, mesh=None,
                      axis=None, index=None, once=False, label=""):
        return cls([DeviceSubsetFault(step, device_ids=device_ids,
                                      count=count, mesh=mesh, axis=axis,
                                      index=index, once=once)],
                   label=label)

    @classmethod
    def from_env(cls, label=""):
        """The env-knob drill harness: an injector armed from
        ``PYSTELLA_FAULT_DEVICE_SUBSET`` (``"<step>:<count>"``; unset
        -> ``None``), persistence from
        ``PYSTELLA_FAULT_DEVICE_SUBSET_PERSIST``. Drivers opt in —
        e.g. a production supervisor rehearsing its own remesh path."""
        from pystella_tpu import config as _config
        spec = _config.getenv("PYSTELLA_FAULT_DEVICE_SUBSET")
        if not spec:
            return None
        persist = _config.get_bool("PYSTELLA_FAULT_DEVICE_SUBSET_PERSIST")
        return cls([DeviceSubsetFault.from_spec(spec,
                                                once=not persist)],
                   label=label)

    # -- the injection point -----------------------------------------------

    def apply(self, step, state):
        """Fire every armed fault scheduled for ``step``; returns the
        (possibly corrupted) state, or raises what a raising fault
        raised. A fault exposing ``still_applies(state)`` (the
        device-subset persistence probe) is consulted first, so a
        remeshed program replaying past a persistent loss neither
        re-raises nor spams ``fault_injected`` events."""
        for fault in self.faults:
            if fault.should_fire(step):
                check = getattr(fault, "still_applies", None)
                if check is not None and not check(state):
                    continue
                desc = fault.describe()
                # "kind"/"step" collide with emit()'s own parameters
                desc["fault_kind"] = desc.pop("kind")
                desc.pop("step", None)
                _events.emit("fault_injected", step=step,
                             label=self.label, **desc)
                state = fault.fire(state)
        return state

    def lost_devices(self):
        """Every device a fired :class:`DeviceSubsetFault` has taken —
        the deterministic survivor probe
        :class:`~pystella_tpu.resilience.remesh.RemeshPlanner` uses in
        single-process drills."""
        lost = []
        for f in self.faults:
            for d in getattr(f, "lost", ()):
                if d not in lost:
                    lost.append(d)
        return lost

    @property
    def fired(self):
        """Total firings so far across all scheduled faults."""
        return sum(f.fired for f in self.faults)
