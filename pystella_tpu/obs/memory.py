"""Compile-time and device-memory instrumentation.

Three kinds of evidence, all recorded into the event log:

- the **compile ledger** — every jit/AOT compile the package dispatches
  routes through here. :func:`compile_with_report` is the explicit
  ahead-of-time path (splitting *trace* seconds — Python tracing +
  StableHLO lowering — from *backend-compile* seconds, extracting XLA's
  ``memory_analysis()`` byte counts, and fingerprinting the program);
  :func:`instrument_jit` wraps the package's internal ``jax.jit``
  objects so a compile triggered by a first dispatch is attributed to a
  stable label (``step.LowStorageRK54``, ``fused.multi_step[10]``,
  ``mg.smooth``...) via jax's monitoring hooks instead of vanishing
  into startup time. Each observed compile emits a ``kind="compile"``
  event carrying the trace/compile split, a program fingerprint, and
  persistent-cache hit/miss attribution — the raw material of the perf
  ledger's ``cold_start`` section.
- :func:`ensure_compilation_cache` — wires jax's persistent
  compilation cache (``jax_compilation_cache_dir``) to the registered
  ``PYSTELLA_COMPILE_CACHE_DIR``, so a process that re-dials a device
  pays XLA's backend compile once per program *ever*, not once per
  process. Hit/miss counts are read back through the same monitoring
  hooks.
- :func:`device_memory_report` — live allocator statistics
  (``Device.memory_stats()``: bytes in use, peak, limit). TPU backends
  populate these; CPU returns ``None`` and the report degrades to a
  no-op instead of raising, so instrumented drivers run everywhere.

The peak-HBM estimate in a :class:`CompileRecord` is exactly the number
that would have caught round 5's 183 MB overshoot *before* the
allocator rejected the 512^3 GW step: ``rec.peak_bytes`` vs the chip's
HBM. The trace/compile split is the number that would have caught
round 3's ~365 s multigrid cold start — and now does.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time

import jax

from pystella_tpu.obs import events as _events
from pystella_tpu.obs import metrics as _metrics

__all__ = ["CompileRecord", "compile_with_report", "compile_watch",
           "instrument_jit", "InstrumentedJit", "compile_totals",
           "ensure_compilation_cache", "cache_donation_safe",
           "should_bypass_cache",
           "cache_bypass", "probe_cache_donation_safety",
           "program_fingerprint", "signature_fingerprint",
           "runtime_versions", "device_memory_stats",
           "device_memory_report"]


# ---------------------------------------------------------------------------
# jax monitoring bridge: trace/compile durations + persistent-cache events
# ---------------------------------------------------------------------------

#: monitoring events that measure Python-side program construction
#: (jaxpr tracing and StableHLO lowering — work a warm AOT start skips)
_TRACE_EVENTS = ("/jax/core/compile/jaxpr_trace_duration",
                 "/jax/core/compile/jaxpr_to_mlir_module_duration")
#: the XLA backend compile itself (work the persistent cache skips)
_BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"
#: persistent compilation cache outcomes
_CACHE_EVENTS = {"/jax/compilation_cache/cache_hits": "cache_hits",
                 "/jax/compilation_cache/cache_misses": "cache_misses"}

_totals_lock = threading.Lock()
_totals = {"trace_s": 0.0, "compile_s": 0.0,
           "cache_hits": 0, "cache_misses": 0}
_watchers = threading.local()
_listeners_installed = False
_install_lock = threading.Lock()


def _watcher_stack():
    stack = getattr(_watchers, "stack", None)
    if stack is None:
        stack = _watchers.stack = []
    return stack


def _on_duration(event, duration, **kwargs):
    if event in _TRACE_EVENTS:
        key = "trace_s"
    elif event == _BACKEND_EVENT:
        key = "compile_s"
    else:
        return
    with _totals_lock:
        _totals[key] += float(duration)
    for w in _watcher_stack():
        w._add(key, float(duration))


def _on_event(event, **kwargs):
    key = _CACHE_EVENTS.get(event)
    if key is None:
        return
    with _totals_lock:
        _totals[key] += 1
    for w in _watcher_stack():
        w._add(key, 1)


def _install_jax_listeners():
    """Register the monitoring listeners (idempotent; thread-safe).
    jax invokes them synchronously on the compiling thread, which is
    what lets a :class:`compile_watch` attribute activity to the
    program label whose dispatch triggered it."""
    global _listeners_installed
    if _listeners_installed:
        return
    with _install_lock:
        if _listeners_installed:
            return
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
        _listeners_installed = True


def compile_totals():
    """Process-wide accumulated compile activity since the listeners
    were installed: ``{trace_s, compile_s, cache_hits, cache_misses}``.
    The denominators of a cold-start story — how much of startup went
    to building programs vs running them."""
    _install_jax_listeners()
    with _totals_lock:
        return dict(_totals)


class compile_watch:
    """Attribute jax compile activity inside a ``with`` block to a
    label. Cheap enough to wrap every dispatch (one list append/pop and
    four float adds per *compile*, nothing per cached call)::

        with compile_watch("mg.smooth") as w:
            out = fn(*args)
        if w.compiled:
            ...  # w.trace_seconds / w.compile_seconds / w.cache_hits

    Nested watches each observe the same activity (an outer driver-level
    watch sees the sum of everything its inner calls compiled).
    """

    def __init__(self, label=None):
        self.label = label
        self.trace_seconds = 0.0
        self.compile_seconds = 0.0
        self.cache_hits = 0
        self.cache_misses = 0

    def _add(self, key, val):
        if key == "trace_s":
            self.trace_seconds += val
        elif key == "compile_s":
            self.compile_seconds += val
        elif key == "cache_hits":
            self.cache_hits += val
        elif key == "cache_misses":
            self.cache_misses += val

    @property
    def compiled(self):
        """Did any program construction happen inside the block?"""
        return (self.trace_seconds > 0.0 or self.compile_seconds > 0.0
                or self.cache_hits > 0 or self.cache_misses > 0)

    @property
    def backend_compiles(self):
        """Backend (XLA) compiles the block actually paid — THE
        zero-extra-compiles proof quantity (warm service leases,
        autotune table-hit rebuilds): the cache-miss count when cache
        counters were observed, else inferred from any nonzero
        backend-compile span (a backend without cache telemetry)."""
        if self.cache_hits or self.cache_misses:
            return int(self.cache_misses)
        return 1 if self.compile_seconds > 0 else 0

    def __enter__(self):
        _install_jax_listeners()
        _watcher_stack().append(self)
        return self

    def __exit__(self, *exc):
        try:
            _watcher_stack().remove(self)
        except ValueError:
            pass


# ---------------------------------------------------------------------------
# program fingerprints
# ---------------------------------------------------------------------------

_versions_cache = None


def runtime_versions():
    """The compiler-stack versions that invalidate cached/AOT programs:
    a jax/jaxlib (or libtpu) bump must never silently load a stale
    executable, so these are baked into every program fingerprint and
    warm-start artifact. One definition, shared with the perf report's
    environment fingerprint (``obs.ledger.runtime_versions``).
    (Memoized — ``importlib.metadata`` scans dist-info, and
    fingerprints are computed per observed compile.)"""
    global _versions_cache
    if _versions_cache is None:
        from pystella_tpu.obs import ledger as _ledger
        _versions_cache = _ledger.runtime_versions()
    return dict(_versions_cache)


def _leaf_signature(leaf):
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    sig = [list(shape) if shape is not None else None,
           str(dtype) if dtype is not None else type(leaf).__name__]
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            sig.append(str(sharding.spec))
            mesh = getattr(sharding, "mesh", None)
            if mesh is not None:
                sig.append([list(mesh.shape.values()),
                            list(mesh.shape.keys()),
                            str(getattr(mesh.devices.flat[0],
                                        "device_kind", ""))])
        except Exception:
            pass
    return sig


def fingerprint_components(label="", args=None, kwargs=None):
    """The JSON-safe identity a program fingerprint hashes: label,
    per-leaf shape/dtype/sharding/mesh signature, compiler-stack
    versions (:func:`runtime_versions`), and the scheduler-relevant
    flag fingerprint (``parallel.overlap.flags_fingerprint`` — the
    same flags the perf-report environment records, because they change
    the compiled schedule)."""
    from pystella_tpu.parallel.overlap import flags_fingerprint
    leaves = []
    if args is not None or kwargs is not None:
        leaves = [_leaf_signature(leaf) for leaf in
                  jax.tree_util.tree_leaves((args or (), kwargs or {}))]
    return {"label": str(label),
            "avals": leaves,
            "versions": runtime_versions(),
            "flags": flags_fingerprint()}


def _digest(components):
    blob = json.dumps(components, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def signature_fingerprint(label="", args=None, kwargs=None):
    """Cheap fingerprint from the call signature only (no re-lowering:
    safe to compute on a hot dispatch path). Returns
    ``(digest, components)``."""
    comp = fingerprint_components(label, args, kwargs)
    return _digest(comp), comp


def program_fingerprint(lowered=None, *, label="", args=None,
                        kwargs=None, text=None):
    """Full program fingerprint: the signature components plus a
    sha256 of the lowered StableHLO module (``lowered.as_text()`` or an
    explicit ``text``). Two programs share a fingerprint exactly when
    the compiler would rebuild the same executable for them — the key
    warm-start artifacts and the compile ledger are indexed by.
    Returns ``(digest, components)``."""
    comp = fingerprint_components(label, args, kwargs)
    if text is None and lowered is not None:
        text = lowered.as_text()
    if text is not None:
        comp["module_sha256"] = hashlib.sha256(
            text.encode() if isinstance(text, str) else text).hexdigest()
    return _digest(comp), comp


# ---------------------------------------------------------------------------
# compile records + the AOT path
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CompileRecord:
    """One computation's compile cost and memory footprint (byte fields
    are ``None`` when the backend provides no memory analysis).

    ``trace_seconds`` is Python-side program construction (jaxpr trace
    + StableHLO lowering — the cost an AOT warm start skips);
    ``compile_seconds`` is the XLA backend-compile span (the cost the
    persistent compilation cache collapses — on a cache HIT the span
    still ticks for retrieval + executable deserialization, so judge
    "did it compile?" by ``cache_hit``, not by seconds alone). Older
    events carried the two lumped into ``compile_seconds``; consumers
    treat a missing ``trace_seconds`` as 0."""

    label: str
    compile_seconds: float
    trace_seconds: float = 0.0
    #: MLIR text serialization for the fingerprint/donation scan —
    #: measurement overhead kept OUT of both spans above, but visible
    #: here so large-module hashing cost cannot hide
    serialize_seconds: float = 0.0
    fingerprint: str | None = None
    fingerprint_kind: str | None = None
    cache_hits: int = 0
    cache_misses: int = 0
    argument_bytes: int | None = None
    output_bytes: int | None = None
    temp_bytes: int | None = None
    alias_bytes: int | None = None
    generated_code_bytes: int | None = None

    @property
    def total_seconds(self):
        """Trace + backend compile: the whole cost of getting this
        program from Python to an executable."""
        return self.trace_seconds + self.compile_seconds

    @property
    def cache_hit(self):
        """Did the persistent cache serve this compile? (``None`` when
        the cache saw no request — cache disabled or nothing reached
        the backend.)"""
        if self.cache_hits == 0 and self.cache_misses == 0:
            return None
        return self.cache_misses == 0

    @property
    def peak_bytes(self):
        """Static peak-HBM estimate: arguments + outputs + temporaries
        (aliased/donated bytes discounted — they reuse input buffers)."""
        parts = [self.argument_bytes, self.output_bytes, self.temp_bytes]
        if all(p is None for p in parts):
            return None
        total = sum(p or 0 for p in parts)
        return total - (self.alias_bytes or 0)

    def asdict(self):
        d = dataclasses.asdict(self)
        d["peak_bytes"] = self.peak_bytes
        d["total_seconds"] = self.total_seconds
        d["cache_hit"] = self.cache_hit
        return d


def _memory_analysis(compiled):
    """``compiled.memory_analysis()`` as a plain field dict (empty when
    the backend returns nothing or the query itself raises)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    fields = {"argument_bytes": "argument_size_in_bytes",
              "output_bytes": "output_size_in_bytes",
              "temp_bytes": "temp_size_in_bytes",
              "alias_bytes": "alias_size_in_bytes",
              "generated_code_bytes": "generated_code_size_in_bytes"}
    return {k: int(getattr(ma, attr)) for k, attr in fields.items()
            if hasattr(ma, attr)}


def _record_compile_metrics(rec):
    _metrics.counter("compiles").inc()
    _metrics.timer("compile_s").observe(rec.compile_seconds)
    _metrics.timer("trace_s").observe(rec.trace_seconds)
    if rec.cache_hits:
        _metrics.counter("compile_cache_hits").inc(rec.cache_hits)
    if rec.cache_misses:
        _metrics.counter("compile_cache_misses").inc(rec.cache_misses)


def compile_with_report(fn, *args, label=None, log=None, step=None,
                        fingerprint=True, **kwargs):
    """AOT-compile ``fn(*args, **kwargs)`` and report the cost.

    :arg fn: a jitted callable (``jax.jit`` result — fused steppers'
        ``_jit_step`` qualifies) or a plain function (jitted here).
    :arg fingerprint: compute the full lowered-module fingerprint
        (default; pass ``False`` to skip hashing a very large module).
    :returns: ``(compiled, record)`` — the executable (call it directly
        to avoid a second compile) and the :class:`CompileRecord`.

    The record splits ``trace_seconds`` (the ``lower()`` wall time:
    jaxpr tracing + StableHLO lowering, pure Python-side cost) from
    ``compile_seconds`` (the ``compile()`` wall time: XLA's backend
    compile, which the persistent cache can satisfy — the record's
    ``cache_hits``/``cache_misses`` say whether it did).

    Side effects: a ``kind="compile"`` event on ``log`` (default: the
    process event log), a ``compiles`` counter increment, and
    ``compile_s``/``trace_s`` timer observations in the default metrics
    registry.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    label = label or getattr(fn, "__name__", None) or repr(fn)
    _install_jax_listeners()
    with compile_watch(label) as w:
        t0 = time.perf_counter()
        lowered = jitted.lower(*args, **kwargs)
        t1 = time.perf_counter()
        # MLIR serialization is Python-side measurement overhead —
        # keep it out of BOTH reported spans (a cache-hit
        # compile_seconds must show retrieval cost, not as_text()),
        # and skip it entirely unless the fingerprint or the donation
        # check below actually needs the text
        text = None
        if fingerprint or (_cache_configured()
                           and not cache_donation_safe()):
            text = lowered.as_text()
        # a DONATED program must not be served from a deserialized
        # cache entry on backends where that corrupts repeat calls
        # (cache_donation_safe docstring): compile it fresh instead
        donated = (text is not None
                   and any(m in text for m in _DONATION_MARKERS))
        bypass = should_bypass_cache(donated)
        tc = time.perf_counter()
        if bypass:
            with cache_bypass():
                compiled = lowered.compile()
        else:
            compiled = lowered.compile()
        t2 = time.perf_counter()
    fp = kind = None
    if fingerprint:
        fp, _ = program_fingerprint(text=text, label=label, args=args,
                                    kwargs=kwargs)
        kind = "lowered"
    rec = CompileRecord(label=label, trace_seconds=t1 - t0,
                        compile_seconds=t2 - tc,
                        serialize_seconds=tc - t1,
                        fingerprint=fp, fingerprint_kind=kind,
                        cache_hits=w.cache_hits,
                        cache_misses=w.cache_misses,
                        **_memory_analysis(compiled))
    _record_compile_metrics(rec)
    (log if log is not None else _events.get_log()).emit(
        "compile", step=step, source="aot",
        **(dict(cache_bypass="donation-unsafe-backend") if bypass
           else {}),
        **rec.asdict())
    return compiled, rec


# ---------------------------------------------------------------------------
# dispatch-path instrumentation
# ---------------------------------------------------------------------------

#: dispatch-path trace activity below this is not worth an event (tiny
#: helper jits re-traced inline inside an enclosing trace)
MIN_EVENT_TRACE_S = 0.005


class InstrumentedJit:
    """A thin proxy over a ``jax.jit`` object that attributes compiles
    triggered by dispatch to ``label`` and reports them as ``compile``
    events (``source="dispatch"``, signature fingerprint — no
    re-lowering is ever forced on the dispatch path). Steady-state
    calls pay one :class:`compile_watch` push/pop (~1 us); everything
    else (``lower``, attribute access) passes through, so the lint
    tier's ``.lower()`` audits and ``functools`` interop keep working.
    """

    __slots__ = ("_jitted", "_label", "_donated")

    def __init__(self, jitted, label, donated=False):
        self._jitted = jitted
        self._label = label
        self._donated = bool(donated)

    def _bypass_cache(self):
        """Donated program dispatched while the persistent cache is
        wired on a donation-unsafe backend: any compile this call
        triggers (first dispatch OR a later re-specialization — e.g. a
        ``static_argnums`` stage index) must be fresh, so the whole
        call runs under :class:`cache_bypass` (see
        :func:`cache_donation_safe`). ~7 us per call, and only in
        that specific configuration; undonated jits and safe backends
        pay one bool."""
        return should_bypass_cache(self._donated)

    def __call__(self, *args, **kwargs):
        with compile_watch(self._label) as w:
            if self._bypass_cache():
                with cache_bypass(watch=w):
                    out = self._jitted(*args, **kwargs)
            else:
                out = self._jitted(*args, **kwargs)
        if (w.compile_seconds > 0.0 or w.cache_hits or w.cache_misses
                or w.trace_seconds >= MIN_EVENT_TRACE_S):
            try:
                rec = CompileRecord(
                    label=self._label, trace_seconds=w.trace_seconds,
                    compile_seconds=w.compile_seconds,
                    fingerprint_kind="signature",
                    cache_hits=w.cache_hits,
                    cache_misses=w.cache_misses)
                _record_compile_metrics(rec)
                # fingerprint hashing only pays off when the event is
                # actually recorded somewhere
                if _events.get_log().enabled:
                    rec.fingerprint, _ = signature_fingerprint(
                        self._label, args, kwargs)
                    _events.emit("compile", source="dispatch",
                                 **rec.asdict())
            except Exception:  # telemetry must never kill a dispatch
                pass
        return out

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._jitted, name)

    def __repr__(self):
        return f"InstrumentedJit({self._label!r}, {self._jitted!r})"


def instrument_jit(jitted, label, donated=False):
    """Wrap a ``jax.jit`` object so its compiles land in the compile
    ledger under ``label``. The package's internal jit sites (steppers,
    fused chunks, multigrid, spectra) all route through this — the
    compile half of cold start stops being invisible. Pass
    ``donated=True`` when the jit donates lattice buffers, so its first
    compile bypasses the persistent cache on backends where a
    cache-served donated executable corrupts
    (:func:`cache_donation_safe`)."""
    return InstrumentedJit(jitted, str(label), donated=donated)


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

#: StableHLO markers of buffer donation (input->output aliasing) — the
#: same attributes the lint tier's donation audit keys on
_DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")

_donation_safe_cache = None


def cache_donation_safe():
    """May a DONATED program be served from a deserialized
    persistent-cache entry on this backend?

    Measured on this container (jax/jaxlib 0.4.37, CPU backend): a
    cache-served executable with donated inputs returns a CORRECT first
    call and progressively corrupted results from the second call on —
    the cold/warm smoke e2e caught the warmed run silently computing
    garbage through all 12 steps (``bench_results/
    cache_donation_repro.py`` is the standalone cross-process repro;
    the corruption is racy but reproduces most runs). Undonated
    programs, and donated programs compiled fresh, are unaffected — so
    on CPU the answer is ``False`` and the drivers dispatch undonated
    twins (a no-op there: XLA:CPU drops donation anyway, realized
    ``alias_bytes`` is 0). TPU is untested on this container; the
    consolidated TPU-window script carries
    :func:`probe_cache_donation_safety` to settle it on hardware.
    """
    global _donation_safe_cache
    if _donation_safe_cache is None:
        try:
            _donation_safe_cache = jax.default_backend() != "cpu"
        except Exception:
            return False
    return _donation_safe_cache


class cache_bypass:
    """Context manager: compile fresh, neither reading nor writing the
    persistent cache (``jax_enable_compilation_cache`` toggled off and
    restored — the flag is not part of the trace context, so no
    retraces are forced). The escape hatch donated compiles take on
    backends where :func:`cache_donation_safe` is ``False``.

    ``watch`` (an active :class:`compile_watch`) lets a dispatch-path
    caller skip the latch reset on exits where nothing compiled inside
    the block — steady-state calls of a donated program then pay only
    the two config toggles, not a cache teardown per step."""

    def __init__(self, watch=None):
        self._watch = watch

    def __enter__(self):
        self._prev = bool(jax.config.jax_enable_compilation_cache)
        jax.config.update("jax_enable_compilation_cache", False)
        return self

    def __exit__(self, *exc):
        jax.config.update("jax_enable_compilation_cache", self._prev)
        if self._prev and (self._watch is None or self._watch.compiled):
            # jax latches cache-enablement at the first compile it
            # inspects; if the bypassed compile was that first one, the
            # latch froze the cache OFF for the task — clear it so
            # later (undonated) compiles still cache
            try:
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception:
                pass


def _cache_configured():
    try:
        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:
        return False


def should_bypass_cache(donated):
    """The donated-compile cache-bypass policy, in ONE place for every
    dispatch site (``compile_with_report``, ``InstrumentedJit``,
    ``warmstart.WarmProgram``): a DONATED program must not have its
    backend compile served from a persistent-cache entry on backends
    where that corrupts repeat calls (:func:`cache_donation_safe`)."""
    return (bool(donated) and _cache_configured()
            and not cache_donation_safe())


def probe_cache_donation_safety(trials=4, calls=3):
    """Empirically probe the cached-donated-executable hazard on the
    LIVE backend (requires :func:`ensure_compilation_cache` first):
    compile a small donated RK-style step (populating the cache), then
    per trial force the backend compile to re-run and be SERVED from
    the persistent cache — ``jax.clear_caches()`` first, because a
    fresh ``jax.jit`` wrapper alone is satisfied by jax's in-memory
    executable caches and never touches the persistent one — and
    compare ``calls`` repeated applications against an undonated
    reference. Returns ``{"triggered", "trials", "mismatched_calls",
    "cache_served_compiles", "populate_cache_served", "valid"}``;
    ``valid`` is ``False`` when no compile was actually cache-served
    (the hazard configuration never arose, so the verdict proves
    nothing).

    The measured CPU corruption only manifests in a process whose
    donated compile is served from a cache populated by an EARLIER
    process (``bench_results/cache_donation_repro.py``) — same-process
    re-serving after ``clear_caches()`` stays clean there. So the
    decisive probe is the one run in a fresh process against an
    already-warm cache: ``populate_cache_served=True`` marks that
    configuration (the TPU-window leg's warm phase), and a first-
    process probe (``populate_cache_served=False``) only covers the
    weaker same-process configuration. The corruption is race-like,
    so a clean *valid* probe is evidence, not proof (hence multiple
    trials). Side effect: ``clear_caches()`` drops every live jit
    executable in the process — run the probe between workloads, not
    inside one. CPU's verdict is already baked into
    :func:`cache_donation_safe`."""
    import numpy as np
    import jax.numpy as jnp

    a_coefs = (0.0, -0.5, -1.2, -0.7, -0.3)
    b_coefs = (0.1, 0.3, 0.8, 0.7, 0.2)

    def step(state, dt):
        y = state
        k = jax.tree_util.tree_map(lambda x: x * 0, state)
        for s in range(5):
            lap = -6.0 * y["f"]
            for ax in (1, 2, 3):
                lap = lap + jnp.roll(y["f"], 1, ax) \
                    + jnp.roll(y["f"], -1, ax)
            r = {"f": y["dfdt"], "dfdt": lap - y["f"]}
            k = jax.tree_util.tree_map(
                lambda kk, rr, s=s: a_coefs[s] * kk + dt * rr, k, r)
            y = jax.tree_util.tree_map(
                lambda yy, kk, s=s: yy + b_coefs[s] * kk, y, k)
        return y

    rng = np.random.default_rng(17)
    host = {n: rng.standard_normal((2, 16, 16, 16)).astype(np.float32)
            for n in ("f", "dfdt")}
    dt = np.float32(0.01)

    def fresh():
        return {k: jax.device_put(v) for k, v in host.items()}

    ref = jax.block_until_ready(jax.jit(step)(fresh(), dt))
    ref = {k: np.asarray(v) for k, v in ref.items()}
    # populate the cache with the donated program's entry — in a FRESH
    # process against an already-warm cache this compile is itself
    # cache-served, which makes that process's probe the faithful
    # cross-process repro (see below)
    with compile_watch("donation_probe_populate") as wp:
        jax.block_until_ready(
            jax.jit(step, donate_argnums=0)(fresh(), dt))
    mismatched = 0
    served_compiles = 0
    for _ in range(int(trials)):
        # drop the in-memory executables so the next dispatch re-runs
        # the backend compile — served (deserialized) from the
        # persistent cache, the exact configuration the hazard needs
        jax.clear_caches()
        served = jax.jit(step, donate_argnums=0)
        with compile_watch("donation_probe") as w:
            out = jax.block_until_ready(served(fresh(), dt))
        served_compiles += w.cache_hits
        for call in range(int(calls)):
            if call:
                out = jax.block_until_ready(served(fresh(), dt))
            if not all(np.array_equal(np.asarray(out[k]), ref[k])
                       for k in ref):
                mismatched += 1
    return {"triggered": mismatched > 0, "trials": int(trials),
            "mismatched_calls": mismatched,
            "cache_served_compiles": int(served_compiles),
            "populate_cache_served": wp.cache_hits > 0,
            "valid": served_compiles > 0}


def ensure_compilation_cache(cache_dir=None, log=None):
    """Point jax's persistent compilation cache at ``cache_dir``
    (default: the registered ``PYSTELLA_COMPILE_CACHE_DIR``). A process
    that re-dials a device then pays each program's XLA backend compile
    once per *cache lifetime*, not once per process — round 3 measured
    ~365 s of multigrid compile at 512^3 that this line amortizes away.

    The compile-time/entry-size floors are zeroed so even fast CPU
    (smoke) compiles populate and hit the cache — the smoke cold/warm
    e2e in CI depends on that, and production TPU compiles clear any
    floor anyway. Values ``""``/``"0"``/``"off"``/``"none"`` disable.

    Returns the absolute cache dir (``None`` when disabled). Emits one
    ``compile_cache`` event recording the wiring.
    """
    if cache_dir is None:
        from pystella_tpu import config as _config
        cache_dir = _config.getenv("PYSTELLA_COMPILE_CACHE_DIR")
    if (cache_dir is None
            or str(cache_dir).strip().lower() in ("", "0", "off", "none")):
        # an explicit "off" must also UN-WIRE a cache set earlier in
        # the process (or inherited via JAX_COMPILATION_CACHE_DIR) —
        # returning None while the cache keeps serving would let a
        # driver report "disabled" over live cache traffic
        try:
            if jax.config.jax_compilation_cache_dir:
                jax.config.update("jax_compilation_cache_dir", None)
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
        except Exception:
            pass
        return None
    cache_dir = str(cache_dir)
    if not os.path.isabs(cache_dir):
        # a relative configured path (the registered default is
        # "bench_results/xla_cache") anchors at the repository root,
        # not the invocation cwd — a warmed rerun from a different
        # directory must find the same cache, and bench.py anchors
        # its bench_results/ the same way
        cache_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), cache_dir)
    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax latches "is the cache enabled for this task" at the FIRST
    # compile; any compile before this call (package import, another
    # test) would freeze the cache off for the whole process — reset
    # the latch so wiring takes effect now
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass
    _install_jax_listeners()
    (log if log is not None else _events.get_log()).emit(
        "compile_cache", dir=cache_dir, enabled=True,
        entries=len(os.listdir(cache_dir)),
        donation_safe=cache_donation_safe())
    return cache_dir


# ---------------------------------------------------------------------------
# device memory
# ---------------------------------------------------------------------------

def device_memory_stats(device=None):
    """Live allocator stats for ``device`` (default: first local device)
    as a dict, or ``None`` where the backend keeps none (CPU)."""
    if device is None:
        device = jax.local_devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def device_memory_report(device=None, label="", step=None, log=None):
    """Record a ``kind="device_memory"`` event with the live HBM numbers
    (and mirror ``peak_bytes_in_use`` into a ``peak_hbm_bytes`` gauge);
    returns the stats dict, or ``None`` (and no event) on stat-less
    backends."""
    stats = device_memory_stats(device)
    if stats is None:
        return None
    keep = {k: stats[k] for k in
            ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_alloc_size") if k in stats}
    if "peak_bytes_in_use" in keep:
        _metrics.gauge("peak_hbm_bytes", reduce="max").set(
            keep["peak_bytes_in_use"])
    (log if log is not None else _events.get_log()).emit(
        "device_memory", step=step, label=label, **keep)
    return stats
