"""Compile-time and device-memory instrumentation.

Two kinds of evidence, both recorded into the event log:

- :func:`compile_with_report` — ahead-of-time compile of a jitted
  computation, timing the compile and extracting XLA's
  ``memory_analysis()`` byte counts (arguments, outputs, temporaries,
  generated code). The peak-HBM estimate is exactly the number that
  would have caught round 5's 183 MB overshoot *before* the allocator
  rejected the 512^3 GW step: ``rec.peak_bytes`` vs the chip's HBM.
- :func:`device_memory_report` — live allocator statistics
  (``Device.memory_stats()``: bytes in use, peak, limit). TPU backends
  populate these; CPU returns ``None`` and the report degrades to a
  no-op instead of raising, so instrumented drivers run everywhere.
"""

from __future__ import annotations

import dataclasses
import time

import jax

from pystella_tpu.obs import events as _events
from pystella_tpu.obs import metrics as _metrics

__all__ = ["CompileRecord", "compile_with_report",
           "device_memory_stats", "device_memory_report"]


@dataclasses.dataclass
class CompileRecord:
    """One computation's compile cost and memory footprint (byte fields
    are ``None`` when the backend provides no memory analysis)."""

    label: str
    compile_seconds: float
    argument_bytes: int | None = None
    output_bytes: int | None = None
    temp_bytes: int | None = None
    alias_bytes: int | None = None
    generated_code_bytes: int | None = None

    @property
    def peak_bytes(self):
        """Static peak-HBM estimate: arguments + outputs + temporaries
        (aliased/donated bytes discounted — they reuse input buffers)."""
        parts = [self.argument_bytes, self.output_bytes, self.temp_bytes]
        if all(p is None for p in parts):
            return None
        total = sum(p or 0 for p in parts)
        return total - (self.alias_bytes or 0)

    def asdict(self):
        d = dataclasses.asdict(self)
        d["peak_bytes"] = self.peak_bytes
        return d


def _memory_analysis(compiled):
    """``compiled.memory_analysis()`` as a plain field dict (empty when
    the backend returns nothing or the query itself raises)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    fields = {"argument_bytes": "argument_size_in_bytes",
              "output_bytes": "output_size_in_bytes",
              "temp_bytes": "temp_size_in_bytes",
              "alias_bytes": "alias_size_in_bytes",
              "generated_code_bytes": "generated_code_size_in_bytes"}
    return {k: int(getattr(ma, attr)) for k, attr in fields.items()
            if hasattr(ma, attr)}


def compile_with_report(fn, *args, label=None, log=None, step=None,
                        **kwargs):
    """AOT-compile ``fn(*args, **kwargs)`` and report the cost.

    :arg fn: a jitted callable (``jax.jit`` result — fused steppers'
        ``_jit_step`` qualifies) or a plain function (jitted here).
    :returns: ``(compiled, record)`` — the executable (call it directly
        to avoid a second compile) and the :class:`CompileRecord`.

    Side effects: a ``kind="compile"`` event on ``log`` (default: the
    process event log), a ``compiles`` counter increment, and a
    ``compile_s`` timer observation in the default metrics registry.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    label = label or getattr(fn, "__name__", None) or repr(fn)
    t0 = time.perf_counter()
    compiled = jitted.lower(*args, **kwargs).compile()
    secs = time.perf_counter() - t0
    rec = CompileRecord(label=label, compile_seconds=secs,
                        **_memory_analysis(compiled))
    _metrics.counter("compiles").inc()
    _metrics.timer("compile_s").observe(secs)
    (log if log is not None else _events.get_log()).emit(
        "compile", step=step, **rec.asdict())
    return compiled, rec


def device_memory_stats(device=None):
    """Live allocator stats for ``device`` (default: first local device)
    as a dict, or ``None`` where the backend keeps none (CPU)."""
    if device is None:
        device = jax.local_devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def device_memory_report(device=None, label="", step=None, log=None):
    """Record a ``kind="device_memory"`` event with the live HBM numbers
    (and mirror ``peak_bytes_in_use`` into a ``peak_hbm_bytes`` gauge);
    returns the stats dict, or ``None`` (and no event) on stat-less
    backends."""
    stats = device_memory_stats(device)
    if stats is None:
        return None
    keep = {k: stats[k] for k in
            ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_alloc_size") if k in stats}
    if "peak_bytes_in_use" in keep:
        _metrics.gauge("peak_hbm_bytes", reduce="max").set(
            keep["peak_bytes_in_use"])
    (log if log is not None else _events.get_log()).emit(
        "device_memory", step=step, label=label, **keep)
    return stats
