"""Lightweight metrics registry: counters, gauges, timers.

Host-side telemetry for driver loops — cheap enough to update every
step, structured enough to aggregate across a multi-host fleet. Three
metric kinds:

- :class:`Counter` — monotonically-increasing event counts (steps
  taken, halo exchanges, V-cycles, compile events). Hosts sum.
- :class:`Gauge` — last-set values (ms/step, site-updates/s, peak HBM
  bytes) with a per-gauge cross-host reduction (``mean``/``max``/
  ``min``/``sum``).
- :class:`Timer` — duration accumulator with an exponential moving
  average; exports ``<name>.count`` / ``<name>.total_s`` (summed across
  hosts) and ``<name>.ema_ms`` (averaged).

:meth:`MetricsRegistry.aggregate` gathers every host's snapshot through
:func:`pystella_tpu.parallel.multihost.all_gather_hosts` and reduces, so
host 0 can report fleet-wide numbers; on a single-process run (tests,
one chip) it degrades to the local snapshot. Counting caveat: counters
incremented inside jit-traced code count *traces*, not executions —
increment from host-level entry points (``step()``, the cycle driver)
for true counts; traced increments are a static proxy only.

Thread-safety contract (the live telemetry endpoint scrapes
:meth:`MetricsRegistry.snapshot` from its own daemon thread while the
serve loop updates): every metric a registry creates shares the
registry's re-entrant lock, each update (``inc``/``set``/``observe``)
is one atomic section under it, and ``snapshot`` holds the same lock
across ALL exports — a scrape can never observe a Timer between its
``count`` bump and its ``total_s`` accumulation, or a half-updated
EMA. A metric constructed standalone gets its own lock.
"""

from __future__ import annotations

import threading
import time

import numpy as np

__all__ = ["Counter", "Gauge", "Timer", "MetricsRegistry",
           "counter", "gauge", "timer", "registry"]

_REDUCERS = {"sum": np.sum, "mean": np.mean, "max": np.max, "min": np.min}


class Counter:
    """Monotonic event count; cross-host reduction: sum."""

    def __init__(self, name, _lock=None):
        self.name = name
        self.value = 0
        self._lock = _lock if _lock is not None else threading.RLock()

    def inc(self, n=1):
        with self._lock:
            self.value += n
            return self.value

    def export(self):
        with self._lock:
            return {self.name: (float(self.value), "sum")}

    def export_typed(self):
        with self._lock:
            return {self.name: (float(self.value), "counter")}


class Gauge:
    """Last-set value; cross-host reduction per ``reduce``."""

    def __init__(self, name, reduce="mean", _lock=None):
        if reduce not in _REDUCERS:
            raise ValueError(f"unknown reduction {reduce!r}; "
                             f"choose from {sorted(_REDUCERS)}")
        self.name = name
        self.reduce = reduce
        self.value = float("nan")
        self._lock = _lock if _lock is not None else threading.RLock()

    def set(self, value):
        with self._lock:
            self.value = float(value)
            return self.value

    def export(self):
        with self._lock:
            return {self.name: (self.value, self.reduce)}

    def export_typed(self):
        with self._lock:
            return {self.name: (self.value, "gauge")}


class Timer:
    """Duration accumulator with an EMA of the per-call milliseconds.

    Use as a context manager (``with registry.timer("halo"): ...``) or
    feed observed seconds via :meth:`observe`.
    """

    def __init__(self, name, ema_alpha=0.2, _lock=None):
        self.name = name
        self.ema_alpha = float(ema_alpha)
        self.count = 0
        self.total_s = 0.0
        self.ema_ms = float("nan")
        self._lock = _lock if _lock is not None else threading.RLock()

    def observe(self, seconds):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            ms = seconds * 1e3
            self.ema_ms = (ms if self.count == 1 else
                           self.ema_alpha * ms
                           + (1.0 - self.ema_alpha) * self.ema_ms)
            return self.ema_ms

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.observe(time.perf_counter() - self._t0)

    def export(self):
        with self._lock:
            return {f"{self.name}.count": (float(self.count), "sum"),
                    f"{self.name}.total_s": (self.total_s, "sum"),
                    f"{self.name}.ema_ms": (self.ema_ms, "mean")}

    def export_typed(self):
        with self._lock:
            return {f"{self.name}.count": (float(self.count), "counter"),
                    f"{self.name}.total_s": (self.total_s, "counter"),
                    f"{self.name}.ema_ms": (self.ema_ms, "gauge")}


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors and multihost
    aggregation. Metric accessors are idempotent (the same name returns
    the same object), so hot-loop call sites need no setup phase."""

    def __init__(self):
        self._metrics = {}
        # re-entrant: _exports holds it while each metric's export()
        # re-enters; metrics created here share it so an update and a
        # snapshot serialize against each other (module docstring)
        self._lock = threading.RLock()

    def _get(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name):
        return self._get(name, lambda: Counter(name, _lock=self._lock),
                         Counter)

    def gauge(self, name, reduce="mean"):
        return self._get(name,
                         lambda: Gauge(name, reduce, _lock=self._lock),
                         Gauge)

    def timer(self, name, ema_alpha=0.2):
        return self._get(name,
                         lambda: Timer(name, ema_alpha,
                                       _lock=self._lock),
                         Timer)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    # -- snapshots and aggregation ----------------------------------------

    def _exports(self, typed=False):
        """Sorted flat exports ``{key: (value, reduce_op)}`` — sorted so
        every host's snapshot vector lines up positionally for the
        cross-host gather (all hosts must register the same metrics,
        which lockstep SPMD drivers do by construction). Held under the
        registry lock end to end, so the whole vector is one consistent
        cut even while another thread updates (the scrape-vs-serve-loop
        race the live endpoint's thread-safety pin covers)."""
        with self._lock:
            flat = {}
            for m in self._metrics.values():
                flat.update(m.export_typed() if typed else m.export())
        return dict(sorted(flat.items()))

    def snapshot(self):
        """Local values as ``{name: float}`` (sorted by name); one
        consistent cut under the registry lock (module docstring)."""
        return {k: v for k, (v, _) in self._exports().items()}

    def snapshot_typed(self):
        """Local values as ``{name: (float, prom_kind)}`` where
        ``prom_kind`` is the Prometheus exposition type (``counter`` /
        ``gauge``) — what :func:`pystella_tpu.obs.live.
        render_prometheus` renders. Same consistency guarantee as
        :meth:`snapshot`."""
        return self._exports(typed=True)

    def reduce_snapshots(self, snapshots):
        """Reduce a sequence of per-host ``{name: value}`` snapshots
        into one fleet-wide dict using each metric's reduction. Exposed
        separately from :meth:`aggregate` so the reduction semantics are
        testable without a multi-host cluster.

        NaN entries are dropped before reducing: gauges are deliberately
        pre-registered at NaN on every host (so the snapshot vectors
        line up) and hosts cross their report cadences at different wall
        times — one not-yet-reported host must not turn the fleet-wide
        mean into NaN. A metric no host has set yet stays NaN."""
        ops = {k: op for k, (_, op) in self._exports().items()}
        out = {}
        for k in ops:
            vals = [s[k] for s in snapshots if k in s]
            finite = [v for v in vals if not np.isnan(v)]
            if finite:
                out[k] = float(_REDUCERS[ops[k]](finite))
            elif vals:
                out[k] = float("nan")
        return out

    def aggregate(self):
        """Fleet-wide reduced values: gathers every host's snapshot via
        :func:`~pystella_tpu.parallel.multihost.all_gather_hosts` and
        applies each metric's reduction; identical to :meth:`snapshot`
        on a single-process run."""
        from pystella_tpu.parallel.multihost import all_gather_hosts
        snap = self.snapshot()
        names = list(snap)
        stacked = all_gather_hosts(np.array([snap[n] for n in names]
                                            or [0.0]))
        if not names:
            return {}
        return self.reduce_snapshots(
            [dict(zip(names, row)) for row in stacked])


#: process-default registry (what the in-tree instrumentation uses)
_default = MetricsRegistry()


def registry():
    """The process-default :class:`MetricsRegistry`."""
    return _default


def counter(name):
    return _default.counter(name)


def gauge(name, reduce="mean"):
    return _default.gauge(name, reduce)


def timer(name, ema_alpha=0.2):
    return _default.timer(name, ema_alpha)
