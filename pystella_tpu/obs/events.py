"""Structured JSONL run-event log.

Every event is one JSON object per line, appended and flushed
immediately so a killed run keeps everything emitted before the kill
(the property that saved round 4's bench record; ``bench.py``'s line
cache pioneered the pattern). Schema (version 2):

===========  ======================================================
key          meaning
===========  ======================================================
``v``        schema version (``2``)
``ts``       wall-clock POSIX seconds (cross-host correlation)
``mono``     ``time.monotonic()`` seconds (robust to clock steps;
             durations within one process difference correctly)
``host``     jax process index (``0`` outside a jax process)
``kind``     event kind, a short snake_case string (``"compile"``,
             ``"diverged"``, ``"checkpoint_save"``, ``"mg_cycle"``,
             ``"bench_metric"``, ``"fault_detected"``, ...). Payload
             keys must not shadow this schema's own field names —
             e.g. the resilience events carry ``fault_kind``, not
             ``kind``. Every kind the package emits is registered in
             :func:`registered_event_kinds` (the source lint's
             ``event-registry`` check enforces it, the way the scope
             registry gates trace-scope literals)
``step``     simulation step number, or ``null``
``trace``    request-scoped trace id (v2, OPTIONAL — present only
             when a :func:`tracing` context was active at emit time;
             absent fields must be tolerated so v1 logs still ingest)
``span``     the causal span this event belongs to (v2, optional)
``parent``   the span's parent span id (v2, optional)
``data``     kind-specific payload (flat, JSON-safe)
===========  ======================================================

The v2 ``trace``/``span``/``parent`` fields are the distributed-tracing
layer: a trace id is allocated per
:class:`~pystella_tpu.service.ScenarioRequest` and propagated through
scheduler, admission, lease dispatch, the supervisor's chunk loop,
checkpoint barriers, recovery, and retire — the
:class:`~pystella_tpu.obs.spans.SpanAssembler` reconstructs per-request
span trees and critical-path latency from exactly these fields. They
ride an ambient thread-local context (:func:`tracing`), so existing
``emit()`` call sites gain them without signature changes, and code
emitting outside any context produces records indistinguishable from
v1 apart from the version number.

This module is importable without jax (the ``bench.py`` orchestrator
process never touches jax by design); the host id is resolved lazily
from an already-imported jax only.

Usage::

    from pystella_tpu import obs
    obs.configure("run_events.jsonl")       # or env PYSTELLA_EVENT_LOG
    obs.emit("checkpoint_save", step=1200, path="ckpts/1200")
    with obs.events.tracing(trace=tid, span=sid):
        obs.emit("service_dispatch", ...)   # carries trace/span/parent
    ...
    for ev in obs.read_events("run_events.jsonl"):
        ...

With no configured path (and no ``PYSTELLA_EVENT_LOG``) the default log
is a disabled sink and :func:`emit` costs one attribute check.
"""

from __future__ import annotations

import contextlib
import json
import os
import secrets
import sys
import threading
import time

__all__ = ["EventLog", "configure", "current_trace", "emit", "get_log",
           "new_span_id", "new_trace_id", "read_events",
           "register_event_kind", "registered_event_kinds",
           "rotated_family", "tracing", "SCHEMA_VERSION"]

SCHEMA_VERSION = 2


# ---------------------------------------------------------------------------
# trace context: the request-scoped causal-span layer (schema v2)
# ---------------------------------------------------------------------------

def new_trace_id():
    """A fresh 16-hex-char trace id (one per request lifecycle; a
    preempted-and-requeued request KEEPS its trace id across leases)."""
    return secrets.token_hex(8)


def new_span_id():
    """A fresh 8-hex-char span id (one per causal span: the request
    root, each lease, each recovery incident)."""
    return secrets.token_hex(4)


_trace_tls = threading.local()


def current_trace():
    """The innermost active :func:`tracing` context as a dict
    (``trace``/``span``/``parent``), or ``None``."""
    stack = getattr(_trace_tls, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def tracing(trace=None, span=None, parent=None):
    """Attach trace/span/parent fields to every event emitted inside
    (this thread only; telemetry from helper threads degrades to
    context-less v1-shaped records rather than mis-attributing).

    Fields not given inherit from the enclosing context, with one
    causal rule: opening a NEW span (``span=`` given, ``parent=`` not)
    records the enclosing span as its parent — so nesting
    ``tracing(trace=T, span=ROOT)`` → ``tracing(span=LEASE)`` emits
    lease-scoped events carrying ``parent=ROOT`` without the inner
    site knowing the outer ids."""
    outer = current_trace() or {}
    ctx = {
        "trace": trace if trace is not None else outer.get("trace"),
        "span": span if span is not None else outer.get("span"),
        "parent": parent if parent is not None else (
            outer.get("span") if span is not None
            and span != outer.get("span")
            else outer.get("parent")),
    }
    stack = getattr(_trace_tls, "stack", None)
    if stack is None:
        stack = _trace_tls.stack = []
    stack.append(ctx)
    try:
        yield ctx
    finally:
        stack.pop()


# ---------------------------------------------------------------------------
# event-kind registry: the emit vocabulary, centrally declared
# ---------------------------------------------------------------------------

#: kind -> one-line description; seeded below with the in-tree
#: vocabulary. The source lint's ``event-registry`` check audits every
#: ``emit("<literal>", ...)`` in the package against this registry
#: (same pattern as ``obs.scope.register_scope``), so the span
#: assembler's kind vocabulary cannot silently drift from emit sites.
_KIND_REGISTRY = {}


def register_event_kind(name, help=""):
    """Register an event kind (idempotent; returns ``name``). Call this
    for any new ``emit("<kind>", ...)`` literal — the tier-1 lint
    (``event-registry``) fails on unregistered kinds, exactly as the
    scope registry gates trace-scope literals."""
    _KIND_REGISTRY.setdefault(str(name), str(help))
    return name


def registered_event_kinds():
    """The registered kind vocabulary as a ``{name: description}``
    dict (copy)."""
    return dict(_KIND_REGISTRY)


for _name, _help in (
    # -- core telemetry (obs) -----------------------------------------------
    ("step_time", "one step's wall time in ms (StepTimer emit_steps)"),
    ("step_timer", "StepTimer window report (ms_per_step, steps_per_s)"),
    ("compile", "one observed program compile (trace/compile split, "
                "fingerprint, cache and memory_analysis counters)"),
    ("compile_cache", "persistent XLA compilation cache wired"),
    ("device_memory", "live allocator stats (TPU backends)"),
    ("cold_start", "driver time-to-first-step phase breakdown"),
    ("warmstart_export", "AOT artifact serialized to the store"),
    ("warmstart_load", "AOT artifact loaded (fingerprint matched)"),
    ("warmstart_mismatch", "AOT artifact refused (stale fingerprint)"),
    ("warmstart_gc", "stale AOT artifacts collected"),
    ("trace_summary", "per-scope duration table from a Perfetto capture"),
    ("trace_missing", "a profiler capture produced no trace file"),
    ("service_trace", "assembled service span timeline exported "
                      "(Perfetto-loadable, obs.spans)"),
    ("health", "one decoded sentinel health vector"),
    ("diverged", "sentinel trip (non-finite fields / bound violation)"),
    ("forensic_bundle", "a sentinel trip wrote a forensic bundle"),
    ("forensic_failed", "a forensic bundle failed to write"),
    ("perf_report", "a PerfLedger wrote perf_report.json"),
    ("gate_verdict", "the perf gate ran (ok, exit_code, reasons)"),
    # -- numerics / solver hot paths ----------------------------------------
    ("mg_cycle", "one multigrid cycle (depth, smooths, errors)"),
    ("assemble_fallback", "explicit assemble='update' fell back to the "
                          "resident kernel tier"),
    # -- fused kernel tiers + the persistent autotuner (ops.autotune) -------
    ("block_choice", "a fused kernel build chose its blocking "
                     "(bx/by/win_halo + source: autotune table hit, "
                     "choose_blocks heuristic, env override, explicit)"),
    ("kernel_fallback", "a fused kernel tier degraded down the ladder "
                        "(chunk -> pair -> single), with the reason"),
    ("kernel_tier", "the kernel tier a fused stepper actually "
                    "dispatched (resident-chunk/streaming-chunk/pair/"
                    "single/xla) + modeled HBM bytes per step"),
    ("autotune_record", "a sweep winner persisted to the per-device "
                        "autotune table"),
    ("autotune_mismatch", "an autotune-table entry was refused "
                          "(version/flag-stale or corrupt table)"),
    ("autotune_gc", "stale autotune entries collected"),
    ("autotune_sweep", "one autotune sweep's totals (winner + "
                       "candidate count)"),
    ("autotune_warm_build", "a table-hit stepper rebuild dispatched "
                            "with its compile-watch record — "
                            "backend_compiles == 0 is the "
                            "zero-extra-compiles proof"),
    # -- checkpoints (utils.checkpoint) -------------------------------------
    ("checkpoint_save", "async checkpoint write SCHEDULED (not durable)"),
    ("checkpoint_durable", "durability barrier passed; last_good advanced"),
    ("checkpoint_restore", "a checkpoint was restored"),
    ("checkpoint_fallback", "restore walked back past a torn checkpoint"),
    # -- elastic runtime (resilience) ---------------------------------------
    ("fault_injected", "the fault harness fired a scripted fault"),
    ("fault_detected", "the supervisor detected a fault (triage result)"),
    ("recovery_attempt", "one recovery attempt (re-dial + restore)"),
    ("recovery_failed", "recovery gave up (budget / recurrence)"),
    ("run_resumed", "the run resumed (recovery MTTR or restart)"),
    ("run_degraded", "the run re-meshed to surviving devices"),
    ("run_preempted", "SIGTERM/preemption drain to a durable checkpoint"),
    ("supervisor_start", "a supervised run began"),
    ("supervisor_done", "supervised-run lifecycle totals"),
    ("remesh_plan", "one re-mesh decision record (RemeshPlanner)"),
    ("retry_wait", "one jittered backoff sleep (Retrier)"),
    ("retry_stop", "the retrier stopped (reason)"),
    # -- ensemble tier ------------------------------------------------------
    ("ensemble_run", "ensemble-driver queue grouping"),
    ("ensemble_chunk", "one batched dispatch window"),
    ("ensemble_done", "ensemble batch totals (member-steps/s, occupancy)"),
    ("ensemble_health", "per-chunk health-matrix summary"),
    ("member_started", "a batch slot was armed with a scenario job"),
    ("member_finished", "a member retired at its step budget"),
    ("member_evicted", "a member was evicted by the per-member sentinel"),
    ("member_preempted", "a driver drain captured a member as a requeue "
                         "record"),
    # -- scenario service ---------------------------------------------------
    ("service_start", "scenario-service serve loop began (policy config)"),
    ("service_done", "scenario-service serve totals"),
    ("service_request", "one request entered ingestion (traced root)"),
    ("service_admit", "admission verdict (warm/cold, fingerprint)"),
    ("service_reject", "typed rejection (quota / cold_signature)"),
    ("service_arm", "a warm-pool entry was armed (compile paid here)"),
    ("service_dispatch", "a request entered a lease (queue latency)"),
    ("service_lease", "a lease finished or drained (TTFS, compile watch)"),
    ("service_preempted", "a lease drained for a higher priority class"),
    ("service_requeue", "an unfinished request re-entered the queue with "
                        "its restored state"),
    ("service_lease_failed", "a lease's supervision gave up; requests "
                             "requeued"),
    ("member_result", "one retired member's streamed analytics + "
                      "deadline margin"),
    ("deadline_missed", "a deadlined request retired after its deadline "
                        "(margin_s < 0)"),
    ("service_loadgen", "the synthetic-mix summary"),
    # -- live operations plane (obs.live / obs.slo) -------------------------
    ("live_serve", "the in-process telemetry endpoint came up "
                   "(port, endpoints)"),
    ("slo_alert", "a rolling-window SLO burn-rate alert FIRED "
                  "(obs.slo.SLOMonitor; leg, windowed value, bar)"),
    ("slo_resolved", "a burning SLO leg recovered below its bar "
                     "(duration_s since the matching slo_alert)"),
    ("obs_subscriber_error", "an EventLog emit subscriber raised; the "
                             "emit path degraded it to this one-time "
                             "event instead of breaking"),
    # -- continuous-performance plane (obs.perf / obs.stragglers) -----------
    ("perf_digest", "one signature's step-time digest window report "
                    "(p50/p95/p99 ms + straggler attribution)"),
    ("perf_anomaly", "the CUSUM change-point detector fired on a "
                     "sustained step-time shift (signature, baseline, "
                     "straggler attribution)"),
    ("perf_recovered", "an anomalous signature's step times returned "
                       "to the baseline band (duration_s since the "
                       "matching perf_anomaly)"),
    ("perf_capture", "an anomaly-triggered flight-recorder profiler "
                     "capture closed (Perfetto artifact path, "
                     "rate-limit suppression count)"),
    ("perf_loadgen", "the seeded continuous-performance drill summary "
                     "(service.loadgen.run_perf)"),
    # -- fleet observability plane (service.registry / obs.fleet) -----------
    ("fleet_announce", "a serving replica published its registry record "
                       "(replica id, url, stack fingerprint)"),
    ("fleet_withdraw", "a replica withdrew its registry record cleanly "
                       "(tombstone written, heartbeats stopped)"),
    ("fleet_scrape", "one fleet aggregation pass: per-replica scrape "
                     "outcomes, merged fleet SLO legs, skew/divergence"),
    ("fleet_replica_lost", "a previously-live replica went dark without "
                           "withdrawing (heartbeat expired or endpoint "
                           "unreachable)"),
    ("fleet_alert", "a fleet-level SLO burn-rate alert FIRED "
                    "(obs.fleet.FleetAggregator; leg, value, bar)"),
    ("fleet_resolved", "a burning fleet SLO leg recovered below its "
                       "bar (duration_s since the matching "
                       "fleet_alert)"),
    ("fleet_loadgen", "the two-replica fleet drill summary "
                      "(service.loadgen.run_fleet)"),
    # -- capacity & goodput plane (obs.capacity) ----------------------------
    ("capacity_footprint", "a program's predicted HBM footprint "
                           "recorded (fingerprint, bytes, source: "
                           "memory_analysis or aval_estimate)"),
    ("capacity_stale", "a persisted footprint was refused "
                       "(version/flag drift — the warmstart staleness "
                       "rule) or none existed"),
    ("capacity_watermark", "one per-chunk live allocator sample "
                           "(bytes_in_use / peak_bytes_in_use / "
                           "headroom fraction)"),
    ("capacity_reject", "memory-aware admission refused a request: "
                        "resident + predicted footprint exceeded "
                        "capacity x headroom (CapacityExceeded)"),
    ("capacity_evict", "the evict admission policy dropped an idle "
                       "warm-pool entry to make room for a candidate "
                       "lease"),
    ("capacity_oom", "a RESOURCE_EXHAUSTED lease failure wrote an OOM "
                     "forensic bundle (footprint table, watermark "
                     "series, the admitting decision)"),
    ("capacity_account", "one request's retire-time chip-second "
                         "account (phases x chip share, committed "
                         "steps, waste, goodput)"),
    ("capacity_usage", "the serve loop's capacity/goodput rollup "
                       "(per-tenant chargeback table, reconciliation, "
                       "watermark coverage)"),
    # -- driver-side kinds (bench.py / examples; outside the package, so
    # -- not lint-audited, but registered so the vocabulary is one list)
    ("bench_run", "bench payload run metadata"),
    ("bench_metric", "one bench headline metric line"),
    ("run_start", "example-driver run began"),
    ("run_complete", "example-driver run completed"),
    ("run_aborted", "example-driver run died (forensic tail)"),
    ("halo_traffic", "per-device ICI bytes per overlapped halo update"),
    ("spectra_time", "one spectra output's wall time"),
    ("fft_spectra", "a driver's sharded-spectra leg totals"),
    ("lint", "the static-analysis verdict of the run"),
    ("smoke_supervised_failed", "smoke: supervised payload failed"),
    ("smoke_autotune_failed", "smoke: fused-tier/autotune payload "
                              "failed its pins"),
    ("smoke_remesh_failed", "smoke: remesh drill failed"),
    ("smoke_service_failed", "smoke: service payload failed"),
    ("smoke_fleet_failed", "smoke: two-replica fleet drill failed"),
    ("smoke_capacity_failed", "smoke: capacity/goodput leg failed its "
                              "pins"),
):
    register_event_kind(_name, _help)
del _name, _help


def _rotated_name(path, index):
    """``run_events.jsonl`` -> ``run_events.<index>.jsonl``."""
    root, ext = os.path.splitext(path)
    return f"{root}.{index}{ext or '.jsonl'}"


def rotated_family(path):
    """Every file of a rotated event log, OLDEST FIRST and the live
    file last: ``[<stem>.0.jsonl, <stem>.1.jsonl, ..., <path>]``
    (missing members are skipped; an un-rotated log is just
    ``[path]``). This is the read-side contract of ``rotate_bytes=``:
    a consumer that wants the whole record reads the family in this
    order and sees one continuous stream."""
    family = []
    index = 0
    while True:
        rotated = _rotated_name(path, index)
        if not os.path.exists(rotated):
            break
        family.append(rotated)
        index += 1
    family.append(path)
    return family


def _host_id():
    """This process's index in the multi-controller cluster. Resolved
    from jax only when jax is already imported — the bench orchestrator
    (and any other jax-free supervisor) must be able to emit events
    without dialing a backend."""
    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        return int(jax.process_index())
    except Exception:
        return 0


def _jsonify(obj):
    """Best-effort JSON coercion for payload values (numpy/jax scalars,
    tuples, paths); unknown types fall back to ``str``."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_jsonify(v) for v in obj]
    if hasattr(obj, "item") and getattr(obj, "ndim", None) in (None, 0):
        try:
            return _jsonify(obj.item())
        except Exception:
            pass
    if hasattr(obj, "tolist"):
        try:
            return _jsonify(obj.tolist())
        except Exception:
            pass
    return str(obj)


class EventLog:
    """Append-only JSONL event sink.

    :arg path: output file (parent directories are created), or ``None``
        for a disabled sink whose :meth:`emit` is a cheap no-op.
    :arg host: override the host id (default: lazy jax process index).
    :arg rotate_bytes: size-triggered rollover for long-lived processes
        (the scenario service runs for days — one unbounded JSONL is an
        operational hazard): when the live file reaches this size after
        a write, it is renamed to the next ``<stem>.<n>.jsonl`` member
        of the rotated family (:func:`rotated_family`) and a fresh file
        is opened at ``path``. Default: the registered
        ``PYSTELLA_EVENT_ROTATE_MB`` (unset disables). Rotation never
        splits a line — whole events only.

    Thread-safe; every line is flushed on write so concurrently-appending
    processes (orchestrator + payload) interleave whole lines.
    """

    def __init__(self, path=None, host=None, rotate_bytes=None):
        self.path = None if path is None else os.path.abspath(str(path))
        self._host = host
        self._lock = threading.Lock()
        self._file = None
        self._warned = False
        self._subscribers = []
        self._subscriber_errored = False
        self._notify_tls = threading.local()
        if rotate_bytes is None:
            # direct read (not config.getenv): this module must stay
            # loadable BY FILE in a jax-free supervisor, where the
            # package import is unavailable
            mb = os.environ.get(
                "PYSTELLA_EVENT_ROTATE_MB")  # env-registry: PYSTELLA_EVENT_ROTATE_MB
            if mb:
                try:
                    rotate_bytes = float(mb) * 2**20
                except ValueError:
                    rotate_bytes = None
        self.rotate_bytes = (int(rotate_bytes)
                             if rotate_bytes else None)
        if self.path is not None:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._file = open(self.path, "a")

    def _maybe_rotate(self):
        """Roll the live file over once it reached ``rotate_bytes``
        (caller holds the lock; the just-written line stays whole in
        the rotated member). Rotation failures degrade to
        keep-appending — telemetry must never kill the run.

        Concurrent appenders (the orchestrator + payload pattern) are
        tolerated via an inode check: when ANOTHER process already
        rotated the live file out from under this one, this writer
        re-points at the fresh live file instead of renaming it away —
        otherwise two writers would leapfrog-rotate each other's fresh
        files. Lines the laggard wrote into the rotated member before
        noticing remain there (whole, just earlier in the family), so
        the family read stays lossless; single-writer logs (the normal
        service deployment) rotate exactly at the threshold."""
        try:
            st_fd = os.fstat(self._file.fileno())
            try:
                st_path = os.stat(self.path)
            except FileNotFoundError:
                st_path = None
            if st_path is None or (st_path.st_ino, st_path.st_dev) \
                    != (st_fd.st_ino, st_fd.st_dev):
                # someone else rotated (or removed) the live file:
                # follow them instead of rotating their fresh file
                self._file.close()
                self._file = open(self.path, "a")
                return
            if st_fd.st_size < self.rotate_bytes:
                return
            index = 0
            while os.path.exists(_rotated_name(self.path, index)):
                index += 1
            self._file.close()
            os.replace(self.path, _rotated_name(self.path, index))
            self._file = open(self.path, "a")
        except OSError as e:
            if not self._warned:
                self._warned = True
                print(f"pystella_tpu.obs: event log rotation failed "
                      f"({e}); continuing on the live file",
                      file=sys.stderr)
            if self._file is None or self._file.closed:
                try:
                    self._file = open(self.path, "a")
                except OSError:
                    self._file = None

    @property
    def enabled(self):
        return self._file is not None

    # -- subscribers: the in-process push channel (live SLO monitors) -------

    def subscribe(self, fn):
        """Register ``fn(record)`` to receive every emitted record
        in-process, immediately after the write — the push channel the
        live SLO monitor (:mod:`pystella_tpu.obs.slo`) rides instead of
        tailing the log file. Subscribers survive size-triggered
        rotation (they hang off the log object, not the file handle)
        but NOT :func:`configure` (which builds a fresh log). A
        subscriber that raises never breaks the emit path: the failure
        degrades to a one-time ``obs_subscriber_error`` event and the
        subscriber stays registered (the fault may be transient).
        Returns ``fn`` so a lambda can be kept for :meth:`unsubscribe`.
        """
        if fn not in self._subscribers:
            self._subscribers.append(fn)
        return fn

    def unsubscribe(self, fn):
        """Remove a subscriber (idempotent)."""
        try:
            self._subscribers.remove(fn)
        except ValueError:
            pass

    def _notify(self, rec):
        """Push ``rec`` to subscribers, outside the write lock (a
        subscriber may itself emit — e.g. the SLO monitor's
        ``slo_alert``) and re-entrancy-guarded per thread: an emit made
        FROM a subscriber callback is written normally but not pushed
        again, so a monitor that emits alerts cannot recurse through
        its own hook."""
        if not self._subscribers:
            return
        if getattr(self._notify_tls, "active", False):
            return
        self._notify_tls.active = True
        try:
            for fn in list(self._subscribers):
                try:
                    fn(rec)
                except Exception as e:  # noqa: BLE001 — never break emit
                    if not self._subscriber_errored:
                        self._subscriber_errored = True
                        print("pystella_tpu.obs: event subscriber "
                              f"{fn!r} raised ({type(e).__name__}: {e});"
                              " telemetry continues without it",
                              file=sys.stderr)
                        self.emit("obs_subscriber_error",
                                  subscriber=repr(fn),
                                  error=f"{type(e).__name__}: {e}")
        finally:
            self._notify_tls.active = False

    def emit(self, kind, step=None, **data):
        """Append one event; returns the record dict (``None`` when
        nothing consumed it: a disabled, subscriber-less sink, or a
        failed write — telemetry is best-effort by design and must
        never kill the instrumented run). The ambient :func:`tracing`
        context, when active on this thread, lands as the v2
        ``trace``/``span``/``parent`` fields. Registered subscribers
        (:meth:`subscribe`) receive the record after the write — also
        on a file-less sink, so a live monitor works without a log."""
        if self._file is None and not self._subscribers:
            # cheap pre-check; file re-read under the lock
            return None
        rec = {"v": SCHEMA_VERSION, "ts": time.time(),
               "mono": time.monotonic(),
               "host": self._host if self._host is not None else _host_id(),
               "kind": str(kind),
               "step": None if step is None else int(step),
               "data": _jsonify(data)}
        ctx = current_trace()
        if ctx:
            for key in ("trace", "span", "parent"):
                if ctx.get(key) is not None:
                    rec[key] = ctx[key]
        written = False
        if self._file is not None:
            line = json.dumps(rec)
            with self._lock:
                f = self._file  # may have been closed/reconfigured since
                if f is not None:
                    try:
                        f.write(line + "\n")
                        f.flush()
                        written = True
                    except (OSError, ValueError) as e:  # ENOSPC, ...
                        if not self._warned:
                            self._warned = True
                            print("pystella_tpu.obs: event log write "
                                  f"failed ({e}); further events may "
                                  "be lost", file=sys.stderr)
                    if written and self.rotate_bytes:
                        self._maybe_rotate()
        self._notify(rec)
        return rec if (written or self._subscribers) else None

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


#: module default: lazily built from ``PYSTELLA_EVENT_LOG`` on first use
_default = None


def get_log():
    """The process-default :class:`EventLog` (disabled sink unless
    :func:`configure` was called or ``PYSTELLA_EVENT_LOG`` is set). An
    unopenable ``PYSTELLA_EVENT_LOG`` path degrades to the disabled sink
    with a stderr warning — implicit env-driven telemetry must never
    kill the instrumented run (an explicit :func:`configure` call still
    raises, so startup misconfiguration surfaces)."""
    global _default
    if _default is None:
        # direct read, not pystella_tpu.config.getenv: this module must
        # stay loadable BY FILE in a jax-free supervisor (bench.py's
        # orchestrator), where no package import is available
        path = os.environ.get(
            "PYSTELLA_EVENT_LOG") or None  # env-registry: PYSTELLA_EVENT_LOG
        try:
            _default = EventLog(path)
        except OSError as e:
            print(f"pystella_tpu.obs: cannot open event log {path!r} "
                  f"({e}); events disabled", file=sys.stderr)
            _default = EventLog(None)
    return _default


def configure(path=None, host=None, rotate_bytes=None):
    """(Re)point the process-default event log at ``path`` (``None``
    disables). Returns the new log; the previous one is closed."""
    global _default
    old, _default = _default, EventLog(path, host=host,
                                       rotate_bytes=rotate_bytes)
    if old is not None:
        old.close()
    return _default


def emit(kind, step=None, **data):
    """Emit on the process-default log (no-op when unconfigured)."""
    return get_log().emit(kind, step=step, **data)


def read_events(path, kind=None, include_rotated=False):
    """Load events from a JSONL file (newest last). Torn trailing lines
    from a killed writer are skipped, like ``bench.py``'s line cache.
    ``kind`` optionally filters. ``include_rotated=True`` reads the
    whole rotated family (:func:`rotated_family`) oldest-first, so a
    size-rotated long-lived log reads as one continuous record — the
    ledger ingests event logs this way."""
    out = []
    paths = rotated_family(path) if include_rotated else [path]
    for member in paths:
        try:
            with open(member) as f:
                for ln in f:
                    if not ln.strip():
                        continue
                    try:
                        rec = json.loads(ln)
                    except ValueError:
                        continue  # torn line
                    if kind is None or rec.get("kind") == kind:
                        out.append(rec)
        except OSError:
            continue
    return out
