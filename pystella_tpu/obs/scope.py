"""Named trace scopes for hot paths, plus the central scope registry.

One context manager, two sinks:

- ``jax.named_scope`` attaches the name to every op traced inside, so
  compiled-code profiles (Perfetto / TensorBoard traces captured with
  :class:`pystella_tpu.trace`) show ``fused_rk_stage_pair`` /
  ``halo_exchange`` / ``pallas_stencil`` regions instead of raw XLA op
  names;
- ``jax.profiler.TraceAnnotation`` marks the host-side timeline, so
  eager driver loops (per-stage protocol, multigrid cycle orchestration)
  show up as named spans in the same trace.

Both are no-ops costing ~a microsecond when no profiler is attached and
are platform-agnostic (the CPU test suite runs them constantly).

The scope names survive into the lowered MLIR's debug locations, which
is how tests verify instrumentation without capturing a real trace:
:func:`lowered_scopes` / :func:`has_scope` parse them back out of a
``jax.jit(...).lower(...)`` result.

**Registry.** Every scope name the package emits must be registered here
(:func:`register_scope`): :data:`pystella_tpu.obs.trace.KNOWN_SCOPES` —
the vocabulary the Perfetto parser folds trace rows into, and therefore
everything the ledger's per-scope tables can ever show — is derived from
:func:`registered_scopes`. A tier-1 test
(``tests/test_scope_registry.py``) greps every ``trace_scope(...)`` /
``named_scope(...)`` literal in ``pystella_tpu/`` against the registry,
so a renamed hot-path scope can no longer silently vanish from
trace/ledger tables: the rename either updates the registry (and the
parser vocabulary with it) or fails CI.

jax is imported lazily inside the functions (not at module import), so
this module stays loadable by file in a jax-free supervisor, like
``obs/events.py``.
"""

from __future__ import annotations

import contextlib
import functools
import re

__all__ = ["trace_scope", "traced", "lowered_scopes", "has_scope",
           "register_scope", "registered_scopes"]


#: the central scope-name registry (see module docstring); seeded below
#: with the in-tree instrumentation vocabulary
_SCOPE_REGISTRY = set()


def register_scope(name):
    """Register a scope name (idempotent; returns ``name``). Call this
    for any new ``trace_scope``/``named_scope`` literal so the Perfetto
    parser (:data:`pystella_tpu.obs.trace.KNOWN_SCOPES`) and the
    ledger's per-scope tables know about it — the tier-1 registry test
    fails on unregistered literals."""
    _SCOPE_REGISTRY.add(str(name))
    return name


def registered_scopes():
    """The registered scope names, as a frozenset."""
    return frozenset(_SCOPE_REGISTRY)


for _name in (
    # generic stepper stages (rk_stage0..N fold into this at parse time)
    "rk_stage",
    # fused Pallas steppers
    "fused_rk_stage", "fused_rk_stage_pair", "fused_rk_stage_energy",
    "fused_coupled_pair",
    # halo exchange: padded path and the overlapped interior/shell split
    "halo_exchange",
    "halo_overlap", "halo_overlap_interior", "halo_overlap_shells",
    # the raw XLA ppermute op rows — device traces carry them with no
    # named-scope path; the ledger's communication-time denominator
    "collective-permute",
    # Pallas kernel dispatch
    "pallas_stencil", "pallas_resident_stencil",
    # the whole-RK-chunk (temporal blocking) kernel dispatch and the
    # persistent autotuner's timed candidate probes (ops.autotune)
    "chunk_stage", "autotune_probe",
    # the sanctioned carry_dtype quantization point (ops.fused): the one
    # scope under which an f32->bf16 narrowing is legal; the dataflow
    # lint tier treats any float downcast OUTSIDE this scope as a
    # POLICY_BF16_ACC32 violation
    "carry_quantize",
    # multigrid
    "mg_cycle", "mg_smooth", "mg_residual",
    # driver-level spans (bench smoke / example loops)
    "bench_step", "driver_step",
    # the in-graph numerics health vector (obs.sentinel)
    "sentinel",
    # the ensemble tier (pystella_tpu.ensemble): the batched member
    # step and the in-graph evict/resample slot write
    "ensemble_step", "ensemble_evict",
    # the elastic runtime (pystella_tpu.resilience): each step taken
    # under Supervisor control — replayed spans after a recovery show
    # up as a second pass over the same step numbers in a trace
    "supervised_step",
    # the sharded pencil-FFT tier (fourier.pencil): per-axis local FFT
    # stages and the all_to_all transposes between them — the ledger's
    # `fft` section derives its exposed-vs-hidden transpose split from
    # these two rows, like the halo rows above
    "fft_stage", "fft_transpose",
    # the RAW XLA op rows of the same two phases — device traces (TPU
    # and the TFRT CPU backend) carry `all-to-all.N` / `fft.N` op rows
    # with no named-scope path; the ledger falls back to them when the
    # scope-path rows are absent (longest-match folding keeps a
    # TPU row like `jit(..)/fft_stage/fft.3` in `fft_stage`, not here)
    "all-to-all", "fft",
    # k-space stencil application through the transform
    # (ops.fft_stencil)
    "fft_stencil",
    # the scenario service's request-scoped span vocabulary
    # (obs.spans): the SpanAssembler exports assembled request
    # timelines as Perfetto complete-span rows under THESE names, so
    # hardware profiler captures and service traces fold through one
    # parser (obs.trace.scope_durations) — the critical-path phases...
    "service_queue_wait", "service_admission", "service_compile",
    "service_chunk_compute", "service_checkpoint_barrier",
    "service_recovery_replay", "service_preempt_drain",
    # ...plus the structural spans they hang off
    "service_request_span", "service_lease_span",
):
    register_scope(_name)
del _name


@contextlib.contextmanager
def trace_scope(name):
    """Name everything inside for both compiled-code traces
    (``jax.named_scope``) and the host timeline
    (``jax.profiler.TraceAnnotation``)."""
    import jax
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def traced(name=None):
    """Decorator form of :func:`trace_scope` (defaults to the function's
    ``__name__``)."""
    def wrap(fn):
        scope_name = name if name is not None else fn.__name__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with trace_scope(scope_name):
                return fn(*args, **kwargs)
        return inner
    return wrap


def lowered_scopes(lowered):
    """The set of debug-location name paths in a ``jax.stages.Lowered``
    — every ``jax.named_scope`` entered during tracing appears as a
    path component (e.g. ``jit(step)/fused_rk_stage_pair/concatenate``).
    Used by tests to assert instrumentation presence under CPU lowering,
    no TPU or live profiler required."""
    asm = lowered.compiler_ir().operation.get_asm(enable_debug_info=True)
    return set(re.findall(r'loc\("([^"]*)"', asm))


def has_scope(lowered, name):
    """True when ``name`` appears in any of ``lowered``'s scope paths."""
    return any(name in path for path in lowered_scopes(lowered))
