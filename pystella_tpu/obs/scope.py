"""Named trace scopes for hot paths.

One context manager, two sinks:

- ``jax.named_scope`` attaches the name to every op traced inside, so
  compiled-code profiles (Perfetto / TensorBoard traces captured with
  :class:`pystella_tpu.trace`) show ``fused_rk_stage_pair`` /
  ``halo_exchange`` / ``pallas_stencil`` regions instead of raw XLA op
  names;
- ``jax.profiler.TraceAnnotation`` marks the host-side timeline, so
  eager driver loops (per-stage protocol, multigrid cycle orchestration)
  show up as named spans in the same trace.

Both are no-ops costing ~a microsecond when no profiler is attached and
are platform-agnostic (the CPU test suite runs them constantly).

The scope names survive into the lowered MLIR's debug locations, which
is how tests verify instrumentation without capturing a real trace:
:func:`lowered_scopes` / :func:`has_scope` parse them back out of a
``jax.jit(...).lower(...)`` result.
"""

from __future__ import annotations

import contextlib
import functools
import re

import jax

__all__ = ["trace_scope", "traced", "lowered_scopes", "has_scope"]


@contextlib.contextmanager
def trace_scope(name):
    """Name everything inside for both compiled-code traces
    (``jax.named_scope``) and the host timeline
    (``jax.profiler.TraceAnnotation``)."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def traced(name=None):
    """Decorator form of :func:`trace_scope` (defaults to the function's
    ``__name__``)."""
    def wrap(fn):
        scope_name = name if name is not None else fn.__name__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with trace_scope(scope_name):
                return fn(*args, **kwargs)
        return inner
    return wrap


def lowered_scopes(lowered):
    """The set of debug-location name paths in a ``jax.stages.Lowered``
    — every ``jax.named_scope`` entered during tracing appears as a
    path component (e.g. ``jit(step)/fused_rk_stage_pair/concatenate``).
    Used by tests to assert instrumentation presence under CPU lowering,
    no TPU or live profiler required."""
    asm = lowered.compiler_ir().operation.get_asm(enable_debug_info=True)
    return set(re.findall(r'loc\("([^"]*)"', asm))


def has_scope(lowered, name):
    """True when ``name`` appears in any of ``lowered``'s scope paths."""
    return any(name in path for path in lowered_scopes(lowered))
