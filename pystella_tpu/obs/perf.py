"""Continuous-performance plane: step-time digests, change-point
detection, and anomaly-triggered flight-recorder profiling.

The stack could already prove an SLO was missed (:mod:`obs.slo` burn
alerts, :mod:`obs.fleet` federation) and autopsy a numerics blowup
(:mod:`obs.forensics`) — but performance *drift* was invisible: a run
slowly losing 20% of its headline site-updates/s, or one straggling
host dragging a pod mesh, produced no event, no alert, no artifact,
and profiler capture was manual-only, so the evidence was gone by the
time an operator noticed. This module closes that loop, in four parts:

- :class:`Digest` — a per-program-signature rolling step-time quantile
  sketch (p50/p95/p99) over geometric histogram bins. The bin-count
  vector is the merge unit: summing two digests' counts IS the merged
  digest (associative and commutative by construction), and
  :func:`merge_across_hosts` gathers the vector through the same
  :func:`~pystella_tpu.parallel.multihost.all_gather_hosts` path the
  metrics registry federates over. Quantiles are exported as
  ``perf.<signature>.p50_ms``/``p95_ms``/``p99_ms`` gauges, so
  ``/metrics`` and the fleet federation pick them up for free.
- :class:`CusumDetector` — a robust one-sided CUSUM over each
  signature's sample series: baseline location/scale from the
  median/MAD of a healthy reference window (scale floored so a
  constant series cannot page on its first jitter), per-sample
  increments clipped so a single spike cannot fire alone — only a
  SUSTAINED shift accumulates past the threshold. Fires
  ``perf_anomaly`` (with straggler attribution from
  :mod:`obs.stragglers` in the payload) and ``perf_recovered`` once
  the series returns to the baseline band; both are registered kinds,
  and :class:`~pystella_tpu.obs.slo.SLOMonitor` routes them into its
  ``perf_regression`` leg — continuous performance gets the standard
  fast/slow burn-rate treatment and shows up on ``/slo``.
- straggler attribution — on every anomaly (and every digest window
  report), the cross-host step-time skew is gathered and the slowest
  host named in the event payload (:func:`~pystella_tpu.obs.
  stragglers.attribute`).
- :class:`FlightRecorder` — on a fired anomaly, a rate-limited
  ``jax.profiler`` capture of the next N steps, written as a Perfetto
  artifact and emitted as a ``perf_capture`` event the ledger's
  ``perf`` section links. At most one capture per cooldown
  (``PYSTELLA_PERF_CAPTURE_COOLDOWN_S``): an anomaly storm produces
  one trace and a suppression count, not a disk full of traces.

:class:`~pystella_tpu.utils.profiling.StepTimer` feeds the
process-default monitor on every tick (``PYSTELLA_PERF=0`` opts out),
and the scenario service's dispatch loop feeds per-chunk step times
under the ``service.chunk`` signature — every existing driver becomes
a detector input with no code changes. The ledger's ``perf`` report
section rolls the events up post-hoc, and the gate refuses a report
whose unresolved ``perf_anomaly`` sits beside a green step-time
verdict (the same live/post-hoc honesty rule as the PR 14 burn
alerts).

Everything here is telemetry: the observe path is a few float ops and
two deque appends, capture failures degrade to a recorded error, and
no code path may take down the step loop it watches.
"""

from __future__ import annotations

import collections
import math
import threading
import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import metrics as _metrics
from pystella_tpu.obs import stragglers as _stragglers

__all__ = ["Digest", "CusumDetector", "FlightRecorder", "PerfMonitor",
           "default_monitor", "enabled", "merge_across_hosts",
           "observe"]

#: geometric bin range of the step-time digest: 1 µs .. 10 min covers a
#: fused 64³ CPU step through a pod-scale 1024³ window with margin
DIGEST_LO_MS = 1e-3
DIGEST_HI_MS = 6e5
#: bins across that range — relative quantile error is one bin width,
#: (HI/LO)^(1/bins) - 1 ≈ 4% at 512 bins; the gatherable vector stays
#: a few KiB
DIGEST_BINS = 512

#: recent raw samples retained per signature for straggler attribution
#: (the per-host window mean) and the recovery band check
_RECENT_SAMPLES = 64

#: once the detector's reference window is full, re-derive median/MAD
#: only every this many appended samples — a 64-sample rolling median
#: drifts far slower than that, and the refit's two sorts dominate the
#: observe() hot path otherwise (the window-filling phase still refits
#: every sample, so the min_samples boundary behaves exactly)
_REFIT_EVERY = 8

#: quantile-gauge refresh cadence (samples) — the p50/p95/p99 gauges
#: are scrape-time telemetry, not the detector input, so paying three
#: 512-bin scans per step buys nothing; transitions always refresh
_GAUGE_EVERY = 16


class Digest:
    """A mergeable step-time quantile sketch: counts over geometric
    bins. ``merge`` sums count vectors, so merging is associative and
    commutative and a cross-host merge is one
    ``all_gather_hosts`` + sum (:func:`merge_across_hosts`). Quantile
    error is bounded by one bin width (~4% relative at the default
    512 bins over 1 µs..10 min) — plenty for p50/p95/p99 drift
    detection, where the signal is tens of percent."""

    def __init__(self, lo_ms=DIGEST_LO_MS, hi_ms=DIGEST_HI_MS,
                 bins=DIGEST_BINS):
        self.lo_ms = float(lo_ms)
        self.hi_ms = float(hi_ms)
        self.bins = int(bins)
        self._log_lo = math.log(self.lo_ms)
        self._log_span = math.log(self.hi_ms) - self._log_lo
        self.counts = [0] * self.bins
        self.count = 0
        self.total_ms = 0.0

    def _bin(self, ms):
        if ms <= self.lo_ms:
            return 0
        if ms >= self.hi_ms:
            return self.bins - 1
        frac = (math.log(ms) - self._log_lo) / self._log_span
        return min(self.bins - 1, int(frac * self.bins))

    def _edge(self, i):
        """Geometric midpoint of bin ``i`` (the quantile estimate)."""
        frac = (i + 0.5) / self.bins
        return math.exp(self._log_lo + frac * self._log_span)

    def add(self, ms):
        ms = float(ms)
        self.counts[self._bin(ms)] += 1
        self.count += 1
        self.total_ms += ms

    def quantile(self, q):
        """The q-th percentile estimate in ms (``q`` in 0..100), or
        ``None`` for an empty digest."""
        if self.count == 0:
            return None
        target = max(1, math.ceil(self.count * float(q) / 100.0))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self._edge(i)
        return self._edge(self.bins - 1)

    def mean(self):
        return self.total_ms / self.count if self.count else None

    def _compatible(self, other):
        return (self.bins == other.bins and self.lo_ms == other.lo_ms
                and self.hi_ms == other.hi_ms)

    def merge(self, other):
        """A NEW digest holding both inputs' samples (count-vector
        sum); inputs are untouched, so merges compose freely."""
        if not self._compatible(other):
            raise ValueError("cannot merge digests with different "
                             "bin layouts")
        out = Digest(self.lo_ms, self.hi_ms, self.bins)
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.count = self.count + other.count
        out.total_ms = self.total_ms + other.total_ms
        return out

    @classmethod
    def from_counts(cls, counts, total_ms=0.0, lo_ms=DIGEST_LO_MS,
                    hi_ms=DIGEST_HI_MS):
        """Rebuild a digest from a (possibly host-summed) count
        vector — the receive side of the federation path."""
        out = cls(lo_ms, hi_ms, len(counts))
        out.counts = [int(c) for c in counts]
        out.count = sum(out.counts)
        out.total_ms = float(total_ms)
        return out

    def summary(self):
        """The JSON-safe window summary the gauges/events carry."""
        return {
            "count": self.count,
            "mean_ms": self.mean(),
            "p50_ms": self.quantile(50),
            "p95_ms": self.quantile(95),
            "p99_ms": self.quantile(99),
        }


def merge_across_hosts(digest):
    """The fleet-wide digest: gather every host's count vector through
    :func:`~pystella_tpu.parallel.multihost.all_gather_hosts` and sum.
    Lockstep contract as with metrics aggregation (SPMD drivers cross
    their report cadence together); degrades to a copy of the local
    digest on a single-process run."""
    import numpy as np

    from pystella_tpu.parallel.multihost import all_gather_hosts

    vec = np.array(digest.counts + [digest.total_ms], dtype=np.float64)
    gathered = all_gather_hosts(vec)
    counts = gathered[:, :-1].sum(axis=0)
    total = float(gathered[:, -1].sum())
    return Digest.from_counts([int(c) for c in counts], total_ms=total,
                              lo_ms=digest.lo_ms, hi_ms=digest.hi_ms)


class CusumDetector:
    """Robust one-sided CUSUM change-point detector over one
    signature's step-time series.

    Location/scale come from the median/MAD of a reference window of
    HEALTHY samples (the window stops updating while an anomaly is
    open, so the baseline cannot absorb the regression it is
    reporting). The scale is floored at ``rel_floor`` of the location:
    a constant series has MAD 0, and without the floor its first
    scheduler jitter would page. Per-sample increments are clipped at
    ``clip`` sigmas, so one spike contributes at most ``clip`` toward
    the ``h`` threshold — only a sustained shift of at least
    ``ceil(h / clip)`` consecutive slow samples can fire. Recovery is
    the last ``recover_n`` samples all back inside the baseline band
    (below ``mu + k * sigma``), which also resets the accumulator.
    """

    def __init__(self, window=64, min_samples=16, k=1.0, h=8.0,
                 clip=4.0, recover_n=6, rel_floor=0.25):
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.k = float(k)
        self.h = float(h)
        self.clip = float(clip)
        self.recover_n = int(recover_n)
        self.rel_floor = float(rel_floor)
        self.reference = collections.deque(maxlen=self.window)
        self.recent = collections.deque(maxlen=max(self.recover_n, 8))
        self.cusum = 0.0
        self.anomalous = False
        self.fired_ts = None
        self.fires = 0
        self.recoveries = 0
        self.mu = None
        self.sigma = None
        self._stale = 0     # healthy samples appended since last refit

    def _refit(self):
        vals = sorted(self.reference)
        n = len(vals)
        mid = n // 2
        mu = vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])
        devs = sorted(abs(v - mu) for v in vals)
        mad = devs[mid] if n % 2 else 0.5 * (devs[mid - 1] + devs[mid])
        self.mu = mu
        # 1.4826: Gaussian-consistent MAD->sigma; floored relative to
        # the location so a near-constant series keeps a usable band
        self.sigma = max(1.4826 * mad, self.rel_floor * abs(mu), 1e-9)

    def update(self, ms, ts=None):
        """Feed one sample; returns ``"fired"`` / ``"recovered"`` /
        ``None``."""
        ms = float(ms)
        self.recent.append(ms)
        if not self.anomalous:
            self.reference.append(ms)
            self._stale += 1
        if len(self.reference) < self.min_samples:
            return None
        # refit every sample while the window fills (the baseline moves
        # fast there); once full, on the _REFIT_EVERY cadence. An open
        # anomaly appends nothing, so the frozen baseline costs nothing.
        if self.mu is None or (self._stale
                               and (len(self.reference) < self.window
                                    or self._stale >= _REFIT_EVERY)):
            self._refit()
            self._stale = 0
        bar = self.mu + self.k * self.sigma
        z = (ms - bar) / self.sigma
        self.cusum = max(0.0, self.cusum
                         + max(-self.clip, min(self.clip, z)))
        if not self.anomalous and self.cusum > self.h:
            self.anomalous = True
            self.fired_ts = time.time() if ts is None else float(ts)
            self.fires += 1
            return "fired"
        if self.anomalous and len(self.recent) >= self.recover_n \
                and all(v <= bar
                        for v in list(self.recent)[-self.recover_n:]):
            self.anomalous = False
            self.cusum = 0.0
            self.recoveries += 1
            return "recovered"
        return None

    def state(self):
        return {
            "anomalous": self.anomalous,
            "cusum": round(self.cusum, 6),
            "threshold": self.h,
            "baseline_ms": self.mu,
            "sigma_ms": self.sigma,
            "fires": self.fires,
            "recoveries": self.recoveries,
            "reference_n": len(self.reference),
        }


class _JaxTracer:
    """The default flight-recorder backend: ``jax.profiler`` around
    the capture window, artifact located with
    :func:`~pystella_tpu.obs.trace.find_trace_file`."""

    def start(self, logdir):
        import os

        import jax
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)

    def stop(self, logdir):
        import jax
        jax.profiler.stop_trace()
        from pystella_tpu.obs.trace import find_trace_file
        return find_trace_file(logdir)


class FlightRecorder:
    """Anomaly-triggered, rate-limited profiler capture.

    :meth:`request` arms a capture (unless one is active or the
    cooldown since the last one has not elapsed — then it only counts
    the suppression); :meth:`tick` is called once per observed step
    and closes the capture after ``steps`` of them, emitting one
    ``perf_capture`` event with the Perfetto artifact path (or
    ``artifact: null`` plus the error when the profiler produced
    nothing — capture is best-effort telemetry and never raises into
    the step loop).

    :arg logdir: capture root; each capture writes under
        ``<logdir>/<signature>-<n>``. ``None`` disables capturing
        (requests only count as suppressed-disabled).
    :arg steps: step-window length per capture.
    :arg cooldown_s: minimum seconds between capture STARTS — the
        rate limit. At most one artifact per cooldown, whatever the
        anomaly rate.
    :arg tracer: start/stop backend (default ``jax.profiler``); tests
        inject a stub.
    :arg clock: monotonic time source (injectable for rate-limit
        tests).
    """

    def __init__(self, logdir=None, steps=None, cooldown_s=None,
                 tracer=None, clock=time.monotonic, label="perf",
                 log=None):
        if steps is None:
            steps = _config.get_int("PYSTELLA_PERF_CAPTURE_STEPS")
        if cooldown_s is None:
            cooldown_s = _config.get_float(
                "PYSTELLA_PERF_CAPTURE_COOLDOWN_S")
        self.logdir = None if logdir is None else str(logdir)
        self.steps = max(1, int(steps or 1))
        self.cooldown_s = float(cooldown_s or 0.0)
        self.tracer = tracer if tracer is not None else _JaxTracer()
        self.clock = clock
        self.label = str(label)
        self.log = log
        self.captures = []          # finished-capture payloads, in order
        self.suppressed = 0         # cooldown-suppressed requests
        self.errors = 0
        self._active = None         # (dir, signature, reason, remaining)
        self._last_start = None
        self._seq = 0

    def _emit(self, kind, **data):
        sink = self.log if self.log is not None else _events.get_log()
        sink.emit(kind, **data)

    def request(self, signature, reason="perf_anomaly"):
        """Arm a capture for ``signature``; returns True when a
        capture actually started."""
        if self.logdir is None or self._active is not None:
            return False
        now = self.clock()
        if self._last_start is not None \
                and now - self._last_start < self.cooldown_s:
            self.suppressed += 1
            return False
        self._seq += 1
        import os
        cap_dir = os.path.join(self.logdir,
                               f"{signature}-{self._seq}")
        try:
            self.tracer.start(cap_dir)
        except Exception as e:  # noqa: BLE001 — telemetry only
            self.errors += 1
            self._emit("perf_capture", signature=signature,
                       reason=reason, artifact=None, logdir=cap_dir,
                       steps=0, error=repr(e), label=self.label)
            return False
        self._last_start = now
        self._active = {"dir": cap_dir, "signature": str(signature),
                        "reason": str(reason),
                        "remaining": self.steps}
        return True

    def tick(self):
        """One observed step passed; closes the active capture when
        its window is complete."""
        if self._active is None:
            return
        self._active["remaining"] -= 1
        if self._active["remaining"] <= 0:
            self.flush()

    def flush(self):
        """Force-close an active capture (end of run / drill)."""
        if self._active is None:
            return
        active, self._active = self._active, None
        artifact = None
        error = None
        try:
            artifact = self.tracer.stop(active["dir"])
        except Exception as e:  # noqa: BLE001 — telemetry only
            self.errors += 1
            error = repr(e)
        payload = {
            "signature": active["signature"],
            "reason": active["reason"],
            "artifact": artifact,
            "logdir": active["dir"],
            "steps": self.steps - active["remaining"],
            "suppressed": self.suppressed,
            "label": self.label,
        }
        if error is not None:
            payload["error"] = error
        self.captures.append(payload)
        self._emit("perf_capture", **payload)

    def state(self):
        return {
            "enabled": self.logdir is not None,
            "captures": len(self.captures),
            "suppressed": self.suppressed,
            "errors": self.errors,
            "active": None if self._active is None
            else self._active["signature"],
            "cooldown_s": self.cooldown_s,
        }


class PerfMonitor:
    """Per-signature step-time digests + change-point detection +
    flight-recorder triggering — the continuous-performance plane's
    live half (module docstring).

    :arg window / min_samples / k / h / recover_n: detector knobs
        (fall back to the registered ``PYSTELLA_PERF_*`` defaults).
    :arg recorder: a :class:`FlightRecorder`; ``None`` builds one from
        ``PYSTELLA_PERF_CAPTURE_DIR`` (disabled when that is unset).
    :arg digest_every: emit a ``perf_digest`` window event every this
        many samples per signature (0 disables the event; the
        quantile gauges refresh on the ``_GAUGE_EVERY`` cadence and on
        every transition regardless).
    :arg emit: emit ``perf_anomaly``/``perf_recovered`` events on
        transitions (``False`` keeps the monitor silent for
        embedding).
    :arg straggler: include cross-host straggler attribution in
        anomaly payloads and digest reports (single-host runs degrade
        to a one-row table).
    """

    def __init__(self, window=None, min_samples=None, k=None, h=None,
                 recover_n=None, recorder=None, digest_every=256,
                 label="perf", emit=True, straggler=True, log=None,
                 metrics=None):
        if window is None:
            window = _config.get_int("PYSTELLA_PERF_WINDOW")
        if min_samples is None:
            min_samples = _config.get_int("PYSTELLA_PERF_MIN_SAMPLES")
        if k is None:
            k = _config.get_float("PYSTELLA_PERF_CUSUM_K")
        if h is None:
            h = _config.get_float("PYSTELLA_PERF_CUSUM_H")
        if recover_n is None:
            recover_n = _config.get_int("PYSTELLA_PERF_RECOVER_N")
        if recorder is None:
            cap_dir = _config.getenv("PYSTELLA_PERF_CAPTURE_DIR")
            recorder = FlightRecorder(cap_dir or None, label=label,
                                      log=log)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.k = float(k)
        self.h = float(h)
        self.recover_n = int(recover_n)
        self.recorder = recorder
        self.digest_every = int(digest_every)
        self.label = str(label)
        self.emit_events = bool(emit)
        self.straggler = bool(straggler)
        self.log = log
        self._metrics = (metrics if metrics is not None
                         else _metrics.registry())
        self._lock = threading.Lock()
        self._sigs = {}             # signature -> (digest, detector,
        #                             recent deque)
        self.observed = 0
        self.observe_s = 0.0        # cumulative observe() cost — the
        #                             emit-path overhead, auditable

    def _emit(self, kind, **data):
        sink = self.log if self.log is not None else _events.get_log()
        sink.emit(kind, **data)

    def _sig_state(self, signature):
        st = self._sigs.get(signature)
        if st is None:
            st = self._sigs[signature] = {
                "digest": Digest(),
                "detector": CusumDetector(
                    window=self.window,
                    min_samples=self.min_samples, k=self.k, h=self.h,
                    recover_n=self.recover_n),
                "recent": collections.deque(maxlen=_RECENT_SAMPLES),
            }
            # pre-register the gauges at NaN so SPMD hosts' snapshot
            # vectors line up before the first report (metrics.py's
            # aggregation contract)
            for q in ("p50", "p95", "p99"):
                self._metrics.gauge(f"perf.{signature}.{q}_ms")
            self._metrics.gauge(f"perf.{signature}.anomalous",
                                reduce="max")
        return st

    def _attribution(self, recent):
        if not self.straggler:
            return None
        return _stragglers.attribute(list(recent))

    def observe(self, signature, ms, step=None, ts=None):
        """Feed one step-time sample (milliseconds) for ``signature``.
        Returns the detector transition (``"fired"`` /
        ``"recovered"`` / ``None``)."""
        t0 = time.perf_counter()
        signature = str(signature)
        ms = float(ms)
        with self._lock:
            st = self._sig_state(signature)
            st["digest"].add(ms)
            st["recent"].append(ms)
            det = st["detector"]
            change = det.update(ms, ts=ts)
            count = st["digest"].count
            # the three 512-bin quantile scans are the observe() hot
            # path — run them on the gauge cadence, on transitions
            # (the anomaly payload carries them), and on digest-event
            # samples, never per step
            summary = (st["digest"].summary()
                       if (change is not None
                           or count % _GAUGE_EVERY == 0
                           or (self.digest_every
                               and count % self.digest_every == 0))
                       else None)
        if summary is not None:
            for q in ("p50", "p95", "p99"):
                v = summary.get(f"{q}_ms")
                if v is not None:
                    self._metrics.gauge(
                        f"perf.{signature}.{q}_ms").set(v)
        self._metrics.gauge(f"perf.{signature}.anomalous",
                            reduce="max").set(1.0 if det.anomalous
                                              else 0.0)
        if change == "fired":
            self._metrics.counter("perf.anomalies").inc()
            straggler = self._attribution(st["recent"])
            if self.emit_events:
                self._emit("perf_anomaly", step=step,
                           signature=signature, ms=ms,
                           baseline_ms=det.mu, sigma_ms=det.sigma,
                           cusum=round(det.cusum, 6), threshold=det.h,
                           straggler=straggler, label=self.label,
                           **{key: summary[key] for key in
                              ("p50_ms", "p95_ms", "p99_ms")})
            self.recorder.request(signature, reason="perf_anomaly")
        elif change == "recovered":
            self._metrics.counter("perf.recoveries").inc()
            if self.emit_events:
                duration = (time.time() - det.fired_ts
                            if det.fired_ts else 0.0)
                self._emit("perf_recovered", step=step,
                           signature=signature, ms=ms,
                           baseline_ms=det.mu,
                           duration_s=round(max(0.0, duration), 6),
                           label=self.label)
        self.recorder.tick()
        if self.digest_every and count % self.digest_every == 0 \
                and self.emit_events:
            self._emit("perf_digest", step=step, signature=signature,
                       straggler=self._attribution(st["recent"]),
                       label=self.label, **summary)
        self.observed += 1
        self.observe_s += time.perf_counter() - t0
        return change

    def digest(self, signature):
        """The signature's :class:`Digest` (or ``None``) — the merge
        unit :func:`merge_across_hosts` federates."""
        with self._lock:
            st = self._sigs.get(str(signature))
            return st["digest"] if st else None

    def state(self):
        """JSON-safe monitor state: per-signature digest summaries and
        detector state, recorder bookkeeping, observe-path cost."""
        with self._lock:
            sigs = {
                name: {**st["digest"].summary(),
                       **st["detector"].state()}
                for name, st in self._sigs.items()
            }
        return {
            "label": self.label,
            "signatures": sigs,
            "anomalous": sorted(n for n, s in sigs.items()
                                if s["anomalous"]),
            "recorder": self.recorder.state(),
            "observed": self.observed,
            "observe_s": round(self.observe_s, 6),
        }


# -- the process-default monitor (what StepTimer / the service feed) ---------

_default = None
_default_lock = threading.Lock()


def enabled():
    """The ``PYSTELLA_PERF`` master switch: when off, the default
    monitor is never constructed and :func:`observe` is a no-op — emit
    paths are then byte-identical to a build without this plane."""
    return bool(_config.get_bool("PYSTELLA_PERF"))


def default_monitor():
    """The process-default :class:`PerfMonitor` (constructed lazily
    from the ``PYSTELLA_PERF_*`` knobs)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = PerfMonitor()
        return _default


def observe(signature, ms, step=None):
    """Feed one step-time sample into the process-default monitor;
    no-op when ``PYSTELLA_PERF=0``. The single integration point the
    drivers use (:class:`~pystella_tpu.utils.profiling.StepTimer`, the
    scenario service's chunk loop)."""
    if not enabled():
        return None
    return default_monitor().observe(signature, ms, step=step)


def _reset_default():
    """Drop the process-default monitor (tests)."""
    global _default
    with _default_lock:
        _default = None
