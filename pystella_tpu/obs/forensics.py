"""Divergence forensics: everything needed to diagnose a tripped run.

When the numerics sentinel trips (:mod:`pystella_tpu.obs.sentinel`),
the bare ``SimulationDiverged`` traceback answers *that* a field went
bad, not *why* or *since when*. The forensic bundle is the record that
does: one JSON file holding

- the trip itself: step, reason, offending fields, and (when an
  invariant bound tripped) the offending invariant by name;
- the last-K health vectors from the monitor's ring buffer, plus a
  pivoted per-field ``max_abs``/``rms`` history (the blowup curve —
  was it a slow drift or a one-step explosion?);
- the tail of the run-event log (``run_events.jsonl`` window:
  checkpoint saves, compiles, step times leading up to the trip);
- the active configuration and environment fingerprint (jax versions,
  device kind, scheduler flags, ``PYSTELLA_*`` env);
- a pointer to the last good checkpoint
  (:class:`~pystella_tpu.Checkpointer` directory + step), the state a
  resume-and-bisect debug session — or an elastic
  :class:`~pystella_tpu.resilience.Supervisor` recovery — starts
  from. "Good" means **durable**: the pointer only ever names steps
  past the checkpointer's durability barrier, never a write that was
  merely scheduled when the run died (``doc/resilience.md``).

:func:`write_bundle` / :func:`load_bundle` round-trip the schema;
:class:`ForensicSink` is the configured writer a
:class:`~pystella_tpu.obs.sentinel.SentinelMonitor` calls on a trip —
best-effort by contract (a failed bundle write must never mask the
``SimulationDiverged`` that triggered it).
"""

from __future__ import annotations

import json
import os
import sys
import time

from pystella_tpu.obs import events as _events
from pystella_tpu.obs import ledger as _ledger

__all__ = ["BUNDLE_SCHEMA_VERSION", "ForensicSink", "load_bundle",
           "write_bundle"]

BUNDLE_SCHEMA_VERSION = 1

#: env-var name prefixes captured into the bundle's environment record
_ENV_PREFIXES = ("PYSTELLA_", "JAX_", "XLA_FLAGS", "LIBTPU_INIT_ARGS")


def _jsonify(obj):
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_jsonify(v) for v in obj]
    if hasattr(obj, "item") and getattr(obj, "ndim", None) in (None, 0):
        try:
            return _jsonify(obj.item())
        except Exception:
            pass
    return str(obj)


def _checkpoint_pointer(checkpoint):
    """Resolve the last-good-checkpoint pointer: a
    :class:`~pystella_tpu.Checkpointer` (via its ``last_good``
    property — durable steps only, so a trip racing an in-flight
    write can never embed a torn checkpoint), an explicit
    ``{"directory", "step"}`` dict, or ``None``."""
    if checkpoint is None:
        return None
    if isinstance(checkpoint, dict):
        return _jsonify(checkpoint)
    last_good = getattr(checkpoint, "last_good", None)
    return _jsonify(last_good)


def _field_history(history):
    """Pivot the monitor's ring buffer into per-field stat series:
    ``{field: {"steps": [...], "max_abs": [...], "rms": [...]}}`` —
    the blowup curve, directly plottable."""
    out = {}
    for rec in history:
        step = rec.get("step")
        for name, st in (rec.get("fields") or {}).items():
            row = out.setdefault(
                name, {"steps": [], "max_abs": [], "rms": []})
            row["steps"].append(step)
            row["max_abs"].append(st.get("max_abs"))
            row["rms"].append(st.get("rms"))
    return out


def write_bundle(out_dir, step, reason, bad_fields=(),
                 offending_invariant=None, history=(), events_path=None,
                 events_window=200, checkpoint=None, config=None,
                 label="", member=None, member_params=None):
    """Write one forensic bundle; returns the JSON path. Also emits a
    ``forensic_bundle`` run event pointing at it, so the event log's
    forensic tail (``diverged`` -> ``forensic_bundle`` ->
    ``run_aborted``) links to the full record.

    For an ensemble trip (:mod:`pystella_tpu.ensemble`) the bundle is
    PER MEMBER: ``member`` is the slot index of the diverged member and
    ``member_params`` its parameter draw (couplings, dt, seed), so the
    record names the bad scenario instead of dumping the whole batch —
    ``history`` should then already be the member's own health series."""
    events_tail = []
    if events_path:
        events_tail = _events.read_events(events_path)[-int(events_window):]
    env = {k: v for k, v in sorted(os.environ.items())
           if k.startswith(_ENV_PREFIXES)}
    bundle = {
        "schema": BUNDLE_SCHEMA_VERSION,
        "generated_ts": time.time(),
        "label": label,
        "trip": {
            "step": int(step),
            "reason": str(reason),
            "bad_fields": [str(f) for f in bad_fields],
            "offending_invariant": offending_invariant,
            "member": None if member is None else int(member),
            "member_params": _jsonify(member_params)
            if member_params is not None else None,
        },
        "health_history": _jsonify(list(history)),
        "field_history": _jsonify(_field_history(history)),
        "events_tail": events_tail,
        "env": _ledger.environment_fingerprint(),
        "env_vars": env,
        "config": _jsonify(config) if config is not None else None,
        "last_good_checkpoint": _checkpoint_pointer(checkpoint),
    }
    os.makedirs(out_dir, exist_ok=True)
    stem = (f"forensic_bundle_step{int(step)}" if member is None else
            f"forensic_bundle_step{int(step)}_member{int(member)}")
    path = os.path.join(out_dir, stem + ".json")
    with open(path, "w") as f:
        json.dump(bundle, f, indent=1, sort_keys=True)
        f.write("\n")
    _events.emit("forensic_bundle", step=step, path=path,
                 reason=str(reason), bad_fields=list(bad_fields),
                 offending_invariant=offending_invariant, label=label,
                 member=None if member is None else int(member))
    return path


def load_bundle(path):
    """Parse a forensic bundle back; raises ``ValueError`` on files
    that are not bundles (so a wrong path fails loudly, not as an
    empty-looking record)."""
    with open(path) as f:
        bundle = json.load(f)
    if not isinstance(bundle, dict) or "trip" not in bundle:
        raise ValueError(f"{path}: not a forensic bundle (no 'trip')")
    return bundle


class ForensicSink:
    """Configured bundle writer for a
    :class:`~pystella_tpu.obs.sentinel.SentinelMonitor`.

    :arg out_dir: bundle directory (created on first write).
    :arg events_path: the run's JSONL event log; its tail is embedded.
    :arg checkpoint: a :class:`~pystella_tpu.Checkpointer` (queried for
        its last good step AT TRIP TIME) or a ``{"directory", "step"}``
        dict.
    :arg config: the run configuration (e.g. the parsed CLI namespace's
        ``vars()``), JSON-coerced best-effort.

    ``write`` never raises: forensics must not mask the
    ``SimulationDiverged`` being raised around it — a failed write
    degrades to a ``forensic_failed`` event plus a stderr line.
    """

    def __init__(self, out_dir, events_path=None, events_window=200,
                 checkpoint=None, config=None, label=""):
        self.out_dir = str(out_dir)
        self.events_path = events_path
        self.events_window = int(events_window)
        self.checkpoint = checkpoint
        self.config = config
        self.label = label
        #: path of the last bundle written (None until a trip)
        self.last_bundle = None

    def write(self, step, reason, bad_fields=(),
              offending_invariant=None, history=(), member=None,
              member_params=None):
        try:
            self.last_bundle = write_bundle(
                self.out_dir, step, reason, bad_fields=bad_fields,
                offending_invariant=offending_invariant, history=history,
                events_path=self.events_path,
                events_window=self.events_window,
                checkpoint=self.checkpoint, config=self.config,
                label=self.label, member=member,
                member_params=member_params)
            return self.last_bundle
        except Exception as e:
            _events.emit("forensic_failed", step=step,
                         error=f"{type(e).__name__}: {e}")
            print(f"pystella_tpu.obs.forensics: bundle write failed "
                  f"({e}); the diverged event still holds the trip "
                  "record", file=sys.stderr)
            return None
