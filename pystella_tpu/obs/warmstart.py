"""AOT warm-start: serialize the dispatched step programs, reload them
at startup, skip trace+compile entirely.

Cold start has two compiler-side costs the compile ledger
(:mod:`pystella_tpu.obs.memory`) now itemizes: Python-side **tracing**
(jaxpr + StableHLO lowering — round 3's 512^3 multigrid spent minutes
here) and the XLA **backend compile**. The persistent compilation cache
(:func:`~pystella_tpu.obs.memory.ensure_compilation_cache`) kills the
second; this module kills the first: the very step programs the lint
tier already lowers (:mod:`pystella_tpu.lint.targets`) are exported
with ``jax.export``, serialized next to a metadata sidecar, and keyed
by their **program fingerprint** — lowered-module hash + arg
shape/dtype/sharding signature + jax/jaxlib/libtpu versions + the
scheduler-flag fingerprint. A warmed process deserializes and calls;
with the persistent cache also populated (``save(verify=True)`` runs
the exported program once, so its backend compile is cached too), the
warm path does **no tracing and no backend compile**.

Staleness is structural, not hoped-for: loading re-derives the
version/flag components from the live process and refuses a mismatched
artifact (``warmstart_mismatch`` event + ``None`` return — the caller
falls back to the jit path). A jax upgrade therefore invalidates every
artifact instead of silently calling a stale executable, and the perf
gate refuses a report that *claims* warm start over mismatched
fingerprints (``obs.gate``).

CLI::

    python -m pystella_tpu.obs.warmstart export --out DIR [--target N]
    python -m pystella_tpu.obs.warmstart verify --dir DIR
    python -m pystella_tpu.obs.warmstart list --dir DIR
    python -m pystella_tpu.obs.warmstart gc --dir DIR [--dry-run]

(all directories default to ``PYSTELLA_WARMSTART_DIR`` when set,
which is also the default store location for drivers — ``bench.py``'s
warm-start leg persists and reloads its artifacts there)

``export`` builds the lint target registry's step programs (the same
CPU-safe 8-device builds the IR audit lowers) and serializes each;
``verify`` checks every artifact in a directory against the live
process's versions/flags (exit 1 when any is stale); ``list``
enumerates artifacts with fingerprint/version/match-status (always
exit 0); ``gc`` removes version- or flag-STALE exports — the tending a
long-lived warm pool needs, since until now the store only ever grew
(a matching artifact is never touched; staleness is exactly the rule
:meth:`WarmstartStore.load` refuses on). Exit codes: 0 ok, 1
mismatch/failure, 2 bad usage.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

from pystella_tpu.obs import events as _events
from pystella_tpu.obs import memory as _memory

__all__ = ["WarmProgram", "WarmstartStore", "export_target",
           "gc_store", "main"]

#: serialized jax.export payload / metadata sidecar suffixes
ARTIFACT_SUFFIX = ".jaxexport"
META_SUFFIX = ".meta.json"

#: fingerprint components that must match the live process for an
#: artifact to be loadable (aval components are checked only when the
#: caller supplies example args)
_STALENESS_KEYS = ("versions", "flags")


def _safe_label(label):
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", str(label)) or "program"


class WarmProgram:
    """A deserialized AOT program plus its export-time metadata.
    Calling it dispatches the exported computation (no tracing; the
    backend compile of the deserialized module hits the persistent
    cache when the artifact was saved with ``verify=True`` against the
    same cache directory)."""

    def __init__(self, exported, meta, path):
        self.exported = exported
        self.meta = meta
        self.path = path
        self.label = meta.get("label")
        self.fingerprint = meta.get("fingerprint")

    def __call__(self, *args, **kwargs):
        # a DONATED exported program must not have its backend compile
        # served from a deserialized persistent-cache entry on backends
        # where that corrupts repeat calls (obs.memory.
        # cache_donation_safe) — bypass the cache for its compile; the
        # AOT artifact still skips all tracing either way
        bypass = _memory.should_bypass_cache(self.meta.get("donated"))
        with _memory.compile_watch(f"warmstart.{self.label}") as w:
            if bypass:
                with _memory.cache_bypass(watch=w):
                    out = self.exported.call(*args, **kwargs)
            else:
                out = self.exported.call(*args, **kwargs)
        if w.compiled:
            rec = _memory.CompileRecord(
                label=f"warmstart.{self.label}",
                trace_seconds=w.trace_seconds,
                compile_seconds=w.compile_seconds,
                fingerprint=self.fingerprint,
                fingerprint_kind="lowered",
                cache_hits=w.cache_hits,
                cache_misses=w.cache_misses)
            _memory._record_compile_metrics(rec)
            _events.emit("compile", source="warmstart", **rec.asdict())
        return out

    def __repr__(self):
        return (f"WarmProgram({self.label!r}, "
                f"fingerprint={self.fingerprint!r})")


class WarmstartStore:
    """A directory of AOT-exported programs, one
    ``<label>-<fingerprint>.jaxexport`` + ``.meta.json`` pair each.

    :meth:`save` exports a jitted program for concrete example
    arguments; :meth:`load` deserializes the newest matching artifact
    for a label, refusing (returning ``None``) when the live process's
    versions/flags — or, when example args are given, the call
    signature — differ from the export-time fingerprint components.
    """

    def __init__(self, root=None):
        if root is None:
            from pystella_tpu import config as _config
            root = _config.getenv("PYSTELLA_WARMSTART_DIR")
            if not root:
                raise ValueError(
                    "WarmstartStore needs a directory: pass root= or "
                    "set PYSTELLA_WARMSTART_DIR")
        self.root = os.path.abspath(str(root))
        os.makedirs(self.root, exist_ok=True)

    # -- save --------------------------------------------------------------

    def save(self, label, fn, args=(), kwargs=None, verify=True,
             log=None):
        """Export ``fn(*args, **kwargs)`` (a ``jax.jit`` object, an
        :class:`~pystella_tpu.obs.memory.InstrumentedJit`, or a plain
        function) under ``label``; returns the metadata dict.

        ``verify=True`` (default) additionally *calls* the exported
        program once on ``args`` — proving the artifact actually runs
        on this mesh AND populating the persistent compilation cache
        with its backend compile, so a later warm process skips that
        too."""
        import jax
        from jax import export as _export
        kwargs = kwargs or {}
        jitted = getattr(fn, "_jitted", fn)  # unwrap InstrumentedJit
        if not hasattr(jitted, "lower"):
            jitted = jax.jit(jitted)
        exported = _export.export(jitted)(*args, **kwargs)
        # the exported module is the ONE lowering this save pays for —
        # an explicit .lower() for the fingerprint would re-trace the
        # whole program (minutes for the 512^3 targets this store
        # exists for), and the export text keeps the aliasing attrs
        # the donation-bypass policy scans for
        text = exported.mlir_module()
        donated = any(m in text for m in _memory._DONATION_MARKERS)
        fingerprint, components = _memory.program_fingerprint(
            text=text, label=label, args=args, kwargs=kwargs)
        blob = exported.serialize()
        stem = f"{_safe_label(label)}-{fingerprint}"
        artifact = os.path.join(self.root, stem + ARTIFACT_SUFFIX)
        with open(artifact, "wb") as f:
            f.write(blob)
        meta = {
            "label": str(label),
            "fingerprint": fingerprint,
            "donated": donated,
            "components": components,
            "artifact": os.path.basename(artifact),
            "serialized_bytes": len(blob),
            "created_ts": time.time(),
            "platforms": list(exported.platforms),
            "nr_devices": int(exported.nr_devices),
        }
        if verify:
            # verify via a DESERIALIZED copy: proves the artifact bytes
            # on disk actually run on this mesh, and populates the
            # persistent compilation cache with the exact calling
            # wrapper a warm process will build from those same bytes —
            # so the warm process's backend compile is a cache hit
            try:
                reloaded = _export.deserialize(blob)
                jax.block_until_ready(reloaded.call(*args, **kwargs))
            except Exception:
                # a failed verify must not leave a loadable pair behind
                # (load() keys on the sidecar, written below)
                try:
                    os.remove(artifact)
                except OSError:
                    pass
                raise
            meta["verified"] = True
        meta_path = os.path.join(self.root, stem + META_SUFFIX)
        with open(meta_path, "w") as f:
            json.dump(meta, f, indent=1, sort_keys=True)
            f.write("\n")
        (log if log is not None else _events.get_log()).emit(
            "warmstart_export", label=str(label),
            fingerprint=fingerprint, path=artifact,
            serialized_bytes=len(blob), verified=bool(verify))
        return meta

    # -- load --------------------------------------------------------------

    def entries(self, label=None):
        """Metadata dicts for every artifact in the store (newest
        first), optionally filtered by ``label``."""
        metas = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        for name in names:
            if not name.endswith(META_SUFFIX):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue
            if label is not None and meta.get("label") != str(label):
                continue
            metas.append(meta)
        metas.sort(key=lambda m: m.get("created_ts", 0), reverse=True)
        return metas

    def _mismatches(self, meta, args=None, kwargs=None):
        """Why the live process cannot trust ``meta``'s artifact:
        version/flag drift always checked; aval signature checked when
        example args are supplied."""
        live = _memory.fingerprint_components(
            meta.get("label", ""), args, kwargs)
        saved = meta.get("components") or {}
        problems = []
        for key in _STALENESS_KEYS:
            if saved.get(key) != live.get(key):
                problems.append(
                    f"{key}: exported {saved.get(key)!r} "
                    f"vs live {live.get(key)!r}")
        if args is not None or kwargs is not None:
            if saved.get("avals") != live.get("avals"):
                problems.append("avals: call signature differs from "
                                "the exported program's")
        return problems

    def load(self, label, args=None, kwargs=None,
             expect_fingerprint=None, log=None):
        """Deserialize the newest artifact for ``label`` that MATCHES
        the live process (a stale newer artifact — e.g. exported under
        different scheduler flags, or before a jax rollback — must not
        shadow an older matching one); ``None`` (plus a
        ``warmstart_mismatch`` event) when no artifact exists or none
        matches — the caller then takes the cold jit path.
        ``expect_fingerprint`` pins an exact program; ``args``/
        ``kwargs`` additionally validate the call signature."""
        sink = log if log is not None else _events.get_log()
        metas = self.entries(label)
        if expect_fingerprint is not None:
            metas = [m for m in metas
                     if m.get("fingerprint") == expect_fingerprint]
        if not metas:
            sink.emit("warmstart_mismatch", label=str(label),
                      reason="no artifact", dir=self.root,
                      expect_fingerprint=expect_fingerprint)
            return None
        meta = first_problems = None
        for candidate in metas:
            problems = self._mismatches(candidate, args, kwargs)
            if not problems:
                meta = candidate
                break
            if first_problems is None:
                first_problems = (candidate, problems)
        if meta is None:
            candidate, problems = first_problems
            sink.emit("warmstart_mismatch", label=str(label),
                      reason="; ".join(problems),
                      fingerprint=candidate.get("fingerprint"),
                      candidates=len(metas),
                      dir=self.root)
            return None
        path = os.path.join(self.root, meta["artifact"])
        from jax import export as _export
        try:
            with open(path, "rb") as f:
                exported = _export.deserialize(f.read())
        except Exception as e:
            sink.emit("warmstart_mismatch", label=str(label),
                      reason=f"deserialize failed: {e}", dir=self.root)
            return None
        sink.emit("warmstart_load", label=str(label),
                  fingerprint=meta.get("fingerprint"), path=path)
        return WarmProgram(exported, meta, path)


def _gc_candidates(store):
    """``(meta, problems)`` per stored artifact, newest first —
    ``problems`` empty when the artifact matches the live process."""
    return [(meta, store._mismatches(meta))
            for meta in store.entries()]


def gc_store(store, dry_run=False, log=None):
    """Garbage-collect STALE artifacts (version/flag mismatch against
    the live process): the warm pool needs a tended store — exports
    keyed on yesterday's compiler stack only cost disk and load-time
    refusals. Returns ``(kept, removed)`` metadata lists; with
    ``dry_run`` nothing is deleted. Emits one ``warmstart_gc`` event.

    Artifacts that merely belong to OTHER labels stay: staleness is
    strictly the fingerprint components the loader itself refuses on
    (:meth:`WarmstartStore.load`), so gc never removes anything load
    would still serve."""
    kept, removed = [], []
    for meta, problems in _gc_candidates(store):
        if not problems:
            kept.append(meta)
            continue
        removed.append({**meta, "problems": problems})
        if dry_run:
            continue
        artifact = meta.get("artifact") or (
            f"{_safe_label(meta.get('label'))}-"
            f"{meta.get('fingerprint')}{ARTIFACT_SUFFIX}")
        stem = artifact[:-len(ARTIFACT_SUFFIX)] \
            if artifact.endswith(ARTIFACT_SUFFIX) else artifact
        for name in (artifact, stem + META_SUFFIX):
            try:
                os.remove(os.path.join(store.root, name))
            except OSError:
                pass
    (log if log is not None else _events.get_log()).emit(
        "warmstart_gc", dir=store.root, kept=len(kept),
        removed=len(removed), dry_run=bool(dry_run),
        removed_labels=[m.get("label") for m in removed][:32])
    return kept, removed


def export_target(store, target, log=None):
    """Build one :class:`~pystella_tpu.lint.graph.GraphTarget` (the
    registry entry the IR audit lowers) and export its program; returns
    the metadata dict."""
    fn, args, kwargs, _ = target.build()
    return store.save(target.name, fn, args, kwargs, log=log)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m pystella_tpu.obs.warmstart",
        description="AOT-export the dispatched step programs "
                    "(jax.export) and verify stored artifacts against "
                    "the live compiler stack")
    sub = p.add_subparsers(dest="cmd", required=True)
    pe = sub.add_parser("export", help="build + serialize the lint "
                                       "target registry's programs")
    pe.add_argument("--out", default=None,
                    help="artifact directory (default: "
                         "$PYSTELLA_WARMSTART_DIR)")
    pe.add_argument("--target", action="append", default=None,
                    help="target name (repeatable; default: all)")
    pe.add_argument("--cache-dir", default=None,
                    help="also wire the persistent compilation cache "
                         "here, so verification populates it")
    pv = sub.add_parser("verify", help="check every artifact against "
                                       "the live versions/flags")
    pv.add_argument("--dir", default=None,
                    help="artifact directory (default: "
                         "$PYSTELLA_WARMSTART_DIR)")
    pl = sub.add_parser(
        "list", help="enumerate stored artifacts with fingerprint, "
                     "version, and match-status against the live "
                     "process (informational: always exit 0)")
    pl.add_argument("--dir", default=None,
                    help="artifact directory (default: "
                         "$PYSTELLA_WARMSTART_DIR)")
    pg = sub.add_parser(
        "gc", help="garbage-collect STALE exports (version- or "
                   "flag-mismatched against the live process) — the "
                   "warm pool needs a tended store; matching artifacts "
                   "are never touched")
    pg.add_argument("--dir", default=None,
                    help="artifact directory (default: "
                         "$PYSTELLA_WARMSTART_DIR)")
    pg.add_argument("--dry-run", action="store_true",
                    help="report what would be removed, remove nothing")
    args = p.parse_args(argv)

    if args.cmd == "export":
        # the lint CLI's platform dance: the targets want the CPU-safe
        # 8-device mesh unless the operator explicitly dialed hardware
        from pystella_tpu.lint.__main__ import _force_platform
        _force_platform()
        from pystella_tpu.lint.targets import targets_by_name
        if args.cache_dir:
            _memory.ensure_compilation_cache(args.cache_dir)
        try:
            store = WarmstartStore(args.out)
        except ValueError as e:
            print(f"warmstart: {e}", file=sys.stderr)
            return 2
        try:
            targets = targets_by_name(args.target or None).values()
        except KeyError as e:
            print(f"warmstart: {e}", file=sys.stderr)
            return 2
        failures = 0
        for tgt in targets:
            try:
                meta = export_target(store, tgt)
            except Exception as e:  # noqa: BLE001 — report and continue
                failures += 1
                print(f"warmstart: export {tgt.name} FAILED: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                continue
            print(f"warmstart: exported {tgt.name} "
                  f"[{meta['fingerprint']}] "
                  f"({meta['serialized_bytes']:,} B) -> {store.root}")
        return 1 if failures else 0

    try:
        store = WarmstartStore(args.dir)
    except ValueError as e:
        print(f"warmstart: {e}", file=sys.stderr)
        return 2

    if args.cmd == "gc":
        kept, removed = gc_store(store, dry_run=args.dry_run)
        verb = "would remove" if args.dry_run else "removed"
        for meta in removed:
            print(f"warmstart: {verb} {meta.get('label')} "
                  f"[{meta.get('fingerprint')}] "
                  f"({'; '.join(meta.get('problems') or [])})")
        print(f"warmstart: gc {store.root}: {len(kept)} kept, "
              f"{len(removed)} stale artifact(s) {verb}")
        return 0

    metas = store.entries()
    if not metas:
        print(f"warmstart: no artifacts under {store.root}",
              file=sys.stderr)
        return 0 if args.cmd == "list" else 1
    stale = 0
    for meta in metas:
        problems = store._mismatches(meta)
        tag = "OK" if not problems else "STALE"
        stale += bool(problems)
        extra = ""
        if args.cmd == "list":
            versions = (meta.get("components") or {}).get("versions")
            extra = (f" jax={_fmt_versions(versions)} "
                     f"{meta.get('serialized_bytes', 0):,} B "
                     f"devices={meta.get('nr_devices')}")
        print(f"warmstart: {meta.get('label')} "
              f"[{meta.get('fingerprint')}] {tag}{extra}"
              + (f" ({'; '.join(problems)})" if problems else ""))
    if args.cmd == "list":
        return 0
    return 1 if stale else 0


def _fmt_versions(versions):
    if not isinstance(versions, dict):
        return "?"
    return "/".join(str(versions.get(k)) for k in ("jax", "jaxlib"))


if __name__ == "__main__":
    sys.exit(main())
