"""Rolling-window SLO burn-rate monitor over the live event stream.

The post-hoc half of the SLO story lives in :mod:`pystella_tpu.obs.
ledger` + :mod:`pystella_tpu.obs.gate`: after the run, the report's
``service``/``latency`` sections are compared against a baseline with
factor+floor bars. This module is the LIVE half: an
:class:`SLOMonitor` subscribes to :meth:`EventLog.emit
<pystella_tpu.obs.events.EventLog.subscribe>` (in-process push, not log
tailing) and evaluates the same metrics as rolling windows *while the
server is serving*, so an operator — or the ``/slo`` endpoint of
:mod:`pystella_tpu.obs.live` — sees SLO burn before retire time.

**Legs** (each maps 1:1 to a gate verdict, ``doc/service.md`` has the
runbook table):

==================  =====================================================
leg                 windowed value (source events)
==================  =====================================================
``queue_p95``       p95 of ``service_dispatch.queue_latency_s``
``warm_ttfs``       p50 of warm ``service_lease.ttfs_s``
``deadline_miss``   miss fraction over ``member_result`` deadline
                    verdicts (``deadline_missed``)
``incident_rate``   count of ``fault_detected`` events in the window
``perf_regression`` open-anomaly fraction over ``perf_anomaly`` /
                    ``perf_recovered`` transitions (obs.perf)
==================  =====================================================

**Bars.** Each leg's alert bar is built from an *objective* with the
SAME factor+floor arithmetic the gate applies to its baseline:
``bar = max(objective * factor, objective + floor)`` — the gate fails a
report when ``current > baseline * factor AND current - baseline >
floor``, and a windowed value above this bar is exactly a live sample
of that verdict. Defaults reuse the gate's knob defaults (queue 2.5× /
0.5 s, TTFS 2.5× / 1 s, deadline-miss 2× / 0.05, incidents bar 0 —
any detected fault burns until it ages out).

**Multi-window burn.** The standard fast/slow split: the breach must
hold over BOTH the fast window (``PYSTELLA_SLO_FAST_WINDOW_S``, it is
still happening) and the slow window (``PYSTELLA_SLO_SLOW_WINDOW_S``,
it is sustained, not one blip) before ``slo_alert`` fires; the alert
resolves (``slo_resolved``) when the fast window recovers below the
bar — or empties, aging the offending samples out. Both events are
registered kinds and land in the run record, so live alerts become
gate-visible evidence: the ledger's ``alerts`` section counts them and
the gate refuses a report whose unresolved burn alert contradicts a
green post-hoc SLO section (``--no-alerts`` opts out).

A leg spec may set ``window_samples`` to cap both windows at the last
N samples — the seeded smoke configuration
(:mod:`pystella_tpu.service.loadgen`) uses ``window_samples=1`` on the
deadline leg so the one guaranteed miss fires the alert and the next
guaranteed hit resolves it, deterministically, inside a seconds-long
run.

Usage (the scenario service wires this up itself when
``PYSTELLA_LIVE_PORT`` is on, or accepts an explicit monitor)::

    from pystella_tpu.obs import events, slo
    monitor = slo.SLOMonitor()
    events.get_log().subscribe(monitor.handle)
    ...serve...
    events.get_log().unsubscribe(monitor.handle)
    monitor.state()     # the /slo payload

The ingest path is a few dict lookups and a deque append — the
monitor tracks its own cumulative ``ingest_s`` so the emit-path
overhead is itself an auditable number (the smoke e2e pins it < 2% of
the serve wall).
"""

from __future__ import annotations

import collections
import threading
import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs.ledger import percentile as _percentile

__all__ = ["DEFAULT_LEGS", "SLOMonitor", "leg_bar"]

#: per-leg defaults: the objective each windowed value is held to and
#: the gate's factor+floor bars (obs.gate.compare_reports defaults for
#: the matching verdict). ``kind`` picks the windowed statistic.
DEFAULT_LEGS = {
    "queue_p95": {"objective": 0.0, "factor": 2.5, "floor": 0.5,
                  "kind": "p95"},
    "warm_ttfs": {"objective": 0.0, "factor": 2.5, "floor": 1.0,
                  "kind": "p50"},
    "deadline_miss": {"objective": 0.0, "factor": 2.0, "floor": 0.05,
                      "kind": "rate"},
    "incident_rate": {"objective": 0.0, "factor": 1.0, "floor": 0.0,
                      "kind": "count"},
    # perf_anomaly/perf_recovered land as 1.0/0.0 samples; bar 0.5
    # means any open anomaly in both windows burns, and the recovery
    # sample (or age-out) resolves — the deadline_miss pattern applied
    # to the continuous-performance plane (obs.perf)
    "perf_regression": {"objective": 0.0, "factor": 2.0, "floor": 0.5,
                        "kind": "rate"},
}

#: bounded per-leg sample memory — a monitor on a weeks-lived server
#: must not grow without bound even with generous windows
_MAX_SAMPLES = 4096

#: per-leg exposition history on ``/slo`` (``state()["samples"]``) —
#: bounded separately from the window deque so window caps do not hide
#: samples from federation readers
_RECENT_SAMPLES = 256


def leg_bar(objective, factor, floor):
    """The alert bar for one leg: the gate fails when ``current >
    baseline * factor AND current - baseline > floor``, so the live
    bar over objective ``b`` is ``max(b * factor, b + floor)`` — the
    smallest value that would fail both gate conditions."""
    objective = float(objective)
    return max(objective * float(factor), objective + float(floor))


def _window_value(kind, samples):
    """The windowed statistic over ``[(ts, value), ...]`` samples."""
    if kind == "count":
        return float(len(samples))
    if not samples:
        return None
    vals = sorted(v for _, v in samples)
    if kind == "p95":
        return _percentile(vals, 95)
    if kind == "p50":
        return _percentile(vals, 50)
    if kind == "rate":
        return sum(vals) / len(vals)
    raise ValueError(f"unknown window kind {kind!r}")


class _LegState:
    """One leg's rolling samples and alert state machine."""

    def __init__(self, name, spec, fast_s, slow_s, min_samples):
        self.name = name
        self.objective = float(spec.get("objective", 0.0))
        self.factor = float(spec.get("factor", 1.0))
        self.floor = float(spec.get("floor", 0.0))
        self.kind = spec.get("kind", "rate")
        self.fast_s = float(spec.get("fast_window_s", fast_s))
        self.slow_s = float(spec.get("slow_window_s", slow_s))
        self.min_samples = int(spec.get("min_samples", min_samples))
        ws = spec.get("window_samples")
        maxlen = min(_MAX_SAMPLES, int(ws)) if ws else _MAX_SAMPLES
        self.samples = collections.deque(maxlen=maxlen)
        # exposition history, decoupled from the window deque: a
        # window_samples=1 leg still shows its recent samples on /slo,
        # so a fleet aggregator scraping after fire+resolve can ingest
        # BOTH verdicts instead of only the survivor
        self.recent = collections.deque(maxlen=_RECENT_SAMPLES)
        self.bar = leg_bar(self.objective, self.factor, self.floor)
        self.alerting = False
        self.fired_ts = None
        self.alerts = 0
        self.resolved = 0
        self.total_alert_s = 0.0
        self.last = {}

    def add(self, ts, value):
        self.samples.append((float(ts), float(value)))
        self.recent.append((float(ts), float(value)))

    def evaluate(self, now):
        """Windowed values + the fire/resolve transition (if any);
        returns ``"fired"`` / ``"resolved"`` / ``None``."""
        while self.samples and self.samples[0][0] < now - self.slow_s:
            self.samples.popleft()
        slow = list(self.samples)
        fast = [s for s in slow if s[0] >= now - self.fast_s]
        v_fast = _window_value(self.kind, fast)
        v_slow = _window_value(self.kind, slow)
        burn = (lambda v: None if v is None else
                (v / self.bar if self.bar > 0 else
                 (float("inf") if v > 0 else 0.0)))
        self.last = {
            "value_fast": v_fast, "value_slow": v_slow,
            "burn_fast": burn(v_fast), "burn_slow": burn(v_slow),
            "n_fast": len(fast), "n_slow": len(slow),
        }
        breach_fast = v_fast is not None and v_fast > self.bar
        breach_slow = v_slow is not None and v_slow > self.bar
        enough = (self.kind == "count"
                  or len(fast) >= self.min_samples)
        if not self.alerting and breach_fast and breach_slow and enough:
            self.alerting = True
            self.fired_ts = float(now)
            self.alerts += 1
            return "fired"
        if self.alerting and not breach_fast:
            self.alerting = False
            duration = max(0.0, float(now) - (self.fired_ts or now))
            self.total_alert_s += duration
            self.resolved += 1
            self.last["duration_s"] = duration
            return "resolved"
        return None

    @property
    def flaps(self):
        """Re-fires after a resolve: fire/resolve/fire churn the gate
        warns on when it grows past the baseline's."""
        return max(0, self.alerts - 1)

    def state(self):
        return {
            "objective": self.objective, "factor": self.factor,
            "floor": self.floor, "bar": self.bar, "kind": self.kind,
            "fast_window_s": self.fast_s, "slow_window_s": self.slow_s,
            "min_samples": self.min_samples,
            "alerting": self.alerting,
            "active_since": self.fired_ts if self.alerting else None,
            "alerts": self.alerts, "resolved": self.resolved,
            "flaps": self.flaps,
            "total_alert_s": round(self.total_alert_s, 6),
            "samples": [[round(ts, 6), v] for ts, v in self.recent],
            **self.last,
        }


class SLOMonitor:
    """The live SLO burn-rate monitor (module docstring).

    :arg legs: ``{name: spec}`` overriding/selecting legs. ``None``
        enables every :data:`DEFAULT_LEGS` entry; passing a dict
        enables ONLY the named legs, each spec merged over its default
        (unknown names need a full spec). Per-leg keys: ``objective``,
        ``factor``, ``floor``, ``kind``, ``fast_window_s``,
        ``slow_window_s``, ``min_samples``, ``window_samples``.
    :arg fast_window_s / slow_window_s / min_samples: window defaults
        (fall back to the registered ``PYSTELLA_SLO_*`` knobs).
    :arg label: tag carried on every alert event.
    :arg emit: emit ``slo_alert``/``slo_resolved`` events on
        transitions (default; ``False`` keeps the monitor silent for
        embedding).
    """

    def __init__(self, legs=None, fast_window_s=None, slow_window_s=None,
                 min_samples=None, label="slo", emit=True):
        if fast_window_s is None:
            fast_window_s = _config.get_float("PYSTELLA_SLO_FAST_WINDOW_S")
        if slow_window_s is None:
            slow_window_s = _config.get_float("PYSTELLA_SLO_SLOW_WINDOW_S")
        if min_samples is None:
            min_samples = _config.get_int("PYSTELLA_SLO_MIN_SAMPLES")
        self.label = str(label)
        self.emit_events = bool(emit)
        chosen = (dict(DEFAULT_LEGS) if legs is None
                  else {name: {**DEFAULT_LEGS.get(name, {}), **(spec or {})}
                        for name, spec in legs.items()})
        self._legs = {name: _LegState(name, spec, fast_window_s,
                                      slow_window_s, min_samples)
                      for name, spec in chosen.items()}
        self._lock = threading.Lock()
        self.ingested = 0
        self.ingest_s = 0.0

    # -- the EventLog subscriber --------------------------------------------

    def handle(self, record):
        """The :meth:`~pystella_tpu.obs.events.EventLog.subscribe`
        callback: route one emitted record into its leg's window and
        re-evaluate. Cheap by design (dict lookups + a deque append);
        cumulative cost lands in ``ingest_s`` so the emit-path overhead
        is auditable."""
        t0 = time.perf_counter()
        try:
            self._ingest(record)
        finally:
            self.ingested += 1
            self.ingest_s += time.perf_counter() - t0

    def _ingest(self, record):
        kind = record.get("kind")
        data = record.get("data") or {}
        ts = record.get("ts") or time.time()
        hits = []
        if kind == "service_dispatch":
            q = data.get("queue_latency_s")
            if isinstance(q, (int, float)):
                hits.append(("queue_p95", float(q)))
        elif kind == "service_lease":
            t = data.get("ttfs_s")
            if data.get("warm") and isinstance(t, (int, float)):
                hits.append(("warm_ttfs", float(t)))
        elif kind == "member_result":
            if "deadline_missed" in data:
                hits.append(("deadline_miss",
                             1.0 if data["deadline_missed"] else 0.0))
        elif kind == "fault_detected":
            hits.append(("incident_rate", 1.0))
        elif kind == "perf_anomaly":
            hits.append(("perf_regression", 1.0))
        elif kind == "perf_recovered":
            hits.append(("perf_regression", 0.0))
        touched = False
        for name, value in hits:
            leg = self._legs.get(name)
            if leg is not None:
                with self._lock:
                    leg.add(ts, value)
                touched = True
        if touched:
            self.evaluate(now=ts)

    # -- direct ingestion (federation seam) ----------------------------------

    def add_sample(self, leg, value, ts=None, evaluate=True):
        """Feed one ``(ts, value)`` sample straight into a leg's
        window, bypassing the event-kind routing of :meth:`handle`.
        This is the federation seam: :class:`~pystella_tpu.obs.fleet.
        FleetAggregator` replays per-replica ``/slo`` samples through
        a fleet-level monitor with the same window machinery. Unknown
        legs raise ``KeyError``; ``evaluate=True`` (default) runs the
        fire/resolve state machine at the sample's timestamp and
        returns its transitions (``[]`` otherwise)."""
        state = self._legs[leg]
        ts = time.time() if ts is None else float(ts)
        with self._lock:
            state.add(ts, float(value))
        self.ingested += 1
        if evaluate:
            return self.evaluate(now=ts)
        return []

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, now=None):
        """Re-evaluate every leg at ``now`` (default wall clock) and
        emit any fire/resolve transitions; the ``/slo`` endpoint calls
        this per scrape so aging-out resolution does not wait for the
        next ingested event. Returns the transitions as
        ``[(leg, "fired"|"resolved"), ...]``."""
        now = time.time() if now is None else float(now)
        transitions = []
        with self._lock:
            for name, leg in self._legs.items():
                change = leg.evaluate(now)
                if change:
                    transitions.append((name, change, dict(leg.last),
                                        leg))
        for name, change, last, leg in transitions:
            if not self.emit_events:
                continue
            if change == "fired":
                _events.emit("slo_alert", leg=name,
                             value=last.get("value_fast"),
                             bar=leg.bar,
                             burn_fast=last.get("burn_fast"),
                             burn_slow=last.get("burn_slow"),
                             n_fast=last.get("n_fast"),
                             n_slow=last.get("n_slow"),
                             objective=leg.objective,
                             factor=leg.factor, floor=leg.floor,
                             label=self.label)
            else:
                _events.emit("slo_resolved", leg=name,
                             value=last.get("value_fast"),
                             bar=leg.bar,
                             duration_s=round(
                                 last.get("duration_s") or 0.0, 6),
                             label=self.label)
        return [(name, change) for name, change, _, _ in transitions]

    # -- introspection -------------------------------------------------------

    def state(self):
        """The JSON-safe burn-rate state (the ``/slo`` payload): every
        leg's windowed values, burn rates, bar, and alert bookkeeping,
        plus monitor totals."""
        with self._lock:
            legs = {name: leg.state()
                    for name, leg in self._legs.items()}
        unresolved = sorted(n for n, s in legs.items() if s["alerting"])
        return {
            "label": self.label,
            "legs": legs,
            "alerting": unresolved,
            "alerts_total": sum(s["alerts"] for s in legs.values()),
            "resolved_total": sum(s["resolved"] for s in legs.values()),
            "flaps_total": sum(s["flaps"] for s in legs.values()),
            "ingested": self.ingested,
            "ingest_s": round(self.ingest_s, 6),
        }
