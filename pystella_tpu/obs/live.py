"""In-process live telemetry endpoint: ``/metrics``, ``/healthz``, ``/slo``.

Every observability layer before this one is post-hoc — events land in
JSONL and the ledger/gate turn them into verdicts after the run. A
persistent scenario service needs the other half of the standard
production-telemetry split: a scrape endpoint an operator (or a
Prometheus collector) can hit *while the server is serving*. This
module is that half, stdlib-only by design (``http.server`` on a
daemon thread — the serving path must not grow a dependency):

- ``GET /metrics`` — Prometheus text exposition (version 0.0.4)
  rendered from :meth:`MetricsRegistry.snapshot_typed
  <pystella_tpu.obs.metrics.MetricsRegistry.snapshot_typed>` (every
  registry counter/gauge/timer, ``pystella_``-prefixed, dots folded to
  underscores) plus the service gauges computed fresh per scrape from
  :meth:`ScenarioService.live_status
  <pystella_tpu.service.ScenarioService.live_status>`: queue depth per
  priority class and per tenant, active leases, warm-pool entries by
  fingerprint match, and the last chunk's member-steps/s.
- ``GET /healthz`` — liveness + readiness JSON derived from the serve
  loop and supervisor state (``serving``, the active lease and whether
  its supervisor is draining, queue depth, uptime). Bare ``/healthz``
  answers 200 whenever the process is alive (the liveness probe);
  ``/healthz?ready`` keys the status code on readiness instead (503
  while the serve loop is not running), so status-code-only probers
  cover both.
- ``GET /slo`` — the current burn-rate state of the attached
  :class:`~pystella_tpu.obs.slo.SLOMonitor` as JSON (the monitor is
  re-evaluated per scrape, so aging-out resolution is visible without
  waiting for the next event).

Opt-in: :func:`start_from_env` reads the registered
``PYSTELLA_LIVE_PORT`` (0/unset = off — the default; the live plane
must cost nothing when disabled) and binds 127.0.0.1 only — this is an
operator loopback/sidecar endpoint, not a public listener. The
scenario service calls it around :meth:`serve
<pystella_tpu.service.ScenarioService.serve>`; a driver can also run
one standalone around any instrumented loop::

    from pystella_tpu.obs import live
    server = live.LiveServer(service=svc, slo=monitor)  # ephemeral port
    server.start()
    print(server.url("/metrics"))
    ...
    server.close()
"""

from __future__ import annotations

import hashlib
import json
import math
import re
import sys
import threading
import time

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import metrics as _metrics

__all__ = ["LiveServer", "build_info_labels", "render_prometheus",
           "start_from_env"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(key):
    return "pystella_" + _NAME_RE.sub("_", str(key))


def _prom_label(value):
    """Escape a label value per the Prometheus text format (backslash,
    double quote, newline) — tenant names are arbitrary caller strings
    and must not be able to break, or inject lines into, the
    exposition."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_value(v):
    if v is None or (isinstance(v, float) and math.isnan(v)):
        return "NaN"
    return format(float(v), ".10g")


#: (versions, flags digest) computed once per process — the compiler
#: stack cannot change under a running server, and importlib.metadata
#: lookups are too slow for a per-scrape path
_BUILD_INFO_STATIC = None


def build_info_labels():
    """The label set of the ``pystella_build_info`` gauge: the
    jax/jaxlib/libtpu version triple, the scheduler-relevant flag
    fingerprint digest (:func:`pystella_tpu.parallel.overlap.
    flags_fingerprint`), and the device kind of an already-imported
    jax. This is the skew-detection surface — a fleet aggregator can
    compare stacks from the exposition alone, no registry read
    required. Absent values render as ``"none"`` so the label set is
    stable across environments."""
    global _BUILD_INFO_STATIC
    if _BUILD_INFO_STATIC is None:
        from pystella_tpu.obs import ledger as _ledger
        from pystella_tpu.parallel.overlap import flags_fingerprint
        versions = _ledger.runtime_versions()
        digest = hashlib.sha256(json.dumps(
            flags_fingerprint(), sort_keys=True).encode()).hexdigest()[:12]
        _BUILD_INFO_STATIC = (versions, digest)
    versions, digest = _BUILD_INFO_STATIC
    device_kind = "none"
    jax = sys.modules.get("jax")  # never import jax for a scrape
    if jax is not None:
        try:
            device_kind = str(jax.devices()[0].device_kind)
        except Exception:  # noqa: BLE001 — a scrape must not kill it
            pass
    return {
        "jax": versions.get("jax") or "none",
        "jaxlib": versions.get("jaxlib") or "none",
        "libtpu": versions.get("libtpu") or "none",
        "flags_fingerprint": digest,
        "device_kind": device_kind,
    }


def render_prometheus(registry=None, status=None):
    """The ``/metrics`` body: the registry's typed snapshot plus the
    service-status gauges, Prometheus text format. Pure function of its
    inputs so the exposition is testable without a socket."""
    reg = registry if registry is not None else _metrics.registry()
    lines = []

    def metric(name, kind, value, labels=None, help=None):
        if help:
            lines.append(f"# HELP {name} {help}")
        lines.append(f"# TYPE {name} {kind}")
        tag = ("{" + ",".join(f'{k}="{_prom_label(v)}"'
                              for k, v in sorted(labels.items())) + "}"
               if labels else "")
        lines.append(f"{name}{tag} {_prom_value(value)}")

    metric("pystella_build_info", "gauge", 1.0,
           labels=build_info_labels(),
           help="constant 1; the labels carry the replica's compiler "
                "stack (versions, flag fingerprint, device kind) for "
                "fleet skew detection")

    for key, (value, kind) in reg.snapshot_typed().items():
        metric(_prom_name(key), kind, value)

    if status:
        by_class = status.get("queue_by_priority") or {}
        by_tenant = status.get("queue_by_tenant") or {}
        name = "pystella_service_queue_depth"
        lines.append(f"# HELP {name} queued requests (per priority "
                     "class / tenant; overall unlabeled)")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} "
                     f"{_prom_value(status.get('queue_depth'))}")
        for cls, n in sorted(by_class.items()):
            lines.append(f'{name}{{priority="{_prom_label(cls)}"}} '
                         f"{_prom_value(n)}")
        for tenant, n in sorted(by_tenant.items()):
            lines.append(f'{name}{{tenant="{_prom_label(tenant)}"}} '
                         f"{_prom_value(n)}")
        metric("pystella_service_active_leases", "gauge",
               status.get("active_leases"),
               help="leases currently holding requests")
        pool = status.get("warm_pool") or {}
        name = "pystella_service_warm_pool_entries"
        lines.append(f"# HELP {name} armed warm-pool entries by live "
                     "fingerprint match")
        lines.append(f"# TYPE {name} gauge")
        for match in ("ok", "stale"):
            lines.append(f'{name}{{fingerprint="{match}"}} '
                         f"{_prom_value(pool.get(match, 0))}")
        metric("pystella_service_last_chunk_member_steps_per_s",
               "gauge", status.get("last_chunk_member_steps_per_s"),
               help="member-steps/s of the most recent batched chunk")
        metric("pystella_service_serving", "gauge",
               1.0 if status.get("serving") else 0.0,
               help="1 while the serve loop is draining the queue")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    # the server_version/sys_version default header leaks stdlib
    # versions; keep the surface anonymous and quiet
    server_version = "pystella-live"
    sys_version = ""

    def log_message(self, *args):  # no stderr chatter per scrape
        pass

    def _send(self, code, body, content_type):
        payload = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):  # noqa: N802 — http.server's contract
        live = self.server.live
        path, _, query = self.path.partition("?")
        path = path.rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(200, live.metrics_text(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                body = live.healthz()
                # bare /healthz is the LIVENESS probe: answering at all
                # means alive -> 200. /healthz?ready keys the status
                # code on readiness (the serve loop running), so a
                # status-code-only readiness prober works too.
                code = 200
                if "ready" in query and not body.get("ready"):
                    code = 503
                self._send(code, json.dumps(body, sort_keys=True),
                           "application/json")
            elif path == "/slo":
                self._send(200, json.dumps(live.slo_state(),
                                           sort_keys=True, default=str),
                           "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "unknown path",
                     "endpoints": ["/metrics", "/healthz", "/slo"]}),
                    "application/json")
        except Exception as e:  # noqa: BLE001 — a scrape must not kill it
            self._send(500, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}),
                "application/json")


class LiveServer:
    """The telemetry endpoint on a daemon thread (module docstring).

    :arg port: TCP port on 127.0.0.1; ``None`` binds an ephemeral port
        (tests, sidecars that read :attr:`port` back).
    :arg service: optional :class:`~pystella_tpu.service.
        ScenarioService` (anything with a ``live_status()`` -> dict) —
        feeds the service gauges and the readiness fields.
    :arg slo: optional :class:`~pystella_tpu.obs.slo.SLOMonitor` for
        ``/slo`` (re-evaluated per scrape).
    :arg registry: metrics registry override (default: the process
        registry).
    :arg label: tag on the ``live_serve`` event.
    """

    def __init__(self, port=None, service=None, slo=None, registry=None,
                 label="live"):
        self.service = service
        self.slo = slo
        self.registry = registry
        self.label = str(label)
        self._t0 = time.time()
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", int(port) if port else 0), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.live = self
        # the bind happens inside ThreadingHTTPServer.__init__, so the
        # resolved port — ephemeral included — is final HERE, before
        # start(): a replica registry may publish url() at announce
        # time without racing the first scrape
        self.port = int(self._httpd.server_port)
        if self.port <= 0:
            raise OSError(f"live endpoint bound no port (got "
                          f"{self.port}); cannot publish a URL")
        self._thread = None

    # -- payloads (also the test seam: no socket required) ------------------

    def metrics_text(self):
        status = None
        if self.service is not None:
            status = self.service.live_status()
        return render_prometheus(registry=self.registry, status=status)

    def healthz(self):
        out = {"ok": True, "alive": True, "ts": time.time(),
               "uptime_s": round(time.time() - self._t0, 3),
               "port": self.port, "label": self.label,
               "ready": True}
        if self.service is not None:
            status = self.service.live_status()
            out.update({
                "ready": bool(status.get("serving")),
                "serving": status.get("serving"),
                "queue_depth": status.get("queue_depth"),
                "active_lease": status.get("active_lease"),
                "supervisor": status.get("supervisor"),
                "leases_completed": status.get("leases_completed"),
                "capacity": status.get("capacity"),
            })
        if self.slo is not None:
            out["slo_alerting"] = self.slo.state()["alerting"]
        return out

    def slo_state(self):
        if self.slo is None:
            return {"enabled": False}
        self.slo.evaluate()
        return {"enabled": True, **self.slo.state()}

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Serve on a daemon thread; returns ``self``. Emits a
        ``live_serve`` event so the run record shows the endpoint (and
        its port) was up."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"pystella-live:{self.port}", daemon=True)
            self._thread.start()
            _events.emit("live_serve", port=self.port, url=self.url(),
                         endpoints=["/metrics", "/healthz", "/slo"],
                         label=self.label)
        return self

    def url(self, path="/"):
        """The endpoint URL — valid from construction (the port is
        bound in ``__init__``), so it can be published before
        :meth:`start`."""
        return f"http://127.0.0.1:{self.port}{path}"

    def close(self):
        """Stop serving and release the port (idempotent)."""
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


def start_from_env(service=None, slo=None, registry=None, label="live",
                   port=None):
    """Start a :class:`LiveServer` when the registered
    ``PYSTELLA_LIVE_PORT`` names a port; return ``None`` when it is
    0/unset (the live plane is strictly opt-in). An explicit ``port``
    overrides the environment: an int binds that port, ``"auto"``
    binds an ephemeral one (two in-process replicas cannot share one
    env var — the fleet drill passes ``"auto"`` per replica). A port
    that cannot be bound degrades to ``None`` with a stderr warning —
    live telemetry must never kill the serving process."""
    if port is None:
        port = _config.get_int("PYSTELLA_LIVE_PORT") or 0
    if port != "auto" and int(port) <= 0:
        return None
    try:
        return LiveServer(port=None if port == "auto" else int(port),
                          service=service, slo=slo,
                          registry=registry, label=label).start()
    except (OSError, OverflowError, ValueError) as e:
        # OSError: port in use / no permission; OverflowError: a port
        # outside 0-65535 (socket.bind raises it, NOT OSError)
        import sys
        print(f"pystella_tpu.obs.live: cannot bind port {port} ({e}); "
              "live endpoint disabled for this run", file=sys.stderr)
        return None
