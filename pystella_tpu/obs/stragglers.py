"""Cross-host straggler attribution for the continuous-performance plane.

A pod-scale SPMD run is as fast as its slowest host: one machine with a
thermal throttle, a noisy neighbour, or a failing HBM bank drags every
collective, and the fleet-wide step time shows the symptom without the
culprit. This module names the culprit: each host contributes its
recent-window step-time mean, the vector is gathered through the same
:func:`~pystella_tpu.parallel.multihost.all_gather_hosts` path the
metrics registry federates over, and :func:`attribute` reduces it to a
JSON-safe record — per-host means, the slowest host, and its skew over
the fleet median — that :class:`~pystella_tpu.obs.perf.PerfMonitor`
embeds in every ``perf_anomaly`` payload.

On a single-process run the gather degrades to the local vector (one
host, skew 1.0, never ``skewed``), so the attribution path is exercised
by every tier-1 drill without a cluster.
"""

from __future__ import annotations

__all__ = ["attribute", "host_means"]

#: slowest-host mean over fleet-median mean beyond which the record is
#: flagged ``skewed`` — 1.25x is well past ICI jitter but inside what a
#: single throttled host does to a lockstep mesh
DEFAULT_SKEW_FACTOR = 1.25


def host_means(window_ms):
    """Every host's mean of its recent step-time window, as a list of
    floats indexed by host (jax process index). Gathers through
    :func:`~pystella_tpu.parallel.multihost.all_gather_hosts` — all
    hosts must call this in lockstep (the SPMD drivers' window-report
    cadence does by construction); a single-process run returns its
    local mean as a one-element list."""
    import numpy as np

    from pystella_tpu.parallel.multihost import all_gather_hosts

    vals = [float(x) for x in window_ms]
    mean = sum(vals) / len(vals) if vals else float("nan")
    gathered = all_gather_hosts(np.array([mean]))
    return [float(row[0]) for row in gathered]


def attribute(window_ms, skew_factor=DEFAULT_SKEW_FACTOR):
    """The straggler record over this host's recent step-time window
    (milliseconds): gather every host's window mean and name the
    slowest one. Returns a JSON-safe dict::

        {"hosts": 4, "mean_ms": [...per host...],
         "slowest": {"host": 2, "mean_ms": 61.4},
         "median_ms": 40.1, "skew": 1.53, "skewed": True}

    ``skew`` is the slowest host's mean over the fleet MEDIAN mean (the
    median, not the mean, so one straggler cannot hide itself by
    inflating its own reference), ``skewed`` flags it past
    ``skew_factor``. Returns ``None`` when the window is empty or the
    gather is unavailable (no jax runtime) — attribution is telemetry
    and must never take down the step loop."""
    if not window_ms:
        return None
    try:
        means = host_means(window_ms)
    except Exception:  # noqa: BLE001 — best-effort telemetry
        return None
    if not means:
        return None
    slowest = max(range(len(means)), key=lambda i: means[i])
    ordered = sorted(means)
    mid = len(ordered) // 2
    median = (ordered[mid] if len(ordered) % 2
              else 0.5 * (ordered[mid - 1] + ordered[mid]))
    skew = means[slowest] / median if median > 0 else 1.0
    return {
        "hosts": len(means),
        "mean_ms": [round(m, 6) for m in means],
        "slowest": {"host": slowest,
                    "mean_ms": round(means[slowest], 6)},
        "median_ms": round(median, 6),
        "skew": round(skew, 6),
        "skewed": bool(len(means) > 1 and skew > float(skew_factor)),
    }
