"""Capacity & goodput plane: HBM footprint ledger, memory-aware
admission evidence, and per-tenant chip-second attribution.

The obs stack watches speed (:mod:`pystella_tpu.obs.perf`), latency
(:mod:`pystella_tpu.obs.spans`), and fleet health
(:mod:`pystella_tpu.obs.fleet`); this module adds the two quantities a
production service budgets against — **HBM capacity** (will this lease
OOM the device?) and **goodput** (of every chip-second burned, how many
became committed member-steps?):

- :class:`FootprintLedger` — per-fingerprint predicted HBM footprints.
  Predictions come from two sources, kept honest by a ``source`` tag:
  ``memory_analysis`` when a ``compile`` event carried the backend's
  byte counts (the AOT path of :func:`~pystella_tpu.obs.memory.
  compile_with_report`), and ``aval_estimate`` when only the call
  signature is known (the warm pool's dispatch-path arms — argument
  bytes from the fingerprint's aval leaves, doubled for the output
  state). Records persist beside the warm-start artifacts as
  ``*.footprint.json`` and loading refuses version/flag drift exactly
  like :meth:`~pystella_tpu.obs.warmstart.WarmstartStore.load`
  (``capacity_stale`` event + ``None``).
- :class:`CapacityMonitor` — the service-side runtime: live watermarks
  polled per chunk from ``device.memory_stats()`` (CPU keeps none, so
  coverage degrades to ``predicted_only`` with an honest flag rather
  than inventing numbers), admission-decision bookkeeping for the
  memory-aware :class:`~pystella_tpu.service.admission.
  AdmissionController`, an OOM forensic bundle on a RESOURCE_EXHAUSTED
  lease failure (resident footprint table + watermark series + the
  admission decision that let it through, via
  :mod:`pystella_tpu.obs.forensics`), and retire-time **chip-second
  attribution**: the PR-13 critical-path phases × chips leased roll up
  into per-tenant, per-request accounts with
  ``goodput = committed member-steps / total chip-seconds`` (replay
  and preempt-drain counted as waste).

Everything leaves as registered ``capacity_*`` events plus
``hbm_bytes_in_use`` / ``hbm_peak_bytes`` / ``goodput`` gauges
(NaN-preregistered so SPMD snapshot vectors line up; rendered as
``pystella_hbm_*`` / ``pystella_goodput`` on ``/metrics``, which the
fleet federation keeps per-replica — a fleet-summed watermark is a
lie, like queue depth). The ledger's ``capacity`` report section and
the gate's capacity verdicts (:mod:`pystella_tpu.obs.gate`) consume
the events; ``python -m pystella_tpu.service usage`` renders the
chargeback table.

Knobs: ``PYSTELLA_CAPACITY_HEADROOM`` (admission budget fraction of
device capacity, default 0.9), ``PYSTELLA_CAPACITY_POLICY``
(``reject`` or ``evict`` — queue-behind-eviction of idle warm
entries), ``PYSTELLA_CAPACITY_BYTES`` (capacity override where the
allocator reports no ``bytes_limit``), ``PYSTELLA_CAPACITY_DIR``
(footprint persistence; defaults to ``PYSTELLA_WARMSTART_DIR``).
"""

from __future__ import annotations

import collections
import json
import math
import os
import re
import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import memory as _memory
from pystella_tpu.obs import metrics as _metrics

__all__ = [
    "FootprintLedger", "CapacityMonitor", "estimate_bytes_from_avals",
    "resource_exhausted_error", "is_resource_exhausted",
    "ON_LEASE_PHASES", "WASTE_PHASES",
]

FOOTPRINT_SCHEMA_VERSION = 1

#: the staleness rule is exactly ``WarmstartStore.load``'s — a
#: footprint predicted under yesterday's compiler stack does not bound
#: today's executable
_STALENESS_KEYS = ("versions", "flags")

#: critical-path phases during which the request actually holds chips
#: (queue/admission hold none — their seconds appear in the account but
#: bill zero chip-seconds)
ON_LEASE_PHASES = (
    "service_compile",
    "service_chunk_compute",
    "service_checkpoint_barrier",
    "service_recovery_replay",
    "service_preempt_drain",
)

#: chip-seconds that bought no committed member-steps
WASTE_PHASES = ("service_recovery_replay", "service_preempt_drain")

#: event kinds the monitor buffers for retire-time attribution (plus
#: any span-carrying record, the ledger's own rule)
_USAGE_KINDS = frozenset((
    "service_request", "service_admit", "service_dispatch",
    "service_requeue", "service_reject", "service_lease",
    "member_result", "deadline_missed",
))


def _safe_label(label):
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", str(label)) or "program"


_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
    "complex128": 16,
}


def _dtype_itemsize(name):
    return _ITEMSIZE.get(str(name), 4)


def estimate_bytes_from_avals(avals):
    """Signature-only footprint estimate from fingerprint aval leaves
    (``obs.memory._leaf_signature`` rows: ``[shape, dtype, ...]``):
    argument bytes = Σ prod(shape) × itemsize, and the predicted
    resident footprint doubles it for the output state (a stepper maps
    state to state; temporaries unknown without a backend compile).
    Returns ``(predicted_bytes, breakdown)`` — ``(None, {})`` when no
    leaf carries a shape."""
    arg_bytes = 0
    seen = False
    for leaf in avals or ():
        if not isinstance(leaf, (list, tuple)) or not leaf:
            continue
        shape = leaf[0]
        if not isinstance(shape, (list, tuple)):
            continue
        n = 1
        for d in shape:
            n *= int(d)
        arg_bytes += n * _dtype_itemsize(leaf[1] if len(leaf) > 1
                                         else "float32")
        seen = True
    if not seen:
        return None, {}
    breakdown = {"argument_bytes": arg_bytes, "output_bytes": arg_bytes,
                 "temp_bytes": None, "generated_code_bytes": None}
    return 2 * arg_bytes, breakdown


def predicted_from_compile(data):
    """Predicted footprint from a ``compile`` event payload carrying
    the backend's ``memory_analysis()`` byte fields; ``None`` when the
    payload has none (the dispatch path on stat-less backends)."""
    parts = [data.get("argument_bytes"), data.get("output_bytes"),
             data.get("temp_bytes")]
    if all(not isinstance(p, (int, float)) for p in parts):
        return None
    total = sum(int(p) for p in parts if isinstance(p, (int, float)))
    alias = data.get("alias_bytes")
    if isinstance(alias, (int, float)):
        total -= int(alias)
    gen = data.get("generated_code_bytes")
    if isinstance(gen, (int, float)):
        total += int(gen)
    return max(total, 0)


def resource_exhausted_error(detail="injected HBM exhaustion "
                             "(fault harness)"):
    """An exception indistinguishable from an allocator OOM as far as
    classification goes: the real ``XlaRuntimeError`` when jaxlib
    exposes it, else a local ``RuntimeError`` subclass of the same
    name; either way the message leads with ``RESOURCE_EXHAUSTED`` —
    the string the OOM forensic path keys on (mirrors
    :func:`~pystella_tpu.resilience.faults.device_loss_error`)."""
    msg = f"RESOURCE_EXHAUSTED: {detail}"
    try:
        from jax._src.lib import xla_client
        return xla_client.XlaRuntimeError(msg)
    except Exception:
        cls = type("XlaRuntimeError", (RuntimeError,), {})
        return cls(msg)


def is_resource_exhausted(error):
    """Does ``error`` look like an allocator OOM? (message-keyed, like
    ``resilience.retry.classify_exception`` — works on the stand-in
    class too)."""
    return "RESOURCE_EXHAUSTED" in str(error)


class FootprintLedger:
    """Per-fingerprint predicted HBM footprints, persisted beside the
    warm-start artifacts.

    :arg root: persistence directory (created lazily). Default:
        ``PYSTELLA_CAPACITY_DIR``, falling back to
        ``PYSTELLA_WARMSTART_DIR``; in-memory only when neither is set.

    A record is ``{schema, label, fingerprint, predicted_bytes,
    breakdown, source, components, created_ts}``; files are named
    ``<label>-<fingerprint>.footprint.json``. :meth:`load` refuses
    version/flag drift against the live process (``capacity_stale``
    event + ``None``) — the same rule
    :meth:`~pystella_tpu.obs.warmstart.WarmstartStore.load` enforces,
    because a footprint predicted for a different compiler stack does
    not bound what today's compiler schedules."""

    def __init__(self, root=None, log=None):
        if root is None:
            root = (_config.getenv("PYSTELLA_CAPACITY_DIR")
                    or _config.getenv("PYSTELLA_WARMSTART_DIR"))
        self.root = root
        self._log = log
        #: (label, fingerprint) -> record, insertion-ordered
        self._records = {}

    def _sink(self):
        return self._log if self._log is not None else _events.get_log()

    # -- recording -----------------------------------------------------------

    def record(self, label, fingerprint, predicted_bytes,
               breakdown=None, source="aval_estimate", components=None,
               persist=True):
        """Store (and optionally persist) one footprint; returns the
        record. A ``memory_analysis`` record is never downgraded by a
        later ``aval_estimate`` for the same program."""
        key = (str(label), str(fingerprint))
        prior = self._records.get(key)
        if (prior is not None and prior.get("source") == "memory_analysis"
                and source != "memory_analysis"):
            return prior
        rec = {
            "schema": FOOTPRINT_SCHEMA_VERSION,
            "label": str(label),
            "fingerprint": str(fingerprint),
            "predicted_bytes": (None if predicted_bytes is None
                                else int(predicted_bytes)),
            "breakdown": dict(breakdown or {}),
            "source": str(source),
            "components": {
                k: (components or {}).get(k) for k in _STALENESS_KEYS},
            "created_ts": time.time(),
        }
        self._records[key] = rec
        self._sink().emit("capacity_footprint", label=rec["label"],
                          fingerprint=rec["fingerprint"],
                          predicted_bytes=rec["predicted_bytes"],
                          source=rec["source"], dir=self.root)
        if persist and self.root:
            try:
                os.makedirs(self.root, exist_ok=True)
                path = os.path.join(
                    self.root,
                    f"{_safe_label(label)}-{fingerprint}.footprint.json")
                with open(path, "w") as f:
                    json.dump(rec, f, indent=1, sort_keys=True)
                    f.write("\n")
            except OSError:
                pass  # footprint telemetry must never kill an arm
        return rec

    def record_entry(self, entry, label=None):
        """Footprint a warm-pool entry from its fingerprint components
        (aval estimate; no backend query). ``None`` when the entry
        carries no usable avals."""
        components = getattr(entry, "components", None) or {}
        fingerprint = getattr(entry, "fingerprint", None)
        if fingerprint is None:
            return None
        predicted, breakdown = estimate_bytes_from_avals(
            components.get("avals"))
        if predicted is None:
            return None
        if label is None:
            label = components.get("label") or getattr(
                entry, "signature", "program")
        return self.record(label, fingerprint, predicted, breakdown,
                           source="aval_estimate", components=components)

    def ingest_compile(self, data):
        """Upgrade the ledger from a ``compile`` event payload carrying
        backend byte counts — the AOT sites make predictions exact
        where an aval estimate stood. No-op without byte fields or a
        fingerprint."""
        fingerprint = data.get("fingerprint")
        predicted = predicted_from_compile(data)
        if fingerprint is None or predicted is None:
            return None
        breakdown = {k: data.get(k) for k in
                     ("argument_bytes", "output_bytes", "temp_bytes",
                      "alias_bytes", "generated_code_bytes")}
        label = data.get("label") or "program"
        return self.record(label, fingerprint, predicted, breakdown,
                           source="memory_analysis",
                           components=_memory.fingerprint_components(label))

    # -- lookup --------------------------------------------------------------

    def get(self, label, fingerprint=None):
        """Newest in-memory record for ``label`` (exact program when
        ``fingerprint`` given); ``None`` when unrecorded."""
        if fingerprint is not None:
            return self._records.get((str(label), str(fingerprint)))
        match = None
        for (lbl, _fp), rec in self._records.items():
            if lbl == str(label):
                match = rec
        return match

    def predicted(self, label, fingerprint=None):
        rec = self.get(label, fingerprint)
        return None if rec is None else rec.get("predicted_bytes")

    def entries(self):
        """All in-memory records, insertion order."""
        return list(self._records.values())

    # -- persistence ---------------------------------------------------------

    def _disk_metas(self, label=None):
        if not self.root or not os.path.isdir(self.root):
            return []
        metas = []
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".footprint.json"):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(meta, dict):
                continue
            if label is not None and meta.get("label") != str(label):
                continue
            metas.append(meta)
        metas.sort(key=lambda m: m.get("created_ts") or 0.0,
                   reverse=True)
        return metas

    def _mismatches(self, meta):
        """Why the live process cannot trust ``meta``'s prediction:
        version/flag drift against the live fingerprint components."""
        live = _memory.fingerprint_components(meta.get("label", ""))
        saved = meta.get("components") or {}
        problems = []
        for key in _STALENESS_KEYS:
            if saved.get(key) != live.get(key):
                problems.append(
                    f"{key}: recorded {saved.get(key)!r} "
                    f"vs live {live.get(key)!r}")
        return problems

    def load(self, label, fingerprint=None):
        """Load the newest persisted footprint for ``label`` that
        MATCHES the live process (a stale newer record must not shadow
        an older matching one); ``None`` plus a ``capacity_stale``
        event when none exists or none matches — the caller then
        re-estimates cold."""
        metas = self._disk_metas(label)
        if fingerprint is not None:
            metas = [m for m in metas
                     if m.get("fingerprint") == str(fingerprint)]
        if not metas:
            self._sink().emit("capacity_stale", label=str(label),
                              reason="no footprint", dir=self.root,
                              fingerprint=fingerprint)
            return None
        first_problems = None
        for meta in metas:
            problems = self._mismatches(meta)
            if not problems:
                key = (meta.get("label"), meta.get("fingerprint"))
                self._records.setdefault(key, meta)
                return meta
            if first_problems is None:
                first_problems = (meta, problems)
        meta, problems = first_problems
        self._sink().emit("capacity_stale", label=str(label),
                          reason="; ".join(problems),
                          fingerprint=meta.get("fingerprint"),
                          candidates=len(metas), dir=self.root)
        return None

    def table(self):
        """The forensic/report footprint table: one row per record."""
        return [{"label": r.get("label"),
                 "fingerprint": r.get("fingerprint"),
                 "predicted_bytes": r.get("predicted_bytes"),
                 "source": r.get("source")}
                for r in self.entries()]


class CapacityMonitor:
    """Service-side capacity runtime (module docstring): watermarks,
    admission bookkeeping, the OOM bundle, and retire-time chip-second
    attribution.

    :arg ledger: a :class:`FootprintLedger` (default-built).
    :arg headroom: admission budget as a fraction of device capacity
        (default ``PYSTELLA_CAPACITY_HEADROOM``).
    :arg capacity_bytes: capacity override (default
        ``PYSTELLA_CAPACITY_BYTES``; unset → the allocator's
        ``bytes_limit``; neither → the admission check honestly skips).
    :arg policy: ``reject`` or ``evict`` (default
        ``PYSTELLA_CAPACITY_POLICY``).

    :meth:`handle` subscribes to the process event log during a serve
    loop (the SLO monitor's channel): it buffers the span-carrying
    records attribution needs and upgrades footprints from byte-bearing
    ``compile`` events. ``capacity_*`` events it emits itself are
    filtered out, and the log's re-entrancy guard keeps emits made
    *from* the callback from echoing back."""

    def __init__(self, ledger=None, headroom=None, capacity_bytes=None,
                 policy=None, device=None, registry=None, log=None):
        self.ledger = ledger if ledger is not None else FootprintLedger(
            log=log)
        if headroom is None:
            headroom = _config.get_float("PYSTELLA_CAPACITY_HEADROOM")
        self.headroom = float(headroom)
        if capacity_bytes is None:
            raw = _config.getenv("PYSTELLA_CAPACITY_BYTES")
            capacity_bytes = int(raw) if raw else None
        self.capacity_bytes = capacity_bytes
        if policy is None:
            policy = _config.getenv("PYSTELLA_CAPACITY_POLICY")
        if policy not in ("reject", "evict"):
            raise ValueError(
                f"capacity policy must be 'reject' or 'evict', "
                f"got {policy!r}")
        self.policy = policy
        self.device = device
        self._log = log
        #: signature -> predicted resident footprint record
        self.resident = {}
        #: watermark samples, oldest first
        self.watermarks = []
        #: lease id -> watermark sample count (coverage)
        self._lease_samples = {}
        #: signature -> last admission decision (the OOM bundle's
        #: "what let it through")
        self.decisions = {}
        self.oom_bundles = []
        self._records = collections.deque(maxlen=65536)
        metrics = registry if registry is not None else _metrics.registry()
        self._metrics = metrics
        # pre-register the gauges at NaN so SPMD hosts' snapshot
        # vectors line up before the first sample/retire
        metrics.gauge("hbm_bytes_in_use")
        metrics.gauge("hbm_peak_bytes", reduce="max")
        metrics.gauge("goodput")

    def _sink(self):
        return self._log if self._log is not None else _events.get_log()

    # -- capacity ------------------------------------------------------------

    def capacity_limit(self):
        """Admittable device bytes: the explicit override, else the
        allocator's ``bytes_limit``; ``None`` where neither exists
        (CPU) — the admission check then skips honestly."""
        if self.capacity_bytes is not None:
            return int(self.capacity_bytes)
        stats = _memory.device_memory_stats(self.device)
        if stats and isinstance(stats.get("bytes_limit"), (int, float)):
            return int(stats["bytes_limit"])
        return None

    def resident_bytes(self):
        """Σ predicted footprint over resident warm-pool programs."""
        return sum(r.get("predicted_bytes") or 0
                   for r in self.resident.values())

    def note_armed(self, signature, entry):
        """Record an armed program's footprint and mark it resident."""
        rec = self.ledger.record_entry(
            entry, label=f"service.{signature}")
        if rec is not None:
            self.resident[str(signature)] = rec
        return rec

    def note_evicted(self, signature):
        self.resident.pop(str(signature), None)

    def admission_check(self, signature, predicted_bytes):
        """The memory-aware admission verdict input: does ``resident +
        candidate`` fit ``capacity × headroom``? Returns a decision
        dict (``admitted``, ``reason``, and the numbers that justify
        it), remembered per signature for the OOM bundle. Unknown
        capacity or footprint admits honestly — a guess that rejects
        real work is worse than an audited skip. An already-armed
        candidate is excluded from the resident sum (leasing it adds
        no new program)."""
        limit = self.capacity_limit()
        resident = sum(r.get("predicted_bytes") or 0
                       for sig, r in self.resident.items()
                       if sig != str(signature))
        decision = {
            "signature": str(signature),
            "predicted_bytes": (None if predicted_bytes is None
                                else int(predicted_bytes)),
            "resident_bytes": int(resident),
            "capacity_bytes": limit,
            "headroom": self.headroom,
            "policy": self.policy,
            "ts": time.time(),
        }
        if limit is None:
            decision.update(admitted=True, reason="no-capacity-limit")
        elif predicted_bytes is None:
            decision.update(admitted=True, reason="unknown-footprint")
        else:
            budget = limit * self.headroom
            fits = resident + predicted_bytes <= budget
            decision.update(
                admitted=fits,
                budget_bytes=int(budget),
                reason="fits" if fits else (
                    f"resident {resident} + predicted "
                    f"{int(predicted_bytes)} > budget {int(budget)} "
                    f"({limit} x {self.headroom})"))
        self.decisions[str(signature)] = decision
        return decision

    def candidate_bytes(self, signature, entry=None):
        """Predicted footprint for an admission candidate: the armed
        entry's record, else the ledger's newest for the service
        label (the pre-arm path — e.g. a persisted or pre-seeded
        footprint), else unknown."""
        label = f"service.{signature}"
        if entry is not None and getattr(entry, "fingerprint", None):
            rec = self.ledger.get(label, entry.fingerprint)
            if rec is None:
                rec = self.ledger.record_entry(entry, label=label)
            if rec is not None:
                return rec.get("predicted_bytes")
        rec = self.ledger.get(label)
        if rec is None:
            rec = self.ledger.load(label)
        return None if rec is None else rec.get("predicted_bytes")

    # -- live watermarks -----------------------------------------------------

    def note_lease(self, lease):
        """Register a lease for coverage accounting (a lease with zero
        watermark samples must show up as a hole, not vanish)."""
        self._lease_samples.setdefault(str(lease), 0)

    def poll_watermark(self, lease=None, step=None):
        """One per-chunk allocator sample: ``bytes_in_use`` /
        ``peak_bytes_in_use`` into the gauges, the series, and a
        ``capacity_watermark`` event. Returns the sample, or ``None``
        on stat-less backends (CPU) — coverage then degrades to
        ``predicted_only`` instead of lying."""
        if lease is not None:
            self.note_lease(lease)
        stats = _memory.device_memory_stats(self.device)
        if stats is None:
            return None
        sample = {
            "ts": time.time(),
            "lease": lease,
            "step": step,
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "limit_bytes": stats.get("bytes_limit"),
        }
        limit = self.capacity_limit()
        if limit:
            used = sample["bytes_in_use"] or 0
            sample["headroom_frac"] = round(
                used / (limit * self.headroom), 6)
        self.watermarks.append(sample)
        if lease is not None:
            self._lease_samples[str(lease)] += 1
        if sample["bytes_in_use"] is not None:
            self._metrics.gauge("hbm_bytes_in_use").set(
                sample["bytes_in_use"])
        if sample["peak_bytes_in_use"] is not None:
            self._metrics.gauge("hbm_peak_bytes", reduce="max").set(
                sample["peak_bytes_in_use"])
        self._sink().emit("capacity_watermark", step=step,
                          **{k: v for k, v in sample.items()
                             if k != "ts"})
        return sample

    # -- event-log subscription ----------------------------------------------

    def handle(self, record):
        """Event-log subscriber: buffer what attribution needs."""
        if not isinstance(record, dict):
            return
        kind = record.get("kind")
        if not isinstance(kind, str) or kind.startswith("capacity_"):
            return
        if kind == "compile":
            self.ledger.ingest_compile(record.get("data") or {})
        if (kind in _USAGE_KINDS or record.get("trace") is not None
                or record.get("span") is not None):
            self._records.append(record)
        if kind == "service_lease":
            lease = (record.get("data") or {}).get("lease")
            if lease is not None:
                self.note_lease(lease)

    # -- live/scrape ---------------------------------------------------------

    def live_fields(self):
        """Lock-free snapshot for ``live_status``/``/healthz``."""
        last = self.watermarks[-1] if self.watermarks else {}
        limit = self.capacity_limit()
        resident = self.resident_bytes()
        out = {
            "capacity_bytes": limit,
            "headroom": self.headroom,
            "resident_predicted_bytes": resident,
            "bytes_in_use": last.get("bytes_in_use"),
            "peak_bytes_in_use": last.get("peak_bytes_in_use"),
            "watermark_samples": len(self.watermarks),
        }
        if limit:
            out["headroom_frac"] = round(
                (last.get("bytes_in_use") or resident)
                / (limit * self.headroom), 6)
        return out

    # -- OOM forensics -------------------------------------------------------

    def write_oom_bundle(self, out_dir, error, signature=None,
                         lease=None, label="service", events_path=None):
        """The OOM forensic bundle: resident-program footprint table,
        watermark series, and the admission decision that let the
        lease through — written via the PR-4 forensics machinery so
        tooling that reads sentinel bundles reads this too. Returns
        the bundle path."""
        from pystella_tpu.obs import forensics as _forensics
        decision = self.decisions.get(str(signature))
        path = _forensics.write_bundle(
            out_dir, step=len(self.watermarks),
            reason="resource_exhausted",
            history=self.watermarks[-256:],
            events_path=events_path,
            config={
                "error": str(error),
                "signature": signature,
                "lease": lease,
                "footprints": self.ledger.table(),
                "resident": sorted(self.resident),
                "resident_bytes": self.resident_bytes(),
                "admission": decision,
                "capacity_bytes": self.capacity_limit(),
                "headroom": self.headroom,
                "policy": self.policy,
            },
            label=label)
        self.oom_bundles.append(path)
        self._sink().emit("capacity_oom", path=path, lease=lease,
                          signature=signature, label=label,
                          error=str(error))
        return path

    # -- chip-second attribution ---------------------------------------------

    def finalize_usage(self, label="service"):
        """Retire-time attribution over the buffered span stream:
        assemble the request trees (:mod:`pystella_tpu.obs.spans`),
        bill each request's on-lease phases × its chip share
        (``chips / members`` of each lease it rode — co-leased members
        split the lease's chips, so per-lease bills sum back to
        ``lease wall × chips``), roll up per tenant, and emit one
        ``capacity_account`` per request plus one ``capacity_usage``
        with the tenant table, goodput, reconciliation, and the
        coverage block the gate audits. Returns the usage dict
        (``None`` when the stream carries no traced request)."""
        from pystella_tpu.obs import spans as _spans
        records = list(self._records)
        asm = _spans.SpanAssembler.from_records(records)
        trees = asm.assemble()
        lease_info = {}
        for rec in records:
            if rec.get("kind") != "service_lease":
                continue
            data = rec.get("data") or {}
            span = rec.get("span")
            if span is not None:
                lease_info[str(span)] = data
        accounts = []
        sink = self._sink()
        for trace in sorted(trees):
            tree = trees[trace]
            shares, chips_list = [], []
            replayed = 0
            for span in tree.leases:
                info = lease_info.get(str(span))
                if not info:
                    continue
                chips = info.get("chips") or 1
                members = max(int(info.get("requests") or 1), 1)
                shares.append(chips / members)
                chips_list.append(int(chips))
                replayed += int(info.get("replayed_member_steps") or 0)
            share = (sum(shares) / len(shares)) if shares else 0.0
            phases = tree.phases or {}
            on_lease_s = sum(phases.get(p, 0.0) for p in ON_LEASE_PHASES)
            chip_s = on_lease_s * share
            waste_s = sum(phases.get(p, 0.0)
                          for p in WASTE_PHASES) * share
            steps = 0
            if tree.status == "completed":
                result = next(
                    (rec.get("data") or {} for rec in records
                     if rec.get("kind") == "member_result"
                     and rec.get("trace") == trace), {})
                steps = int(result.get("steps") or 0)
            account = {
                "id": tree.request_id,
                "trace": trace,
                "tenant": tree.tenant,
                "signature": tree.signature,
                "status": tree.status,
                "chips": max(chips_list) if chips_list else 0,
                "leases": len(tree.leases),
                "share": round(share, 6),
                "queue_s": round(
                    phases.get("service_queue_wait", 0.0), 6),
                "chip_s": round(chip_s, 6),
                "waste_chip_s": round(waste_s, 6),
                "committed_steps": steps,
                "replayed_steps": replayed,
                "goodput": (round(steps / chip_s, 4)
                            if chip_s > 0 else None),
                "label": label,
            }
            accounts.append(account)
            sink.emit("capacity_account", **account)
        if not accounts:
            return None
        tenants = {}
        for a in accounts:
            row = tenants.setdefault(a["tenant"] or "-", {
                "requests": 0, "rejected": 0, "chip_s": 0.0,
                "waste_chip_s": 0.0, "committed_steps": 0})
            row["requests"] += 1
            if a["status"] == "rejected":
                row["rejected"] += 1
            row["chip_s"] += a["chip_s"]
            row["waste_chip_s"] += a["waste_chip_s"]
            row["committed_steps"] += a["committed_steps"]
        total_chip_s = total_steps = total_waste = 0
        for row in tenants.values():
            row["chip_s"] = round(row["chip_s"], 6)
            row["waste_chip_s"] = round(row["waste_chip_s"], 6)
            row["goodput"] = (round(
                row["committed_steps"] / row["chip_s"], 4)
                if row["chip_s"] > 0 else None)
            total_chip_s += row["chip_s"]
            total_steps += row["committed_steps"]
            total_waste += row["waste_chip_s"]
        goodput = (round(total_steps / total_chip_s, 4)
                   if total_chip_s > 0 else None)
        if goodput is not None and math.isfinite(goodput):
            self._metrics.gauge("goodput").set(goodput)
        leases = len(self._lease_samples)
        sampled = sum(1 for n in self._lease_samples.values() if n > 0)
        samples = len(self.watermarks)
        coverage = {
            "leases": leases,
            "leases_sampled": sampled,
            "watermark_samples": samples,
            "predicted_only": samples == 0,
            "complete": leases > 0 and sampled == leases,
        }
        reconciliation = None
        peaks = [w.get("peak_bytes_in_use") for w in self.watermarks
                 if isinstance(w.get("peak_bytes_in_use"), (int, float))]
        if peaks:
            predicted = self.resident_bytes()
            peak = max(peaks)
            reconciliation = {
                "predicted_bytes": int(predicted),
                "peak_bytes_in_use": int(peak),
                "rel_err": round(
                    abs(predicted - peak) / max(peak, 1), 4),
            }
        usage = {
            "label": label,
            "requests": len(accounts),
            "total_chip_s": round(total_chip_s, 6),
            "committed_steps": int(total_steps),
            "waste_chip_s": round(total_waste, 6),
            "goodput": goodput,
            "tenants": tenants,
            "coverage": coverage,
            "reconciliation": reconciliation,
            "capacity_bytes": self.capacity_limit(),
            "headroom": self.headroom,
            "resident_predicted_bytes": self.resident_bytes(),
        }
        sink.emit("capacity_usage", **usage)
        return usage
