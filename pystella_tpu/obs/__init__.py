"""Unified telemetry: run events, metrics, trace scopes, memory reports.

One subsystem behind the pieces that grew up scattered (``utils/monitor``,
``utils/profiling``, ``bench.py``'s hand-rolled orchestrator prints):

- :mod:`pystella_tpu.obs.events` — a structured JSONL run-event log
  (wall + monotonic timestamps, host id, step, event kind, payload) that
  drivers, :class:`~pystella_tpu.HealthMonitor`, checkpointing, the
  multigrid driver, and ``bench.py`` all emit through. Outage and
  contamination forensics become ``grep``s over one file instead of
  archaeology on interleaved stderr.
- :mod:`pystella_tpu.obs.metrics` — a lightweight registry of counters /
  gauges / timers (steps taken, halo exchanges, V-cycles, compile
  events, ms/step EMA, site-updates/s) with a multihost-aware
  :meth:`~pystella_tpu.obs.metrics.MetricsRegistry.aggregate` so host 0
  reports fleet-wide numbers.
- :mod:`pystella_tpu.obs.scope` — ``jax.named_scope`` +
  ``jax.profiler.TraceAnnotation`` wrappers threaded through the hot
  paths, so Perfetto/TensorBoard traces show semantically named regions
  (RK stages, halo exchanges, stencil kernels, multigrid smoothers)
  instead of raw XLA op soup.
- :mod:`pystella_tpu.obs.memory` — compile-time and HBM
  instrumentation: per-computation compile seconds and
  ``memory_analysis()`` byte counts recorded into the event log, plus
  live device-memory reports (the evidence that catches an HBM
  overshoot *before* Mosaic or the allocator rejects it).

See ``doc/observability.md`` for the event schema and driver recipes.
"""

from pystella_tpu.obs.events import (
    EventLog, configure, emit, get_log, read_events)
from pystella_tpu.obs.metrics import (
    Counter, Gauge, MetricsRegistry, Timer, counter, gauge, registry, timer)
from pystella_tpu.obs.scope import (
    has_scope, lowered_scopes, trace_scope, traced)
from pystella_tpu.obs.memory import (
    CompileRecord, compile_with_report, device_memory_report,
    device_memory_stats)

__all__ = [
    "EventLog", "configure", "emit", "get_log", "read_events",
    "Counter", "Gauge", "Timer", "MetricsRegistry",
    "counter", "gauge", "timer", "registry",
    "trace_scope", "traced", "lowered_scopes", "has_scope",
    "CompileRecord", "compile_with_report",
    "device_memory_report", "device_memory_stats",
]
