"""Unified telemetry: run events, metrics, trace scopes, memory reports.

One subsystem behind the pieces that grew up scattered (``utils/monitor``,
``utils/profiling``, ``bench.py``'s hand-rolled orchestrator prints):

- :mod:`pystella_tpu.obs.events` — a structured JSONL run-event log
  (wall + monotonic timestamps, host id, step, event kind, payload) that
  drivers, :class:`~pystella_tpu.HealthMonitor`, checkpointing, the
  multigrid driver, and ``bench.py`` all emit through. Outage and
  contamination forensics become ``grep``s over one file instead of
  archaeology on interleaved stderr.
- :mod:`pystella_tpu.obs.metrics` — a lightweight registry of counters /
  gauges / timers (steps taken, halo exchanges, V-cycles, compile
  events, ms/step EMA, site-updates/s) with a multihost-aware
  :meth:`~pystella_tpu.obs.metrics.MetricsRegistry.aggregate` so host 0
  reports fleet-wide numbers.
- :mod:`pystella_tpu.obs.scope` — ``jax.named_scope`` +
  ``jax.profiler.TraceAnnotation`` wrappers threaded through the hot
  paths, so Perfetto/TensorBoard traces show semantically named regions
  (RK stages, halo exchanges, stencil kernels, multigrid smoothers)
  instead of raw XLA op soup.
- :mod:`pystella_tpu.obs.memory` — compile-time and HBM
  instrumentation: per-computation compile seconds and
  ``memory_analysis()`` byte counts recorded into the event log, plus
  live device-memory reports (the evidence that catches an HBM
  overshoot *before* Mosaic or the allocator rejects it).

The PERF EVIDENCE PIPELINE (PR 2) sits on top — emission above, analysis
below, so a throughput claim is a distribution with provenance instead
of one wall-clock number:

- :mod:`pystella_tpu.obs.trace` — ``jax.profiler`` capture around a
  step window plus a stdlib Perfetto-trace parser that recovers
  per-scope durations for the names ``obs.scope`` threaded through the
  hot paths, emitted as ``trace_summary`` events.
- :mod:`pystella_tpu.obs.ledger` — :class:`~pystella_tpu.obs.ledger.
  PerfLedger` ingests the event log + metrics registry into
  ``bench_results/perf_report.json`` / ``.md``: step-time percentiles
  and MAD, per-scope breakdown, site-updates/s, roofline fraction, and
  an environment fingerprint.
- :mod:`pystella_tpu.obs.gate` — the noise-aware regression gate CLI
  (``python -m pystella_tpu.obs.gate``): ``median +- k*MAD`` comparison
  plus a contamination detector; exits nonzero on regression or invalid
  evidence so CI can consume it.

The NUMERICS OBSERVABILITY layer (PR 4) makes the *physics* of a run as
observable as its performance — always-on, with no host sync on the
step critical path:

- :mod:`pystella_tpu.obs.sentinel` — a compact per-step health vector
  (per-field finite/max-abs/rms plus model invariants: energy
  components, Friedmann-constraint residual) computed *inside* the
  compiled step, consumed asynchronously by a
  :class:`~pystella_tpu.obs.sentinel.SentinelMonitor` that only ever
  blocks on vectors already ``every`` steps behind the driver.
- :mod:`pystella_tpu.obs.forensics` — on a tripped sentinel, a
  forensic bundle: last-K health vectors, per-field blowup curves, the
  event-log tail, config/env fingerprint, and the last-good-checkpoint
  pointer.
- the ledger gains a ``numerics`` report section (invariant drift
  slopes, sentinel overhead) and the gate fails CI on a
  constraint-drift regression exactly like a step-time regression.

The REQUEST TRACING layer (PR 13) makes the scenario service's latency
causal, not just measured: event schema v2 carries
``trace``/``span``/``parent`` fields through an ambient
:func:`~pystella_tpu.obs.events.tracing` context, and
:mod:`pystella_tpu.obs.spans` (``python -m pystella_tpu.obs.spans``)
reassembles them into per-request span trees — critical-path phase
decomposition, the deadline-miss ledger, and a Perfetto-loadable
service timeline sharing the hardware traces' scope vocabulary. The
ledger's ``latency`` section and the gate's deadline-miss SLO consume
it; :func:`~pystella_tpu.obs.events.registered_event_kinds` is the
central emit vocabulary the source lint audits.

The LIVE OPERATIONS PLANE (PR 14) is the other half of the
production-telemetry split — everything above is post-hoc, while a
persistent service needs scrape-time truth:

- :mod:`pystella_tpu.obs.live` — an opt-in stdlib ``http.server``
  endpoint on a daemon thread (``PYSTELLA_LIVE_PORT``, 0 = off):
  ``/metrics`` Prometheus exposition of the metrics registry plus the
  scenario service's live gauges (queue depth per class/tenant, active
  leases, warm-pool fingerprint health, last-chunk member-steps/s),
  ``/healthz`` liveness+readiness from the serve loop and supervisor
  state, ``/slo`` the current burn-rate state.
- :mod:`pystella_tpu.obs.slo` — a rolling-window SLO monitor fed by the
  :meth:`EventLog.subscribe <pystella_tpu.obs.events.EventLog.
  subscribe>` in-process push hook (not log tailing): queue-p95, warm
  TTFS, deadline-miss rate, and incident rate as fast/slow multi-window
  burn rates against the SAME factor+floor bars the gate uses, emitting
  ``slo_alert``/``slo_resolved`` events so live alerts become
  gate-visible evidence — the ledger's ``alerts`` section counts them
  and the gate refuses an unresolved burn alert beside a green post-hoc
  SLO section.

The CONTINUOUS-PERFORMANCE PLANE (PR 17) watches for the regression
nobody pages on — performance *drift*:

- :mod:`pystella_tpu.obs.perf` — per-program-signature rolling
  step-time quantile digests (p50/p95/p99, count-vector mergeable
  across hosts) fed by every :class:`~pystella_tpu.utils.profiling.
  StepTimer` tick and the scenario service's dispatch loop; a robust
  CUSUM change-point detector emitting ``perf_anomaly`` /
  ``perf_recovered`` (routed into the SLO monitor's
  ``perf_regression`` burn leg); and an anomaly-triggered, rate-limited
  ``jax.profiler`` flight recorder whose Perfetto artifacts land as
  ``perf_capture`` events — the evidence is captured while the
  regression is live, not after an operator notices.
- :mod:`pystella_tpu.obs.stragglers` — cross-host step-time skew
  attribution naming the slowest host in every anomaly payload.
- the ledger gains a ``perf`` report section (anomaly rollup, digest
  summaries, linked captures) and the gate refuses a report whose
  unresolved ``perf_anomaly`` sits beside a green step-time verdict.

See ``doc/observability.md`` for the event schema and driver recipes.
"""

from pystella_tpu.obs.events import (
    EventLog, configure, current_trace, emit, get_log, new_span_id,
    new_trace_id, read_events, register_event_kind,
    registered_event_kinds, tracing)
from pystella_tpu.obs.metrics import (
    Counter, Gauge, MetricsRegistry, Timer, counter, gauge, registry, timer)
from pystella_tpu.obs.scope import (
    has_scope, lowered_scopes, register_scope, registered_scopes,
    trace_scope, traced)
from pystella_tpu.obs.memory import (
    CompileRecord, cache_bypass, cache_donation_safe, compile_totals,
    compile_watch, compile_with_report, device_memory_report,
    device_memory_stats, ensure_compilation_cache, instrument_jit,
    probe_cache_donation_safety, program_fingerprint, runtime_versions,
    signature_fingerprint)
# obs.gate, obs.warmstart, and obs.spans are deliberately NOT imported
# here: their primary entry points are ``python -m pystella_tpu.obs.gate``
# / ``... .obs.warmstart`` / ``... .obs.spans``, and runpy warns when
# the module is already in sys.modules at -m execution time. Import
# them explicitly (``from pystella_tpu.obs import gate, spans,
# warmstart``) for programmatic use.
from pystella_tpu.obs import forensics, ledger, perf, sentinel, stragglers, trace
from pystella_tpu.obs.ledger import PerfLedger, environment_fingerprint
from pystella_tpu.obs.perf import (
    CusumDetector, Digest, FlightRecorder, PerfMonitor)
from pystella_tpu.obs.trace import scope_durations, summarize_trace
from pystella_tpu.obs.sentinel import (
    Sentinel, SentinelMonitor, SimulationDiverged)
from pystella_tpu.obs.forensics import ForensicSink, load_bundle, write_bundle

__all__ = [
    "EventLog", "configure", "current_trace", "emit", "get_log",
    "new_span_id", "new_trace_id", "read_events",
    "register_event_kind", "registered_event_kinds", "tracing",
    "Counter", "Gauge", "Timer", "MetricsRegistry",
    "counter", "gauge", "timer", "registry",
    "trace_scope", "traced", "lowered_scopes", "has_scope",
    "register_scope", "registered_scopes",
    "CompileRecord", "compile_with_report", "compile_watch",
    "compile_totals", "instrument_jit", "ensure_compilation_cache",
    "cache_bypass", "cache_donation_safe", "probe_cache_donation_safety",
    "program_fingerprint", "signature_fingerprint", "runtime_versions",
    "device_memory_report", "device_memory_stats",
    "trace", "ledger", "sentinel", "forensics", "perf", "stragglers",
    "PerfLedger", "environment_fingerprint",
    "CusumDetector", "Digest", "FlightRecorder", "PerfMonitor",
    "scope_durations", "summarize_trace",
    "Sentinel", "SentinelMonitor", "SimulationDiverged",
    "ForensicSink", "load_bundle", "write_bundle",
]
