"""Profiler capture and Perfetto-trace analysis for the PR-1 scope names.

PR 1 threaded :func:`pystella_tpu.obs.scope.trace_scope` names through
every hot path (RK stages, halo exchange, Pallas stencils, multigrid
smoothers); this module closes the loop by turning a captured trace back
into *numbers* — per-scope durations the perf ledger can cite, instead
of a screenshot of a timeline.

Two halves:

- :class:`capture` — a context manager around ``jax.profiler``
  start/stop that, on exit, locates the emitted Perfetto
  ``*.trace.json.gz``, parses it, and emits one ``trace_summary`` run
  event carrying the per-scope duration table. Degrades gracefully: a
  backend that produces no trace file (some CPU/interpret setups) emits
  a ``trace_missing`` event and ``summary`` stays ``None`` — the
  instrumented run never dies for lack of a profile.
- the parser (:func:`find_trace_file`, :func:`parse_trace_file`,
  :func:`scope_durations`) — stdlib-only (``gzip`` + ``json``), so the
  jax-free bench orchestrator and offline analysis scripts can digest a
  trace captured elsewhere.

Matching semantics: a trace event belongs to the *longest* known scope
name that appears in the event name at a token boundary (so host-side
``TraceAnnotation`` spans named ``halo_exchange`` match exactly;
device-op rows named ``jit(step)/fused_rk_stage_pair/fusion.3`` match
``fused_rk_stage_pair`` and NOT its prefix ``fused_rk_stage``; the
generic stepper's ``rk_stage0`` ... ``rk_stage4`` all fold into
``rk_stage``). Nested scopes each keep their own wall time — per-scope
totals may overlap and are reported as independent rows, not a
partition of the window.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re

from pystella_tpu.obs import events as _events
from pystella_tpu.obs.scope import registered_scopes as _registered

__all__ = ["KNOWN_SCOPES", "capture", "find_trace_file",
           "parse_trace_file", "scope_durations", "summarize_trace"]

# The instrumentation vocabulary (doc/observability.md "Trace
# scopes") is the central registry in :mod:`pystella_tpu.obs.scope`:
# ``KNOWN_SCOPES`` (served via module ``__getattr__`` below) and every
# ``scopes=None`` default in this module resolve the registry AT CALL
# TIME, so ``register_scope()`` after import is sufficient for traces
# and ledger tables to pick a scope up (and an unregistered literal
# fails ``tests/test_scope_registry.py``). Notable members:
# ``halo_overlap*`` are the overlapped-halo-path phases (whole
# overlapped update / interior-while-collectives-fly / shell
# stitching); ``collective-permute`` matches the RAW XLA ppermute op
# rows, which appear in device traces (TPU and the TFRT CPU backend)
# without any named-scope path — the comm-time denominator for the
# ledger's exposed-vs-hidden breakdown.


def __getattr__(name):
    if name == "KNOWN_SCOPES":
        return tuple(sorted(_registered()))
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def _scope_matchers(scopes):
    """Longest-first ``(scope, compiled_regex)`` pairs. The boundary
    rule: the scope name must not be preceded by an identifier char and
    must not be followed by a lowercase letter or underscore — digits
    ARE allowed after (``rk_stage0`` is an ``rk_stage`` span) but
    ``fused_rk_stage_pair`` is not a ``fused_rk_stage`` span."""
    out = []
    for s in sorted(scopes, key=len, reverse=True):
        out.append((s, re.compile(
            r"(?<![A-Za-z0-9_])" + re.escape(s) + r"(?![a-z_])")))
    return out


def find_trace_file(logdir):
    """Newest ``*.trace.json(.gz)`` under ``logdir`` (jax writes
    ``<logdir>/plugins/profile/<run>/<host>.trace.json.gz``), or ``None``
    when the capture produced nothing."""
    hits = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        hits += glob.glob(os.path.join(logdir, "**", pat), recursive=True)
    if not hits:
        return None
    return max(hits, key=os.path.getmtime)


def parse_trace_file(path):
    """The Perfetto/Chrome ``traceEvents`` list from a ``.json`` or
    ``.json.gz`` trace file. Returns ``[]`` for unreadable or
    schema-less files rather than raising — trace analysis is evidence
    collection, not a correctness gate."""
    try:
        opener = gzip.open if str(path).endswith(".gz") else open
        with opener(path, "rt") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    evs = data.get("traceEvents") if isinstance(data, dict) else None
    return evs if isinstance(evs, list) else []


def scope_durations(trace_events, scopes=None):
    """Fold complete-span events (``ph == "X"``, microsecond ``dur``)
    into ``{scope: {"count", "total_ms", "mean_ms", "min_ms",
    "max_ms"}}`` for every known scope that appears (default: the live
    scope registry). Each event counts toward the longest matching
    scope only."""
    matchers = _scope_matchers(_registered() if scopes is None
                               else scopes)
    acc = {}
    for ev in trace_events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        name = ev.get("name")
        dur = ev.get("dur")
        if not isinstance(name, str) or not isinstance(dur, (int, float)):
            continue
        for scope, rx in matchers:
            if rx.search(name):
                ms = dur / 1e3
                a = acc.setdefault(scope, [0, 0.0, ms, ms])
                a[0] += 1
                a[1] += ms
                a[2] = min(a[2], ms)
                a[3] = max(a[3], ms)
                break
    return {scope: {"count": n, "total_ms": tot, "mean_ms": tot / n,
                    "min_ms": lo, "max_ms": hi}
            for scope, (n, tot, lo, hi) in sorted(acc.items())}


def summarize_trace(logdir, scopes=None, label="", step=None,
                    log=None):
    """Parse the newest trace under ``logdir`` into a per-scope duration
    table and emit it as one ``kind="trace_summary"`` run event
    (``kind="trace_missing"`` when no trace file appeared — CPU or
    interpret-mode captures sometimes produce none). Returns the summary
    dict, or ``None`` when there was nothing to parse."""
    sink = log if log is not None else _events.get_log()
    path = find_trace_file(logdir)
    if path is None:
        sink.emit("trace_missing", step=step, logdir=str(logdir),
                  label=label)
        return None
    table = scope_durations(parse_trace_file(path), scopes)
    summary = {"trace_file": path, "label": label, "scopes": table}
    sink.emit("trace_summary", step=step, **summary)
    return summary


class capture:
    """``jax.profiler`` capture around a step window, with automatic
    post-capture analysis.

    Usage (the bench/example drivers' ``--profile`` flag)::

        with obs.trace.capture(logdir, label="preheat-256^3") as cap:
            for _ in range(profile_steps):
                state = step(state)
            jax.block_until_ready(state)
        cap.summary      # per-scope table, or None if no trace appeared

    The underlying Perfetto file stays in ``logdir`` for interactive
    inspection (``ui.perfetto.dev``); the extracted per-scope durations
    additionally land in the run-event log, where
    :class:`pystella_tpu.obs.ledger.PerfLedger` picks them up.
    """

    def __init__(self, logdir, scopes=None, label="", step=None,
                 log=None):
        self.logdir = str(logdir)
        self.scopes = scopes
        self.label = label
        self.step = step
        self.log = log
        self.summary = None

    def __enter__(self):
        import jax
        os.makedirs(self.logdir, exist_ok=True)
        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, exc_type, exc, tb):
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            # a failed stop must not mask the body's exception (or kill
            # a healthy run); there is simply no trace to analyze
            return False
        if exc_type is None:
            self.summary = summarize_trace(
                self.logdir, self.scopes, label=self.label,
                step=self.step, log=self.log)
        return False
