"""Fleet federation: N replicas' live endpoints, one fleet view.

The live plane (:mod:`pystella_tpu.obs.live`) gives every replica its
own ``/metrics`` / ``/healthz`` / ``/slo``; the replica registry
(:mod:`pystella_tpu.service.registry`) answers who is in the fleet.
This module closes the loop: :class:`FleetAggregator` reads the
registry, scrapes every live replica's three endpoints, and federates
them into one fleet-level view with the same evidence discipline as
every single-replica subsystem — registered events in, ledger section
and gate verdicts out.

**Merging.** ``/metrics`` is parsed by :func:`parse_prometheus` — we
round-trip our own Prometheus 0.0.4 exposition, the same text a real
collector would scrape, so the federation path exercises the format
end to end. Counters merge by sum (fleet totals); gauges stay
per-replica-labeled (a fleet-mean queue depth is a lie when one
replica is drowning). The ``pystella_build_info`` gauge's labels are
the scrape-side half of skew detection.

**Fleet SLOs.** Each replica's ``/slo`` exposes its legs' recent
``samples``; the aggregator replays every not-yet-ingested sample
(deduplicated per replica+leg by timestamp) into its own
:class:`~pystella_tpu.obs.slo.SLOMonitor` via
:meth:`~pystella_tpu.obs.slo.SLOMonitor.add_sample` — so the fleet
queue-p95 is a true p95 over BOTH replicas' dispatch samples, and
fleet alerts fire/resolve under the identical fast/slow multi-window
burn rule. One extra leg exists only at fleet level:
``dead_replicas`` (bar 0 — any replica lost without a withdraw
burns until the record is acknowledged or recovered).

**Loss.** A replica that tombstoned (``withdrawn``) left cleanly. A
replica whose heartbeat expired, or whose endpoint fails several
consecutive scrapes while its record still beats, is LOST:
``fleet_replica_lost`` is emitted once and the replica counts into
``dead_replicas`` until it returns. The ledger's ``fleet`` section
and the gate's fleet verdicts are built from these events — a report
claiming full-fleet coverage over a scrape record with losses is
refused as invalid evidence.

Ops CLI::

    python -m pystella_tpu.obs.fleet status          # one pass
    python -m pystella_tpu.obs.fleet watch -i 2      # live table

Both read ``PYSTELLA_FLEET_DIR`` (or ``--dir``) and need nothing but
a filesystem view of the registry plus loopback HTTP to the replicas.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import slo as _slo
from pystella_tpu.service import registry as _registry

__all__ = ["DEFAULT_FLEET_LEGS", "FleetAggregator", "parse_prometheus"]

#: fleet-level SLO legs: the replica legs re-evaluated over the merged
#: sample stream, plus ``dead_replicas`` (fleet-only; any lost replica
#: breaches its zero bar). Same spec schema as
#: :data:`pystella_tpu.obs.slo.DEFAULT_LEGS`.
DEFAULT_FLEET_LEGS = {
    "queue_p95": {"objective": 0.0, "factor": 2.5, "floor": 0.5,
                  "kind": "p95"},
    "warm_ttfs": {"objective": 0.0, "factor": 2.5, "floor": 1.0,
                  "kind": "p50"},
    "deadline_miss": {"objective": 0.0, "factor": 2.0, "floor": 0.05,
                      "kind": "rate"},
    "incident_rate": {"objective": 0.0, "factor": 1.0, "floor": 0.0,
                      "kind": "count"},
    "dead_replicas": {"objective": 0.0, "factor": 1.0, "floor": 0.0,
                      "kind": "rate"},
}

#: consecutive endpoint-scrape failures after which a replica whose
#: registry record still beats is declared lost anyway (a wedged
#: process can keep heartbeating while its server thread is dead)
_UNREACHABLE_AFTER = 3


# -- the exposition parser ---------------------------------------------------


def _unescape_label(raw):
    out, i = [], 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(body):
    """``k1="v1",k2="v2"`` -> dict, honouring the text format's
    escapes (``\\\\``, ``\\"``, ``\\n``) — the inverse of
    ``live._prom_label``."""
    labels, i, n = {}, 0, len(body)
    while i < n:
        while i < n and body[i] in ", ":
            i += 1
        if i >= n:
            break
        eq = body.index("=", i)
        name = body[i:eq].strip()
        if body[eq + 1] != '"':
            raise ValueError(f"malformed label at {body[i:]!r}")
        j = eq + 2
        buf = []
        while j < n:
            c = body[j]
            if c == "\\" and j + 1 < n:
                buf.append(body[j:j + 2])
                j += 2
            elif c == '"':
                break
            else:
                buf.append(c)
                j += 1
        labels[name] = _unescape_label("".join(buf))
        i = j + 1
    return labels


def parse_prometheus(text):
    """Parse a Prometheus text-format (0.0.4) exposition into
    ``{name: {"type": kind, "help": str|None,
    "samples": [(labels_dict, value), ...]}}`` — the inverse of
    :func:`pystella_tpu.obs.live.render_prometheus`, so the fleet
    aggregator consumes exactly what a real collector would. Unknown
    or malformed lines are skipped (a federation pass must not die on
    one bad line); untyped samples get type ``"untyped"``."""
    families = {}

    def family(name):
        return families.setdefault(
            name, {"type": "untyped", "help": None, "samples": []})

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("TYPE", "HELP"):
                fam = family(parts[2])
                if parts[1] == "TYPE":
                    fam["type"] = parts[3] if len(parts) > 3 else "untyped"
                else:
                    fam["help"] = parts[3] if len(parts) > 3 else ""
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                body, value_part = rest.rsplit("}", 1)
                labels = _parse_labels(body)
            else:
                name, value_part = line.split(None, 1)
                labels = {}
            value = float(value_part.strip().split()[0])
        except (ValueError, IndexError):
            continue
        family(name.strip())["samples"].append((labels, value))
    return families


# -- the aggregator ----------------------------------------------------------


class FleetAggregator:
    """Registry-driven fleet scraper + federator (module docstring).

    :arg registry_dir: the shared registry directory; ``None`` reads
        the registered ``PYSTELLA_FLEET_DIR`` (raises ``ValueError``
        when that is unset too — an aggregator without a registry has
        nothing to aggregate).
    :arg expire_s / timeout_s: heartbeat expiry and per-endpoint HTTP
        timeout; default to the registered ``PYSTELLA_FLEET_*`` knobs.
    :arg legs: fleet SLO leg overrides, merged over
        :data:`DEFAULT_FLEET_LEGS` exactly like
        :class:`~pystella_tpu.obs.slo.SLOMonitor` merges its own.
    :arg label: tag on every emitted fleet event.
    :arg emit: emit ``fleet_*`` events (default; ``False`` keeps the
        aggregator silent for synthetic-replica unit tests).
    """

    def __init__(self, registry_dir=None, expire_s=None, timeout_s=None,
                 legs=None, fast_window_s=None, slow_window_s=None,
                 min_samples=None, label="fleet", emit=True):
        if registry_dir is None:
            registry_dir = _config.getenv("PYSTELLA_FLEET_DIR")
        if not registry_dir:
            raise ValueError(
                "no registry directory: pass registry_dir or set "
                "PYSTELLA_FLEET_DIR")
        self.registry_dir = str(registry_dir)
        if expire_s is None:
            expire_s = _config.get_float("PYSTELLA_FLEET_EXPIRE_S")
        if timeout_s is None:
            timeout_s = _config.get_float("PYSTELLA_FLEET_SCRAPE_TIMEOUT_S")
        self.expire_s = float(expire_s)
        self.timeout_s = float(timeout_s)
        self.label = str(label)
        self.emit_events = bool(emit)
        chosen = (dict(DEFAULT_FLEET_LEGS) if legs is None
                  else {name: {**DEFAULT_FLEET_LEGS.get(name, {}),
                               **(spec or {})}
                        for name, spec in legs.items()})
        # the monitor stays silent: the aggregator owns the fleet_*
        # event vocabulary and emits transitions itself
        self.monitor = _slo.SLOMonitor(
            legs=chosen, fast_window_s=fast_window_s,
            slow_window_s=slow_window_s, min_samples=min_samples,
            label=f"{self.label}-slo", emit=False)
        self._fleet_legs = set(chosen)
        self.replicas = {}          # id -> bookkeeping dict
        self.scrapes = 0            # aggregation passes
        self.endpoint_ok = 0        # per-replica scrape outcomes
        self.endpoint_failed = 0
        self.lost = []              # [{replica, ts, reason}]
        self.alert_log = []         # [{leg, change, ts, ...}]
        self.counters = {}          # fleet-summed counters, last pass
        self.gauges = {}            # name -> {replica: value}, last pass
        self.skew = {"skewed": False, "fingerprints": {}}
        self.divergence = {"divergent": {}, "signatures": 0}
        self._seen = {}             # (replica, leg) -> last ingested ts

    # -- one replica ---------------------------------------------------------

    def _get_json(self, url):
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _scrape_replica(self, record):
        url = record.get("url")
        if not url:
            return {"error": "no url in registry record"}
        base = url.rstrip("/")
        try:
            with urllib.request.urlopen(
                    base + "/metrics", timeout=self.timeout_s) as r:
                metrics_text = r.read().decode()
            return {
                "metrics": parse_prometheus(metrics_text),
                "slo": self._get_json(base + "/slo"),
                "healthz": self._get_json(base + "/healthz"),
                "error": None,
            }
        except (OSError, ValueError, urllib.error.URLError) as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _merge_metrics(self, rid, families, counters, gauges):
        for name, fam in families.items():
            kind = fam["type"]
            if kind == "counter":
                total = sum(v for _labels, v in fam["samples"])
                counters[name] = counters.get(name, 0.0) + total
            elif kind == "gauge":
                # keep gauges per-replica: only the unlabeled headline
                # sample (labeled series stay replica-local detail)
                for labels, v in fam["samples"]:
                    if not labels:
                        gauges.setdefault(name, {})[rid] = v
        info = families.get("pystella_build_info")
        if info and info["samples"]:
            return dict(info["samples"][0][0])
        return None

    def _ingest_slo(self, rid, payload, transitions):
        legs = (payload or {}).get("legs") or {}
        for leg_name, leg_state in legs.items():
            if leg_name not in self._fleet_legs:
                continue
            key = (rid, leg_name)
            last = self._seen.get(key)
            for ts, value in (leg_state.get("samples") or []):
                if last is not None and ts <= last:
                    continue
                transitions.extend(
                    self.monitor.add_sample(leg_name, value, ts=ts))
                last = ts
            if last is not None:
                self._seen[key] = last

    # -- the aggregation pass ------------------------------------------------

    def scrape(self, now=None):
        """One full pass: read the registry, scrape every live
        replica, merge, re-evaluate the fleet SLOs, detect skew and
        divergence, emit ``fleet_*`` events. Returns :meth:`state`."""
        now = time.time() if now is None else float(now)
        self.scrapes += 1
        records = _registry.read_records(
            self.registry_dir, expire_s=self.expire_s, now=now)
        by_id = {r["replica"]: r for r in records}
        counters, gauges = {}, {}
        transitions = []
        pass_ok = pass_failed = 0
        for rec in records:
            rid = rec["replica"]
            book = self.replicas.setdefault(rid, {
                "replica": rid, "ever_live": False, "lost": False,
                "withdrawn": False, "consecutive_failures": 0,
                "scrapes_ok": 0, "scrapes_failed": 0,
                "build_info": None, "healthz": None})
            book["record"] = rec
            book["withdrawn"] = rec["status"] == "withdrawn"
            if rec["status"] != "live":
                continue
            book["ever_live"] = True
            result = self._scrape_replica(rec)
            if result.get("error"):
                pass_failed += 1
                book["scrapes_failed"] += 1
                book["consecutive_failures"] += 1
                book["last_error"] = result["error"]
                continue
            pass_ok += 1
            book["scrapes_ok"] += 1
            book["consecutive_failures"] = 0
            book["healthz"] = result["healthz"]
            book["build_info"] = self._merge_metrics(
                rid, result["metrics"], counters, gauges)
            self._ingest_slo(rid, result["slo"], transitions)
            if book["lost"]:
                book["lost"] = False  # it came back
        self.endpoint_ok += pass_ok
        self.endpoint_failed += pass_failed
        self.counters, self.gauges = counters, gauges

        # -- loss: expired heartbeat, or live-but-unreachable ----------------
        for rid, book in self.replicas.items():
            rec = by_id.get(rid)
            status = rec["status"] if rec else "stale"
            if not book["ever_live"] or book["withdrawn"] or book["lost"]:
                continue
            reason = None
            if status == "stale":
                reason = "expired"
            elif (status == "live"
                  and book["consecutive_failures"] >= _UNREACHABLE_AFTER):
                reason = "unreachable"
            if reason:
                book["lost"] = True
                entry = {"replica": rid, "ts": now, "reason": reason,
                         "age_s": rec.get("age_s") if rec else None}
                self.lost.append(entry)
                if self.emit_events:
                    _events.emit("fleet_replica_lost", label=self.label,
                                 **entry)
        dead = sum(1 for b in self.replicas.values() if b["lost"])
        transitions.extend(
            self.monitor.add_sample("dead_replicas", float(dead), ts=now,
                                    evaluate=False) or [])
        transitions.extend(self.monitor.evaluate(now=now))
        self._note_transitions(transitions, now)

        # -- skew + divergence across live records ---------------------------
        live = [r for r in records if r["status"] == "live"]
        fps = {}
        for rec in live:
            fp = rec.get("fingerprint") or "unknown"
            info = (self.replicas[rec["replica"]].get("build_info")
                    or {})
            key = (fp, info.get("flags_fingerprint"),
                   info.get("jax"), info.get("device_kind"))
            fps.setdefault("|".join(str(k) for k in key),
                           []).append(rec["replica"])
        self.skew = {"skewed": len(fps) > 1, "fingerprints": fps}
        sigs = {}
        for rec in live:
            for sig, fp in (rec.get("warm_fingerprints") or {}).items():
                sigs.setdefault(sig, {}).setdefault(
                    str(fp), []).append(rec["replica"])
        self.divergence = {
            "signatures": len(sigs),
            "divergent": {sig: fps_ for sig, fps_ in sigs.items()
                          if len(fps_) > 1},
        }

        state = self.state(now=now)
        if self.emit_events:
            _events.emit(
                "fleet_scrape", label=self.label,
                replicas=[{
                    "replica": r["replica"], "status": r["status"],
                    "url": r.get("url"),
                    "age_s": (round(r["age_s"], 3)
                              if isinstance(r.get("age_s"), float)
                              else r.get("age_s")),
                    "fingerprint": r.get("fingerprint"),
                    "queue_depth": r.get("queue_depth"),
                } for r in records],
                ok=pass_ok, failed=pass_failed, dead=dead,
                legs={name: {"value_fast": leg.get("value_fast"),
                             "bar": leg.get("bar"),
                             "alerting": leg.get("alerting")}
                      for name, leg in state["legs"].items()},
                skewed=self.skew["skewed"],
                stacks=len(self.skew["fingerprints"]),
                divergent=sorted(self.divergence["divergent"]))
        return state

    def _note_transitions(self, transitions, now):
        if not transitions:
            return
        legs = self.monitor.state()["legs"]
        for name, change in transitions:
            leg = legs.get(name, {})
            entry = {"leg": name, "change": change, "ts": now,
                     "value": leg.get("value_fast"),
                     "bar": leg.get("bar")}
            self.alert_log.append(entry)
            if not self.emit_events:
                continue
            if change == "fired":
                _events.emit("fleet_alert", leg=name,
                             value=leg.get("value_fast"),
                             bar=leg.get("bar"),
                             burn_fast=leg.get("burn_fast"),
                             burn_slow=leg.get("burn_slow"),
                             label=self.label)
            else:
                _events.emit("fleet_resolved", leg=name,
                             value=leg.get("value_fast"),
                             bar=leg.get("bar"),
                             duration_s=round(
                                 leg.get("duration_s") or 0.0, 6),
                             label=self.label)

    # -- introspection -------------------------------------------------------

    def state(self, now=None):
        """The JSON-safe fleet view: per-replica rows, merged
        counters/gauges, fleet SLO legs, loss + skew + divergence
        records, scrape bookkeeping."""
        now = time.time() if now is None else float(now)
        rows = {}
        for rid, book in sorted(self.replicas.items()):
            rec = book.get("record") or {}
            rows[rid] = {
                "status": ("lost" if book["lost"]
                           else rec.get("status", "unknown")),
                "url": rec.get("url"),
                "age_s": rec.get("age_s"),
                "fingerprint": rec.get("fingerprint"),
                "device_kind": rec.get("device_kind"),
                "queue_depth": rec.get("queue_depth"),
                "serving": rec.get("serving"),
                "scrapes_ok": book["scrapes_ok"],
                "scrapes_failed": book["scrapes_failed"],
                "build_info": book.get("build_info"),
            }
        attempts = self.endpoint_ok + self.endpoint_failed
        mstate = self.monitor.state()
        return {
            "label": self.label,
            "registry_dir": self.registry_dir,
            "ts": now,
            "replicas": rows,
            "live": sum(1 for r in rows.values()
                        if r["status"] == "live"),
            "lost": list(self.lost),
            "dead": sum(1 for b in self.replicas.values()
                        if b["lost"]),
            "scrapes": self.scrapes,
            "endpoint_ok": self.endpoint_ok,
            "endpoint_failed": self.endpoint_failed,
            "scrape_success_rate": (self.endpoint_ok / attempts
                                    if attempts else None),
            "counters": dict(self.counters),
            "gauges": {k: dict(v) for k, v in self.gauges.items()},
            "legs": mstate["legs"],
            "alerting": mstate["alerting"],
            "alerts_total": mstate["alerts_total"],
            "resolved_total": mstate["resolved_total"],
            "flaps_total": mstate["flaps_total"],
            "alert_log": list(self.alert_log),
            "skew": dict(self.skew),
            "divergence": dict(self.divergence),
        }


# -- ops CLI -----------------------------------------------------------------


def _render(state):
    lines = []
    lines.append(f"fleet @ {state['registry_dir']}  "
                 f"(pass {state['scrapes']}, "
                 f"live {state['live']}, dead {state['dead']})")
    lines.append(f"{'replica':<20} {'status':<10} {'age_s':>7} "
                 f"{'queue':>5} {'ok/fail':>8} {'fingerprint':<14} url")
    for rid, row in state["replicas"].items():
        age = row.get("age_s")
        age_s = f"{age:.2f}" if isinstance(age, (int, float)) else "—"
        q = row.get("queue_depth")
        okf = f"{row['scrapes_ok']}/{row['scrapes_failed']}"
        lines.append(
            f"{rid:<20} {row['status']:<10} {age_s:>7} "
            f"{q if q is not None else '—':>5} {okf:>8} "
            f"{(row.get('fingerprint') or '—'):<14} "
            f"{row.get('url') or '—'}")
    legs = state["legs"]
    if legs:
        lines.append("fleet SLO legs:")
        for name, leg in sorted(legs.items()):
            v = leg.get("value_fast")
            v_s = "—" if v is None else f"{v:.4g}"
            mark = " ALERTING" if leg.get("alerting") else ""
            lines.append(f"  {name:<16} value {v_s:>8}  "
                         f"bar {leg['bar']:.4g}{mark}")
    if state["skew"].get("skewed"):
        lines.append(f"SKEW: {len(state['skew']['fingerprints'])} "
                     "distinct stacks across live replicas")
    if state["divergence"].get("divergent"):
        lines.append("WARM DIVERGENCE: "
                     + ", ".join(sorted(state["divergence"]["divergent"])))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m pystella_tpu.obs.fleet",
        description="fleet ops view: scrape the replica registry and "
                    "every live replica's /metrics //slo //healthz")
    parser.add_argument("command", choices=("status", "watch"),
                        help="status: one aggregation pass; watch: "
                             "repeat every --interval seconds")
    parser.add_argument("--dir", default=None,
                        help="registry dir (default PYSTELLA_FLEET_DIR)")
    parser.add_argument("--expire", type=float, default=None,
                        help="heartbeat expiry override (s)")
    parser.add_argument("--timeout", type=float, default=None,
                        help="per-endpoint scrape timeout override (s)")
    parser.add_argument("--interval", "-i", type=float, default=2.0,
                        help="watch cadence (s)")
    parser.add_argument("--count", type=int, default=0,
                        help="watch: stop after N passes (0 = forever)")
    parser.add_argument("--json", action="store_true",
                        help="print the raw state dict instead of the "
                             "table")
    args = parser.parse_args(argv)
    try:
        agg = FleetAggregator(registry_dir=args.dir,
                              expire_s=args.expire,
                              timeout_s=args.timeout, emit=False)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    passes = 0
    while True:
        state = agg.scrape()
        if args.json:
            print(json.dumps(state, sort_keys=True, default=str))
        else:
            print(_render(state))
        passes += 1
        if args.command == "status" or (args.count
                                        and passes >= args.count):
            break
        time.sleep(args.interval)
    return 0


if __name__ == "__main__":
    sys.exit(main())
