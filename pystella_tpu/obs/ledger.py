"""PerfLedger: run events + metrics -> a defensible perf report.

Round 5's verdict was that the headline throughput claim rested on
"zero valid measurements": single wall-clock numbers, no noise model,
no environment provenance, one contaminated run flagged by hand. The
ledger is the analysis layer that turns the PR-1 telemetry (the JSONL
run-event log plus the metrics registry) into evidence the way the
stencil-compiler literature justifies results — distributions and
roofline fractions, not a lone number:

- **step-time distribution** — per-step wall-time samples (from
  ``step_time`` events, falling back to ``step_timer`` window reports)
  summarized as percentiles, mean, and MAD (median absolute deviation —
  the robust noise scale the regression gate's ``median +- k*MAD``
  comparison needs);
- **per-scope breakdown** — the latest ``trace_summary`` event's
  per-scope duration table (:mod:`pystella_tpu.obs.trace`);
- **derived throughput** — site-updates/s from the lattice volume in
  the run-metadata event and the median step time;
- **roofline fraction** — bytes moved per step from the step
  executable's ``compile`` event (XLA ``memory_analysis()`` argument +
  output bytes, a traffic lower bound) over the step time, against the
  device's peak HBM bandwidth;
- **environment fingerprint** — jax/jaxlib versions, device kind and
  count, process count, mesh shape, hostname: the provenance that makes
  two reports comparable at all.

``PerfLedger.write(dir)`` produces ``perf_report.json`` (schema below,
consumed by :mod:`pystella_tpu.obs.gate`) and a human ``perf_report.md``.
The module body never requires jax at runtime — versions come from
package metadata and device fields degrade to ``None`` when no jax is
loaded (importing it as ``pystella_tpu.obs.ledger`` still pulls jax via
the package ``__init__``; a jax-free supervisor should load it by file,
like ``bench.py`` loads ``obs/events.py``).
"""

from __future__ import annotations

import json
import os
import platform as _platform
import socket
import sys
import time

from pystella_tpu.obs import events as _events

__all__ = ["REPORT_SCHEMA_VERSION", "PerfLedger", "environment_fingerprint",
           "mad", "percentile", "step_stats"]

REPORT_SCHEMA_VERSION = 1

#: peak HBM bandwidth per device generation, GB/s (vendor figures; keys
#: are matched as substrings of ``device_kind``, longest first). Used
#: for the roofline denominator; unknown kinds (CPU included) yield a
#: ``None`` fraction rather than a made-up one.
HBM_PEAK_GBPS = {
    "TPU v2": 700.0,
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}

#: cap on raw samples persisted into the report: enough for the gate's
#: contamination detector to see bursts, small enough to keep reports
#: reviewable in a diff
MAX_SAMPLES = 4096


def _version_of(dist):
    try:
        from importlib.metadata import version
        return version(dist)
    except Exception:
        return None


def runtime_versions():
    """The jax/jaxlib/libtpu version triple — the compiler stack that
    keys both perf-report comparability (this module's environment
    fingerprint) and cached/AOT program staleness
    (``obs.memory`` bakes it into every program fingerprint, via this
    one definition so the two can never diverge). Stdlib-only:
    resolved from installed-distribution metadata, no jax import."""
    return {
        "jax": _version_of("jax"),
        "jaxlib": _version_of("jaxlib"),
        # a libtpu bump changes the generated code: cached/AOT programs
        # keyed without it would silently serve stale executables
        "libtpu": _version_of("libtpu") or _version_of("libtpu-nightly"),
    }


#: env-var name substrings that make an XLA/libtpu flag relevant to the
#: fingerprint: async-collective and latency-hiding-scheduler toggles
#: change what a step-time comparison means (the overlapped halo path
#: depends on them to pay off). Kept in sync with
#: ``pystella_tpu.parallel.overlap`` — duplicated here because this
#: module must stay loadable BY FILE in a jax-free supervisor, where
#: the package import (and thus jax) is unavailable.
_FLAG_MARKERS = ("async_collective", "async_all_gather",
                 "latency_hiding", "scheduler")


def xla_flag_fingerprint():
    """The scheduler-relevant flags in this process's environment
    (``XLA_FLAGS`` + ``LIBTPU_INIT_ARGS``), as ``{name: value}``, plus
    the ``PYSTELLA_HALO_OVERLAP`` policy setting when present —
    stdlib-only, embedded in every report's environment fingerprint so
    the gate can warn when two reports differ only in flags."""
    flags = {}
    for var in ("XLA_FLAGS", "LIBTPU_INIT_ARGS"):
        # direct reads: this module stays loadable by file, jax- and
        # package-free  # env-registry: XLA_FLAGS, LIBTPU_INIT_ARGS
        for tok in os.environ.get(var, "").split():
            name, _, value = tok.lstrip("-").partition("=")
            if any(m in name for m in _FLAG_MARKERS):
                flags[name] = value if value else "true"
    setting = os.environ.get(
        "PYSTELLA_HALO_OVERLAP")  # env-registry: PYSTELLA_HALO_OVERLAP
    if setting is not None:
        flags["PYSTELLA_HALO_OVERLAP"] = setting
    return flags


def environment_fingerprint():
    """Everything needed to decide whether two perf reports are
    comparable. Resolved from an already-imported jax only (the module
    must stay importable in the jax-free orchestrator); device fields
    are ``None`` when jax is not loaded."""
    env = {
        "python": _platform.python_version(),
        **runtime_versions(),
        "hostname": socket.gethostname(),
        "platform": None,
        "device_kind": None,
        "num_devices": None,
        "num_processes": None,
        "xla_flags": xla_flag_fingerprint(),
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            devs = jax.devices()
            env["platform"] = devs[0].platform
            env["device_kind"] = devs[0].device_kind
            env["num_devices"] = len(devs)
            env["num_processes"] = int(jax.process_count())
        except Exception:
            pass
    return env


def percentile(sorted_xs, q):
    """Linear-interpolation percentile of an already-sorted list
    (``q`` in [0, 100])."""
    if not sorted_xs:
        return None
    if len(sorted_xs) == 1:
        return float(sorted_xs[0])
    pos = q / 100.0 * (len(sorted_xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_xs) - 1)
    frac = pos - lo
    return float(sorted_xs[lo] * (1 - frac) + sorted_xs[hi] * frac)


def mad(xs):
    """Median absolute deviation — the robust noise scale. (Multiply by
    1.4826 for a Gaussian-consistent sigma; the gate does.)"""
    if not xs:
        return None
    s = sorted(xs)
    med = percentile(s, 50)
    return percentile(sorted(abs(x - med) for x in s), 50)


def step_stats(samples_ms):
    """Distribution summary of per-step wall times (ms)."""
    if not samples_ms:
        return {"count": 0}
    s = sorted(samples_ms)
    return {
        "count": len(s),
        "mean_ms": sum(s) / len(s),
        "min_ms": s[0],
        "max_ms": s[-1],
        "p10_ms": percentile(s, 10),
        "p50_ms": percentile(s, 50),
        "p90_ms": percentile(s, 90),
        "p99_ms": percentile(s, 99),
        "mad_ms": mad(s),
    }


def _peak_gbps(device_kind):
    if not device_kind:
        return None
    for key in sorted(HBM_PEAK_GBPS, key=len, reverse=True):
        if key in device_kind:
            return HBM_PEAK_GBPS[key]
    return None


class PerfLedger:
    """Aggregates one run's telemetry into a perf report.

    Build with :meth:`from_events` (the normal path: ingest a
    ``run_events.jsonl`` plus the live metrics registry), or construct
    directly and feed :meth:`add_step_ms` / attributes for synthetic
    ledgers in tests.
    """

    def __init__(self, label="", sites=None, env=None):
        self.label = label
        self.sites = sites              # lattice sites updated per step
        self.env = env or environment_fingerprint()
        self.samples_ms = []            # per-step wall times
        self.scopes = {}                # trace-derived per-scope table
        self.trace_file = None
        self.bytes_per_step = None      # HBM traffic lower bound
        self.halo_bytes_per_step = None  # ICI bytes per overlapped call
        self.compile_records = []       # compile-event payloads
        self.metrics = {}               # registry snapshot
        self.meta = {}                  # run-metadata event payload
        self.health_series = {}         # invariant name -> [(step, value)]
        self.health_events = 0          # health events ingested
        self.diverged = []              # sentinel trips (step, fields)
        self.forensic_bundles = []      # bundle paths written this run
        self.lint = None                # lint-event summary (see lint())
        self.donated_bytes = None       # aliased bytes in the step compile
        self.kernel_tiers = []          # kernel_tier payloads (dispatch
        #                                 record: which fused tier ran)
        self.block_choices = []         # block_choice payloads
        self.autotune_mismatches = []   # refused stale table entries
        self.autotune_records = []      # autotune_record/sweep payloads
        self.autotune_warm_builds = []  # table-hit rebuild compile proof
        self.cold_start_meta = {}       # cold_start-event payload
        self.cache_info = {}            # compile_cache-event payload
        self.warmstart_loads = []       # warmstart_load payloads
        self.warmstart_mismatches = []  # warmstart_mismatch payloads
        self.ensemble_runs = []         # ensemble_done payloads
        self.ensemble_chunks_ms = []    # per-dispatch ms (ensemble_chunk)
        self.ensemble_evictions = []    # member_evicted payloads
        self.faults_injected = 0        # fault_injected events (harness)
        self.faults_detected = []       # fault_detected payloads
        self.recovery_attempts = 0      # recovery_attempt events
        self.recovery_failures = []     # recovery_failed payloads
        self.resumes = []               # run_resumed payloads
        self.degraded_events = []       # run_degraded payloads
        self.preempted_events = []      # run_preempted payloads
        self.checkpoint_counts = {}     # checkpoint_* event kind -> count
        self.durable_steps = []         # checkpoint_durable steps, in order
        self.checkpoint_barrier_s = 0.0  # summed durability-barrier waits
        self.supervisor_runs = []       # supervisor_done payloads, in order
        self.remesh_plans = []          # remesh_plan payloads, in order
        self._post_remesh_start = None  # samples_ms index at last remesh
        self.fft_runs = []              # fft_spectra payloads (driver legs)
        self.spectra_ms = []            # per-call spectra wall times
        #                                 (spectra_time events — drivers
        #                                 emit one per spectra output)
        self.service_dispatches = []    # service_dispatch payloads
        self.service_leases = []        # service_lease payloads
        self.service_admits = []        # service_admit payloads
        self.service_rejects = []       # service_reject payloads
        self.service_preemptions = 0    # service_preempted events
        self.service_results = []       # member_result payloads
        self.service_done = {}          # last service_done payload
        self.service_loadgen = {}       # last service_loadgen payload
        self.service_lease_failures = 0  # service_lease_failed events
        self.span_records = []          # raw trace/span-carrying events
        #                                 (obs schema v2) — the latency
        #                                 section's SpanAssembler input
        self.deadline_miss_events = 0   # deadline_missed events
        self.slo_events = []            # ("alert"|"resolved", ts, data)
        #                                 from the live burn-rate
        #                                 monitor (obs.slo) -> alerts()
        self.fleet_scrapes = []         # fleet_scrape payloads, in order
        self.fleet_lost = []            # fleet_replica_lost payloads
        self.fleet_slo_events = []      # ("alert"|"resolved", ts, data)
        #                                 from the fleet aggregator
        self.fleet_announces = []       # fleet_announce payloads
        self.fleet_withdraws = []       # fleet_withdraw payloads
        self.perf_events = []           # ("anomaly"|"recovered", ts,
        #                                 data) from the continuous-
        #                                 performance detector
        #                                 (obs.perf) -> perf()
        self.perf_captures = []         # perf_capture payloads (the
        #                                 flight-recorder artifacts)
        self.perf_digests = []          # perf_digest window reports
        self.capacity_footprints = []   # capacity_footprint payloads
        self.capacity_watermarks = []   # capacity_watermark samples
        self.capacity_rejects = []      # capacity_reject payloads
        self.capacity_evictions = []    # capacity_evict payloads
        self.capacity_oom = []          # capacity_oom payloads (the
        #                                 OOM forensic-bundle pointers)
        self.capacity_accounts = []     # capacity_account payloads
        self.capacity_usage = {}        # last capacity_usage payload

    # -- ingestion ---------------------------------------------------------

    def add_step_ms(self, ms):
        self.samples_ms.append(float(ms))

    @classmethod
    def from_events(cls, events_path, registry=None, label="",
                    sites=None, step_label=None):
        """Ingest a run-event JSONL file (and optionally the live
        metrics registry).

        - per-step samples: ``step_time`` events (``data.ms``); when a
          run only kept ``step_timer`` window reports, those window
          averages stand in (coarser, still gateable);
        - lattice sites: explicit ``sites`` arg, else the grid shape in
          the latest ``run_start`` / ``bench_run`` event;
        - scope table: the latest ``trace_summary`` event;
        - bytes/step: the ``compile`` event labeled ``step_label`` (or
          the largest-argument one), argument + output bytes.

        :class:`~pystella_tpu.obs.events.EventLog` appends, so a reused
        log file holds several runs; ingestion is scoped to the LATEST
        run — everything from the last ``run_start``/``bench_run``
        event on — so a report never averages two runs' step times
        together (a regression between them would vanish into the mix).
        A log with no run-metadata event is ingested whole.
        """
        led = cls(label=label, sites=sites)
        window_ms = []
        # include_rotated: a size-rotated long-lived log (the scenario
        # service's rotate_bytes=) ingests as one continuous stream —
        # the latest-run scoping below then applies across the family
        all_events = _events.read_events(events_path,
                                         include_rotated=True)
        starts = [i for i, ev in enumerate(all_events)
                  if ev.get("kind") in ("run_start", "bench_run")]
        if starts:
            all_events = all_events[starts[-1]:]
        for ev in all_events:
            kind = ev.get("kind")
            data = ev.get("data") or {}
            # the span stream: every record carrying schema-v2 trace
            # context feeds the latency section's SpanAssembler (raw,
            # not just data — the assembler needs ts/trace/span/parent)
            if ev.get("trace") is not None or ev.get("span") is not None:
                led.span_records.append(ev)
            if kind == "deadline_missed":
                led.deadline_miss_events += 1
            if kind == "step_time" and isinstance(
                    data.get("ms"), (int, float)):
                led.samples_ms.append(float(data["ms"]))
            elif kind == "step_timer" and isinstance(
                    data.get("ms_per_step"), (int, float)):
                window_ms.append(float(data["ms_per_step"]))
            elif kind == "trace_summary":
                led.scopes = data.get("scopes") or {}
                led.trace_file = data.get("trace_file")
            elif kind == "halo_traffic" and isinstance(
                    data.get("bytes_per_step"), (int, float)):
                # per-device ICI bytes one overlapped halo update moves
                # (drivers compute it from decomp.traced_halo_bytes())
                led.halo_bytes_per_step = float(data["bytes_per_step"])
            elif kind == "compile":
                led.compile_records.append(data)
            elif kind == "kernel_tier":
                led.kernel_tiers.append(data)
            elif kind == "block_choice":
                led.block_choices.append(data)
            elif kind == "autotune_mismatch":
                led.autotune_mismatches.append(data)
            elif kind in ("autotune_record", "autotune_sweep"):
                led.autotune_records.append({"kind": kind, **data})
            elif kind == "autotune_warm_build":
                led.autotune_warm_builds.append(data)
            elif kind == "health":
                # sentinel health vectors (obs.sentinel): the invariant
                # scalars become the numerics section's drift series
                led.health_events += 1
                for name, val in (data.get("invariants") or {}).items():
                    if isinstance(val, (int, float)):
                        led.health_series.setdefault(name, []).append(
                            (ev.get("step"), float(val)))
            elif kind == "diverged":
                led.diverged.append({"step": ev.get("step"),
                                     "fields": data.get("fields"),
                                     "offending_invariant":
                                         data.get("offending_invariant")})
            elif kind == "forensic_bundle":
                led.forensic_bundles.append(data.get("path"))
            elif kind == "lint":
                # the static-analysis verdict (pystella_tpu.lint): the
                # report's `lint` section, and the gate's refusal
                # trigger when the run's lint failed
                led.lint = data
            elif kind == "cold_start":
                # driver-emitted time-to-first-step breakdown (import /
                # build / trace / compile / first dispatch)
                led.cold_start_meta = data
            elif kind == "compile_cache":
                # persistent-compilation-cache wiring
                # (obs.memory.ensure_compilation_cache)
                led.cache_info = data
            elif kind == "warmstart_load":
                led.warmstart_loads.append(data)
            elif kind == "warmstart_mismatch":
                led.warmstart_mismatches.append(data)
            elif kind == "ensemble_done":
                # the ensemble driver's batch totals (member-steps/s,
                # occupancy, evictions) -> the `ensemble` report section
                led.ensemble_runs.append(data)
            elif kind == "ensemble_chunk" and isinstance(
                    data.get("ms"), (int, float)):
                led.ensemble_chunks_ms.append(float(data["ms"]))
            elif kind == "member_evicted":
                led.ensemble_evictions.append(
                    {"member": data.get("member"),
                     "step": ev.get("step"),
                     "scenario": data.get("scenario"),
                     "fields": data.get("fields"),
                     "params": data.get("params")})
            elif kind == "fault_injected":
                led.faults_injected += 1
            elif kind == "fault_detected":
                led.faults_detected.append(
                    {"step": ev.get("step"),
                     "kind": data.get("fault_kind"),
                     "error": data.get("error"),
                     "action": data.get("action")})
            elif kind == "recovery_attempt":
                led.recovery_attempts += 1
            elif kind == "recovery_failed":
                led.recovery_failures.append(data)
            elif kind == "run_resumed":
                led.resumes.append({"step": ev.get("step"), **data})
            elif kind == "run_degraded":
                led.degraded_events.append(
                    {"step": ev.get("step"), **data})
            elif kind == "remesh_plan":
                # the re-mesh library's decision record (resilience.
                # remesh): old/new mesh, survivors, rejected
                # candidates. Only a plan that actually CHANGED the
                # mesh marks degradation (a transport-blip recovery
                # emits changed=False and leaves the program alone);
                # steps ingested after a changed plan are the degraded
                # mesh's — the `degraded` block normalizes throughput
                # per SURVIVING chip from them.
                led.remesh_plans.append({"step": ev.get("step"), **data})
                if data.get("changed") and data.get("feasible"):
                    led._post_remesh_start = len(led.samples_ms)
            elif kind == "run_preempted":
                led.preempted_events.append(
                    {"step": ev.get("step"), **data})
            elif kind in ("checkpoint_save", "checkpoint_durable",
                          "checkpoint_fallback", "checkpoint_restore"):
                led.checkpoint_counts[kind] = \
                    led.checkpoint_counts.get(kind, 0) + 1
                if kind == "checkpoint_durable":
                    if isinstance(ev.get("step"), (int, float)):
                        led.durable_steps.append(int(ev["step"]))
                    if isinstance(data.get("wait_s"), (int, float)):
                        led.checkpoint_barrier_s += float(data["wait_s"])
            elif kind == "supervisor_done":
                led.supervisor_runs.append(data)
            elif kind == "fft_spectra":
                # a driver's sharded-spectra leg totals (scheme, grid,
                # field count, per-call ms) -> the `fft` report section
                led.fft_runs.append(data)
            elif kind == "spectra_time" and isinstance(
                    data.get("ms"), (int, float)):
                # one spectra output's wall time — emitted per output
                # step by the preheating driver (--spectra-cadence), so
                # spectra cost is a ledger-visible series, not a one-off
                # microbenchmark
                led.spectra_ms.append(float(data["ms"]))
            elif kind == "service_dispatch":
                # the scenario service's per-request dispatch record
                # (queue latency, priority class, warm/cold tag) — the
                # `service` section's queue-latency percentiles come
                # from these
                led.service_dispatches.append(data)
            elif kind == "service_lease":
                led.service_leases.append(data)
            elif kind == "service_admit":
                led.service_admits.append(data)
            elif kind == "service_reject":
                led.service_rejects.append(data)
            elif kind == "service_preempted":
                led.service_preemptions += 1
            elif kind == "service_lease_failed":
                led.service_lease_failures += 1
            elif kind == "member_result":
                led.service_results.append(data)
            elif kind == "service_done":
                led.service_done = data
            elif kind == "service_loadgen":
                led.service_loadgen = data
            elif kind == "slo_alert":
                led.slo_events.append(("alert", ev.get("ts"), data))
            elif kind == "slo_resolved":
                led.slo_events.append(("resolved", ev.get("ts"), data))
            elif kind == "fleet_scrape":
                led.fleet_scrapes.append(data)
            elif kind == "fleet_replica_lost":
                led.fleet_lost.append(data)
            elif kind == "fleet_alert":
                led.fleet_slo_events.append(("alert", ev.get("ts"),
                                             data))
            elif kind == "fleet_resolved":
                led.fleet_slo_events.append(("resolved", ev.get("ts"),
                                             data))
            elif kind == "fleet_announce":
                led.fleet_announces.append(data)
            elif kind == "fleet_withdraw":
                led.fleet_withdraws.append(data)
            elif kind == "perf_anomaly":
                led.perf_events.append(("anomaly", ev.get("ts"), data))
            elif kind == "perf_recovered":
                led.perf_events.append(("recovered", ev.get("ts"),
                                        data))
            elif kind == "perf_capture":
                led.perf_captures.append(data)
            elif kind == "perf_digest":
                led.perf_digests.append(data)
            elif kind == "capacity_footprint":
                led.capacity_footprints.append(data)
            elif kind == "capacity_watermark":
                led.capacity_watermarks.append(data)
            elif kind == "capacity_reject":
                led.capacity_rejects.append(data)
            elif kind == "capacity_evict":
                led.capacity_evictions.append(data)
            elif kind == "capacity_oom":
                led.capacity_oom.append(data)
            elif kind == "capacity_account":
                led.capacity_accounts.append(data)
            elif kind == "capacity_usage":
                led.capacity_usage = data
            elif kind in ("run_start", "bench_run"):
                led.meta = data
        if not led.samples_ms and window_ms:
            led.samples_ms = window_ms
            # window averages cannot be attributed before/after a
            # remesh (the index marker was taken against the empty
            # per-step list): drop the post-remesh split rather than
            # blending full-mesh windows into the degraded stats
            led._post_remesh_start = None
        if led.sites is None:
            shape = led.meta.get("grid_shape")
            if isinstance(shape, (list, tuple)) and shape:
                sites = 1
                for d in shape:
                    sites *= int(d)
                led.sites = sites
        led._pick_step_compile(step_label)
        if registry is not None:
            try:
                led.metrics = registry.snapshot()
            except Exception:
                led.metrics = {}
        return led

    def _pick_step_compile(self, step_label=None):
        """Bytes moved per step from the step executable's compile
        record: arguments read + outputs written is the floor on HBM
        traffic for one call. Prefers the record labeled ``step_label``;
        otherwise the one with the largest argument footprint (the step
        computation dominates any helper compiles)."""
        recs = [r for r in self.compile_records
                if isinstance(r.get("argument_bytes"), (int, float))]
        if not recs:
            return
        if step_label is not None:
            labeled = [r for r in recs if r.get("label") == step_label]
            recs = labeled or recs
        rec = max(recs, key=lambda r: r["argument_bytes"])
        out = rec.get("output_bytes")
        self.bytes_per_step = int(rec["argument_bytes"]) + int(out or 0)
        alias = rec.get("alias_bytes")
        if isinstance(alias, (int, float)):
            # donated (input->output aliased) bytes the step does NOT
            # hold twice — the realized HBM saving buffer donation buys
            # (0 on backends that drop donation, e.g. CPU)
            self.donated_bytes = int(alias)

    # -- derived quantities ------------------------------------------------

    def stats(self):
        return step_stats(self.samples_ms)

    def site_updates_per_s(self):
        st = self.stats()
        if not self.sites or not st.get("p50_ms"):
            return None
        return float(self.sites) * 1e3 / st["p50_ms"]

    def roofline(self):
        """Achieved HBM bandwidth (bytes/step over median step time)
        and its fraction of the device peak; fields are ``None`` when
        the inputs (compile bytes, step times, a known device kind) are
        missing."""
        st = self.stats()
        achieved = None
        if self.bytes_per_step and st.get("p50_ms"):
            achieved = self.bytes_per_step / (st["p50_ms"] / 1e3) / 1e9
        peak = _peak_gbps(self.env.get("device_kind"))
        frac = achieved / peak if achieved and peak else None
        return {"bytes_per_step": self.bytes_per_step,
                "achieved_gbps": achieved,
                "peak_gbps": peak,
                "fraction_of_peak": frac,
                "donated_bytes": self.donated_bytes,
                "kernel_tiers": self.kernel_tier_summary()}

    def kernel_tier_summary(self):
        """The roofline's dispatch record: which fused kernel tier each
        stepper ACTUALLY ran (``kernel_tier`` events: resident-chunk /
        streaming-chunk / pair / single / xla, with the modeled
        per-step lattice traffic — exact for the Pallas tiers, whose
        kernels read every input and write every output once), the
        chunk-vs-pair per-step HBM-traffic reduction when both tiers
        ran in the window, and the autotune-table provenance of the
        block choices (``block_choice`` sources + refused stale
        entries). ``None`` when the run carried no tier telemetry."""
        if not (self.kernel_tiers or self.block_choices
                or self.autotune_mismatches
                or self.autotune_warm_builds):
            return None
        rows = {}
        for kt in self.kernel_tiers:
            key = (kt.get("label"), kt.get("entrypoint"),
                   kt.get("tier"))
            rows[key] = kt  # last emission wins per dispatch site
        tiers = [
            {k: r.get(k) for k in (
                "label", "entrypoint", "tier", "chunk_depth",
                "bytes_per_step", "kernels_per_2_steps", "local_shape",
                "autotune")}
            for r in rows.values()]
        # measured per-step traffic reduction: the chunked stepper's
        # bytes/step against the pair-tier stepper of the same system
        # and local shape in the same window (the smoke payload runs
        # both back to back for exactly this comparison)
        reduction = None
        chunk = next((r for r in tiers
                      if "chunk" in (r.get("tier") or "")), None)
        if chunk is not None:
            pair = next(
                (r for r in tiers if r.get("tier") == "pair"
                 and r.get("local_shape") == chunk.get("local_shape")
                 and r.get("label") == chunk.get("label")), None)
            cb = chunk.get("bytes_per_step")
            pb = (pair or {}).get("bytes_per_step")
            if (isinstance(cb, (int, float))
                    and isinstance(pb, (int, float)) and pb):
                reduction = {
                    "chunk_bytes_per_step": int(cb),
                    "pair_bytes_per_step": int(pb),
                    "traffic_reduction": 1.0 - cb / pb}
        sources = {}
        for bc in self.block_choices:
            src = bc.get("source") or "?"
            sources[src] = sources.get(src, 0) + 1
        tables = sorted({r.get("path") for r in self.autotune_records
                         if r.get("path")})
        return {
            "dispatched": tiers,
            "chunk_vs_pair": reduction,
            "block_choice_sources": sources,
            "autotune": {
                "hits": sources.get("autotune", 0),
                "mismatches_refused": len(self.autotune_mismatches),
                "tables": tables,
                # the zero-extra-backend-compiles proof: a table-hit
                # rebuild dispatched against the warm compilation
                # cache (last record wins)
                "warm_build": (self.autotune_warm_builds[-1]
                               if self.autotune_warm_builds else None),
            },
        }

    def overlap_summary(self):
        """Exposed-vs-hidden communication time of the overlapped halo
        path, from the trace scope table: the comm denominator is the
        raw ``collective-permute`` op rows (present in device traces
        with no named-scope path; falls back to the ``halo_exchange``
        scope), the hidden share is bounded by the
        ``halo_overlap_interior`` compute that ran concurrently, and
        ``halo_overlap`` host spans count the overlapped calls in the
        window. With a ``halo_traffic`` event (per-device ICI bytes per
        overlapped call) an achieved-ICI-bandwidth estimate is derived.
        ``None`` when the trace shows no halo activity at all.

        Device rows appear once PER DEVICE in a trace, so the raw scope
        totals are fleet sums; ``comm_ms``/``interior_ms`` here are
        normalized to per-device wall time (divided by
        ``env.num_devices``), which is what the exposed-vs-hidden split
        and the per-device ICI bandwidth are about. ``halo_overlap``
        host spans are emitted once per call and are not scaled."""
        scopes = self.scopes or {}
        comm_scope = next((s for s in ("collective-permute",
                                       "halo_exchange") if s in scopes),
                          None)
        calls = scopes.get("halo_overlap")
        if comm_scope is None and calls is None:
            return None
        ndev = self.env.get("num_devices") or 1
        comm = scopes.get(comm_scope) or {}
        comm_ms = comm.get("total_ms")
        if isinstance(comm_ms, (int, float)):
            comm_ms /= ndev
        interior = scopes.get("halo_overlap_interior")
        interior_ms = interior.get("total_ms") if interior else None
        if isinstance(interior_ms, (int, float)):
            interior_ms /= ndev
        hidden = exposed = None
        if isinstance(comm_ms, (int, float)):
            # the interior compute is the only work the scheduler can
            # hide the collectives behind; without device rows for it
            # (host-span-only CPU traces) nothing is provably hidden
            hidden = min(comm_ms, interior_ms or 0.0)
            exposed = comm_ms - hidden
        n_calls = calls.get("count") if calls else None
        ici = None
        if (self.halo_bytes_per_step and n_calls
                and isinstance(comm_ms, (int, float)) and comm_ms > 0):
            ici = (self.halo_bytes_per_step * n_calls
                   / (comm_ms / 1e3) / 1e9)
        return {
            "comm_scope": comm_scope,
            "comm_ms": comm_ms,
            "interior_ms": interior_ms,
            "hidden_ms": hidden,
            "exposed_ms": exposed,
            "num_devices": ndev,
            "overlapped_calls": n_calls,
            "halo_bytes_per_step": self.halo_bytes_per_step,
            "achieved_ici_gbps": ici,
        }

    def comm(self):
        """Modeled-vs-measured communication: joins the lint event's
        static comm model (``static_comm`` — per-target per-invocation
        collective bytes the dataflow lint tier classified as halo /
        transpose / scalar from the compiled HLO) against the traffic
        the run actually measured. The halo leg pairs the
        ``smoke_overlap`` model with the ``halo_traffic`` event
        (``decomp.traced_halo_bytes()`` — the per-device ICI bytes one
        overlapped call moves, the same per-invocation unit the model
        counts); targets the run has no byte counter for stay
        model-only rows. ``covered`` is True only when at least one
        leg has BOTH sides — the gate refuses a report that claims
        coverage without a model. ``None`` when the run carried
        neither a model nor a measured counter."""
        model = (self.lint or {}).get("static_comm") or {}
        calls = (self.scopes or {}).get("halo_overlap") or {}
        measured = {}
        if self.halo_bytes_per_step:
            measured["smoke_overlap"] = {
                "bytes": float(self.halo_bytes_per_step),
                "class": "halo",
                "source": "halo_traffic",
                "calls": calls.get("count"),
            }
        if not model and not measured:
            return None
        legs = []
        for target in sorted(set(model) | set(measured)):
            block = model.get(target) or {}
            per_inv = block.get("per_invocation_bytes") or {}
            total = (block.get("total_bytes")
                     if block.get("modeled") else None)
            meas = measured.get(target)
            cls = meas["class"] if meas else (
                max(per_inv, key=per_inv.get) if per_inv else None)
            # compare like against like: a measured halo counter joins
            # the model's halo class, not the program's total (which
            # may also carry scalar all-reduces)
            modeled = per_inv.get(cls, total) if cls else total
            leg = {
                "target": target,
                "class": cls,
                "modeled_bytes": modeled,
                "modeled_total_bytes": total,
                "modeled_classes": per_inv or None,
                "measured_bytes": meas["bytes"] if meas else None,
                "measured_source": meas["source"] if meas else None,
                "calls": meas["calls"] if meas else None,
                "excess_pct": None,
                "within": None,
            }
            if meas and modeled:
                leg["excess_pct"] = round(
                    (meas["bytes"] / modeled - 1.0) * 100.0, 2)
                # 25% is the gate's default excess threshold
                # (PYSTELLA_GATE_COMM_EXCESS_PCT); recorded here so
                # the markdown can flag a leg without re-deriving it
                leg["within"] = leg["excess_pct"] <= 25.0
            legs.append(leg)
        return {
            "covered": any(leg["modeled_bytes"] and leg["measured_bytes"]
                           for leg in legs),
            "legs": legs,
            "halo_bytes_exchanged":
                self.metrics.get("halo_bytes_exchanged"),
        }

    def cold_start(self):
        """The cold-start summary: time-to-first-step breakdown (from
        the driver's ``cold_start`` event), the per-program compile
        table (from ``compile`` events — trace vs backend-compile
        seconds, fingerprint, persistent-cache attribution), cache
        wiring and hit rate, and the warm-start story (artifacts
        loaded, fingerprint mismatches). ``None`` when the run carried
        no compile telemetry at all.

        Nested instrumented dispatches each report their own row, so
        the table's per-row seconds may overlap (an outer chunk's row
        includes its inner kernels'); the headline phase numbers come
        from the driver's own breakdown, not a sum of rows."""
        if not (self.cold_start_meta or self.compile_records
                or self.cache_info or self.warmstart_loads
                or self.warmstart_mismatches):
            return None
        compiles = []
        hits = misses = 0
        for r in self.compile_records:
            h = int(r.get("cache_hits") or 0)
            m = int(r.get("cache_misses") or 0)
            hits += h
            misses += m
            compiles.append({
                "label": r.get("label"),
                "fingerprint": r.get("fingerprint"),
                "fingerprint_kind": r.get("fingerprint_kind"),
                "trace_s": float(r.get("trace_seconds") or 0.0),
                "compile_s": float(r.get("compile_seconds") or 0.0),
                "cache_hit": r.get("cache_hit"),
                "source": r.get("source"),
            })
        compiles.sort(key=lambda c: -(c["trace_s"] + c["compile_s"]))
        cache = dict(self.cold_start_meta.get("cache") or {})
        cache.setdefault("dir", self.cache_info.get("dir"))
        cache.setdefault("hits", hits)
        cache.setdefault("misses", misses)
        tot = (cache.get("hits") or 0) + (cache.get("misses") or 0)
        cache["hit_rate"] = (cache.get("hits", 0) / tot) if tot else None
        warm = self.cold_start_meta.get("warmstart") or {}
        artifacts = list(warm.get("artifacts") or [])
        seen = {(a.get("label"), a.get("fingerprint"))
                for a in artifacts}
        for w in self.warmstart_loads:
            key = (w.get("label"), w.get("fingerprint"))
            if key not in seen:
                seen.add(key)
                artifacts.append({"label": w.get("label"),
                                  "fingerprint": w.get("fingerprint"),
                                  "match": True})
        # a warmstart_mismatch event means the store REFUSED an
        # artifact and the driver took the cold jit path — an honest
        # fallback, not a warm-path claim, so it must not land in
        # `artifacts` where the gate would refuse the run as invalid
        # evidence; only driver-declared artifacts and actual loads
        # belong there
        fallbacks = [{"label": w.get("label"),
                      "fingerprint": w.get("fingerprint"),
                      "reason": w.get("reason")}
                     for w in self.warmstart_mismatches]
        warmstart = {
            "claimed": bool(warm.get("claimed",
                                     bool(self.warmstart_loads))),
            "artifacts": artifacts,
            "fallbacks": fallbacks,
        }
        return {
            "time_to_first_step_s":
                self.cold_start_meta.get("time_to_first_step_s"),
            "phases": self.cold_start_meta.get("phases") or {},
            "compiles": compiles[:64],
            "n_compile_events": len(compiles),
            "cache": cache,
            "warmstart": warmstart,
        }

    def numerics(self):
        """The numerics-observability summary (sentinel health): per
        invariant the first/last values and the least-squares
        **drift slope per step** (the quantity the gate compares — a
        silent physics regression shows up as a steeper slope), plus
        health-event counts, the sentinel's host-side overhead as a
        percentage of step time (from the ``sentinel`` and ``step``
        metrics timers), any sentinel trips, and forensic-bundle
        pointers. ``None`` when the run carried no numerics telemetry
        at all."""
        invariants = {}
        for name, series in self.health_series.items():
            vals = [v for _, v in series]
            steps = [s if isinstance(s, (int, float)) else i
                     for i, (s, _) in enumerate(series)]
            invariants[name] = {
                "n": len(vals),
                "first": vals[0],
                "last": vals[-1],
                "min": min(vals),
                "max": max(vals),
                "drift_per_step": _slope(steps, vals),
            }
        overhead = None
        step_s = self.metrics.get("step.total_s")
        sent_s = self.metrics.get("sentinel.total_s")
        if isinstance(step_s, (int, float)) and step_s > 0 \
                and isinstance(sent_s, (int, float)):
            overhead = 100.0 * sent_s / step_s
        checks = self.metrics.get("health_checks")
        if not (invariants or self.health_events or self.diverged
                or checks):
            return None
        return {
            "invariants": invariants,
            "health_events": self.health_events,
            "health_checks": checks,
            "sentinel_overhead_pct": overhead,
            "diverged": self.diverged,
            "forensic_bundles": self.forensic_bundles,
        }

    def ensemble(self):
        """The ensemble-throughput summary (:mod:`pystella_tpu.
        ensemble`): the driver's batch totals from ``ensemble_done``
        events (member-steps/s, mean batch occupancy, members
        completed), per-member throughput normalized per device
        (``member_steps_per_s_per_device`` — the packed-small-lattice
        figure of merit the TPU-window validation compares against the
        single-run headline), a chunk-dispatch time distribution from
        the ``ensemble_chunk`` events, and the eviction record (count +
        the ``member_evicted`` events naming each member, its scenario,
        and its parameter draw). ``None`` when the run carried no
        ensemble telemetry at all. Several ``ensemble_done`` events
        (one driver run per scenario group) are summed into the
        totals."""
        if not (self.ensemble_runs or self.ensemble_chunks_ms
                or self.ensemble_evictions):
            return None
        member_steps = sum(int(r.get("member_steps") or 0)
                           for r in self.ensemble_runs)
        wall_s = sum(float(r.get("wall_s") or 0.0)
                     for r in self.ensemble_runs)
        completed = sum(int(r.get("members_completed") or 0)
                        for r in self.ensemble_runs)
        rate = member_steps / wall_s if wall_s > 0 else None
        # the driver names each eviction in a member_evicted event AND
        # counts them in the ensemble_done totals; trust whichever
        # survived into the log (an event-window truncation must not
        # understate the count)
        evict_total = max(len(self.ensemble_evictions),
                          sum(int(r.get("evictions") or 0)
                              for r in self.ensemble_runs))
        ndev = self.env.get("num_devices")
        occs = [r.get("occupancy_mean") for r in self.ensemble_runs
                if isinstance(r.get("occupancy_mean"), (int, float))]
        return {
            "runs": len(self.ensemble_runs),
            "size": (self.ensemble_runs[-1].get("size")
                     if self.ensemble_runs else None),
            "member_steps": member_steps,
            "wall_s": wall_s,
            "member_steps_per_s": rate,
            "member_steps_per_s_per_device":
                (rate / ndev if rate and ndev else None),
            "occupancy_mean": (sum(occs) / len(occs) if occs else None),
            "members_completed": completed,
            "evictions": evict_total,
            "eviction_records": self.ensemble_evictions[:64],
            "chunks": step_stats(self.ensemble_chunks_ms),
        }

    def resilience(self):
        """The elastic-runtime summary (:mod:`pystella_tpu.resilience`):
        the incident table (one row per recovered fault, from
        ``run_resumed`` events with ``incident=True`` — kind, detect
        step, MTTR, steps replayed, attempts), detected-vs-claimed
        consistency against the supervisor's own ``supervisor_done``
        totals, recovery-attempt and give-up counts, the checkpoint
        record (saves scheduled vs durable, restore fallbacks, cadence
        between durable steps, summed durability-barrier seconds and
        their share of the supervised wall time), preemption/degrade
        flags, and the fault-injection count (a drill's harness
        activity is part of its evidence). ``None`` when the run
        carried no resilience telemetry at all.

        ``consistent`` is the gate's refusal trigger: a report whose
        supervisors CLAIM fewer incidents than the event log's
        RESOLVED (``run_resumed``) count is hiding a degraded fleet
        behind a clean headline. Detected-but-unresolved incidents (a
        run that died mid-recovery never wrote a ``supervisor_done``
        and could not claim its fault) land in ``unresolved`` instead
        — the gate warns on those, honestly."""
        # checkpoint events alone do NOT make a resilience section: any
        # plain Checkpointer-using driver emits them, and a section for
        # every such run would make the gate's lost-resilience-coverage
        # warning fire on runs that were never supervised — noise that
        # trains operators to ignore the real warning. The section
        # requires actual supervisor/fault telemetry; the checkpoint
        # record then rides inside it.
        if not (self.faults_detected or self.faults_injected
                or self.resumes or self.recovery_failures
                or self.preempted_events or self.supervisor_runs
                or self.remesh_plans):
            return None
        incidents = [
            {"kind": r.get("fault_kind"),
             "detected_at_step": r.get("from_step"),
             "restored_step": r.get("step"),
             "mttr_s": r.get("mttr_s"),
             "steps_replayed": r.get("steps_replayed"),
             "attempts": r.get("attempts")}
            for r in self.resumes if r.get("incident")]
        detected = len([f for f in self.faults_detected
                        if f.get("action") != "reraise"])
        mttrs = [i["mttr_s"] for i in incidents
                 if isinstance(i.get("mttr_s"), (int, float))]
        replayed = sum(int(i.get("steps_replayed") or 0)
                       for i in incidents)
        # several supervised runs can share one ingestion window (a
        # preempted run + its resumed successor, an ensemble beside a
        # main run): the CLAIM the gate audits is their SUM — keeping
        # only the last run's count would flag an honest multi-run log
        # as inconsistent
        claims = [r.get("incidents") for r in self.supervisor_runs
                  if isinstance(r.get("incidents"), int)]
        claimed = sum(claims) if claims else None
        cadence = None
        if len(self.durable_steps) >= 2:
            deltas = [b - a for a, b in zip(self.durable_steps,
                                            self.durable_steps[1:])
                      if b > a]
            if deltas:
                cadence = percentile(sorted(deltas), 50)
        walls = [r.get("wall_s") for r in self.supervisor_runs
                 if isinstance(r.get("wall_s"), (int, float))]
        wall_s = sum(walls) if walls else None
        overhead_pct = None
        if isinstance(wall_s, (int, float)) and wall_s > 0:
            overhead_pct = 100.0 * self.checkpoint_barrier_s / wall_s
        return {
            "incidents": incidents,
            "n_incidents": detected,
            "resolved": len(incidents),
            "unresolved": max(0, detected - len(incidents)),
            "claimed_incidents": claimed,
            # the claim is audited against RESOLVED incidents (each
            # run_resumed row), not raw detections: a run that died
            # mid-recovery never wrote a supervisor_done and could not
            # claim its fault — that is the honest `unresolved` path
            # (the gate warns), not a lie about recovered ones
            "consistent": (claimed is None
                           or int(claimed) >= len(incidents)),
            # completed = every supervised run in the window either
            # finished or handed off cleanly (a preemption drain is a
            # clean hand-off, not a death mid-recovery)
            "completed": (all(r.get("completed") or r.get("preempted")
                              for r in self.supervisor_runs)
                          if self.supervisor_runs else None),
            "mttr_s_mean": (sum(mttrs) / len(mttrs) if mttrs else None),
            "mttr_s_max": (max(mttrs) if mttrs else None),
            "steps_replayed": replayed,
            "recovery_attempts": self.recovery_attempts,
            "recovery_failures": self.recovery_failures[:8],
            "faults_injected": self.faults_injected,
            "preempted": bool(self.preempted_events),
            "degraded": self.degraded_block(),
            "checkpoints": {
                "saved": self.checkpoint_counts.get(
                    "checkpoint_save", 0),
                "durable": self.checkpoint_counts.get(
                    "checkpoint_durable", 0),
                "fallbacks": self.checkpoint_counts.get(
                    "checkpoint_fallback", 0),
                "restores": self.checkpoint_counts.get(
                    "checkpoint_restore", 0),
                "cadence_steps": cadence,
                "barrier_s": self.checkpoint_barrier_s,
                "barrier_pct_of_wall": overhead_pct,
            },
        }

    def degraded_block(self):
        """The degraded-mode accounting inside the ``resilience``
        section (``None`` when the run never degraded): the
        ``run_degraded`` notes, the ``remesh_plan`` decision records
        (:mod:`pystella_tpu.resilience.remesh` — old -> new mesh,
        survivors, rejected candidates), and the post-remesh
        throughput normalized per **surviving** chip — the only honest
        per-chip figure for a window that finished on fewer devices
        than it started with. The gate refuses a degraded report whose
        throughput section still normalizes by the full pre-loss mesh
        (:func:`pystella_tpu.obs.gate.compare_reports`)."""
        plan = self._degrading_plan()
        if not (self.degraded_events or plan is not None):
            # blip-only remesh_plan records (changed=False: every old
            # device survived, nothing was swapped) do NOT make a
            # degraded block — the window never degraded
            return None
        block = {"events": self.degraded_events[:8],
                 "remesh_plans": self.remesh_plans[:4]}
        if plan is not None:
            used = plan.get("devices") or plan.get("survivors") or []
            block.update({
                "old_mesh": plan.get("old_proc_shape"),
                "new_mesh": plan.get("new_proc_shape"),
                "surviving_devices": (len(plan.get("survivors"))
                                      if isinstance(plan.get("survivors"),
                                                    list) else None),
                "devices_used": len(used) if isinstance(used, list)
                else None,
                "lost_devices": (len(plan.get("lost"))
                                 if isinstance(plan.get("lost"), list)
                                 else None),
            })
            post = (self.samples_ms[self._post_remesh_start:]
                    if self._post_remesh_start is not None else [])
            post_block = None
            if post:
                stats = step_stats(post)
                per_chip = None
                if self.sites and stats.get("p50_ms") and used:
                    per_chip = (float(self.sites) * 1e3
                                / stats["p50_ms"] / len(used))
                post_block = {
                    "samples": len(post),
                    "p50_ms": stats.get("p50_ms"),
                    "site_updates_per_s_per_surviving_chip": per_chip,
                }
            block["post_remesh"] = post_block
        return block

    def fft(self):
        """The distributed-spectral-tier summary
        (:mod:`pystella_tpu.fourier.pencil`): per-call spectra wall
        times (``spectra_time`` events — the preheating driver emits
        one per spectra output, a bench leg several per run) folded
        with the driver's ``fft_spectra`` leg metadata (scheme, grid,
        field count); a ``5 N log₂ N``-per-field flops model over the
        median call time (achieved GFLOP/s, and — since distributed
        FFTs are HBM-bandwidth bound — a traffic model of the three
        local stages against the device's peak HBM bandwidth, the
        roofline fraction); and the per-stage scope rows
        (``fft_stage`` / ``fft_transpose``) with the transposes'
        exposed-vs-hidden split, derived exactly like the halo
        overlap's (hidden is bounded by the stage compute available to
        run concurrently; device rows are fleet sums, normalized
        per-device). ``None`` when the run carried no spectral
        telemetry at all."""
        scopes = self.scopes or {}
        # prefer the named-scope rows (TPU device traces carry the
        # scope path); fall back to the raw op rows (`fft.N` /
        # `all-to-all.N`), which CPU device traces carry instead
        stage = scopes.get("fft_stage") or scopes.get("fft")
        transpose = (scopes.get("fft_transpose")
                     or scopes.get("all-to-all"))
        samples = list(self.spectra_ms)
        if not samples:
            samples = [float(r["ms_per_call"]) for r in self.fft_runs
                       if isinstance(r.get("ms_per_call"), (int, float))]
        if not (self.fft_runs or samples or stage or transpose):
            return None
        meta = self.fft_runs[-1] if self.fft_runs else {}
        stats = step_stats(samples)

        model = None
        shape = meta.get("grid_shape")
        if isinstance(shape, (list, tuple)) and shape:
            import math
            ntot = 1
            for d in shape:
                ntot *= int(d)
            nfields = int(meta.get("nfields") or 1)
            # r2c forward per field: the standard 5 N log2 N real-FFT
            # flops model (the roofline numerator the ISSUE pins)
            flops = nfields * 5 * ntot * math.log2(max(ntot, 2))
            # traffic floor: each of the 3 local FFT stages reads and
            # writes the complex field once per field (transposes move
            # the same bytes again over the interconnect, not HBM).
            # The complex array is the r2c HALF spectrum — sizing the
            # full grid would overstate the roofline fraction ~2x, the
            # same accounting error the DFT replicate limit fixed
            kelems = ntot
            if meta.get("real", True) and len(shape) == 3:
                kelems = (int(shape[0]) * int(shape[1])
                          * (int(shape[2]) // 2 + 1))
            itemsize = int(meta.get("complex_itemsize") or 8)
            traffic = nfields * 3 * 2 * kelems * itemsize
            model = {"grid_shape": list(shape), "nfields": nfields,
                     "model_flops": flops,
                     "model_bytes": traffic,
                     "achieved_gflops": None,
                     "achieved_gbps": None,
                     "peak_gbps": _peak_gbps(self.env.get("device_kind")),
                     "fraction_of_peak": None}
            p50 = stats.get("p50_ms")
            if isinstance(p50, (int, float)) and p50 > 0:
                model["achieved_gflops"] = flops / (p50 / 1e3) / 1e9
                model["achieved_gbps"] = traffic / (p50 / 1e3) / 1e9
                if model["peak_gbps"]:
                    model["fraction_of_peak"] = (
                        model["achieved_gbps"] / model["peak_gbps"])

        ndev = self.env.get("num_devices") or 1

        def _row(scope_row):
            if not scope_row:
                return None
            out = dict(scope_row)
            if isinstance(out.get("total_ms"), (int, float)):
                out["total_ms_per_device"] = out["total_ms"] / ndev
            return out

        stage_row = _row(stage)
        transpose_row = _row(transpose)
        hidden = exposed = None
        if transpose_row and isinstance(
                transpose_row.get("total_ms_per_device"), (int, float)):
            t_ms = transpose_row["total_ms_per_device"]
            s_ms = (stage_row or {}).get("total_ms_per_device") or 0.0
            hidden = min(t_ms, s_ms)
            exposed = t_ms - hidden
        return {
            "scheme": meta.get("scheme"),
            "calls": len(samples) or None,
            "ms": stats,
            "runs": self.fft_runs[:16],
            "model": model,
            "stages": {"fft_stage": stage_row,
                       "fft_transpose": transpose_row},
            "transpose_hidden_ms": hidden,
            "transpose_exposed_ms": exposed,
            "num_devices": ndev,
        }

    def service(self):
        """The scenario-service summary (:mod:`pystella_tpu.service`):
        queue-latency percentiles per priority class (from the
        per-request ``service_dispatch`` records), time-to-first-step
        split warm/cold (from the lease records — the cold side pays
        the build+compile, the warm side must stay pure dispatch),
        tenant occupancy shares, preemption counts plus
        work-lost-to-replay, rejection/eviction accounting, and the
        warm-admission evidence the gate audits: every warm admission's
        fingerprint status and the warm leases' backend-compile count
        from the compile ledger (a warm lease that compiled broke the
        dispatch-never-compile contract). ``None`` when the run carried
        no service telemetry at all."""
        if not (self.service_dispatches or self.service_leases
                or self.service_admits or self.service_rejects
                or self.service_results or self.service_done):
            return None
        by_class = {}
        qlats = []
        for d in self.service_dispatches:
            q = d.get("queue_latency_s")
            if not isinstance(q, (int, float)):
                continue
            qlats.append(float(q))
            by_class.setdefault(str(d.get("priority")), []).append(
                float(q))
        ttfs = {"warm": [], "cold": []}
        for rec in self.service_leases:
            t = rec.get("ttfs_s")
            if isinstance(t, (int, float)):
                ttfs["warm" if rec.get("warm") else "cold"].append(
                    float(t))
        warm_admissions = [
            {"id": a.get("id"), "fingerprint": a.get("fingerprint"),
             "fingerprint_ok": a.get("fingerprint_ok")}
            for a in self.service_admits if a.get("warm")]
        warm_leases = [r for r in self.service_leases if r.get("warm")]
        warm_compiles = sum(int(r.get("backend_compiles") or 0)
                            for r in warm_leases)
        rejects = {}
        for r in self.service_rejects:
            reason = str(r.get("reason"))
            rejects[reason] = rejects.get(reason, 0) + 1
        statuses = {}
        for r in self.service_results:
            s = str(r.get("status"))
            statuses[s] = statuses.get(s, 0) + 1
        tenant_steps = dict(self.service_done.get("tenant_steps") or {})
        if not tenant_steps:
            for rec in self.service_leases:
                for tenant, steps in (rec.get("tenant_steps")
                                      or {}).items():
                    tenant_steps[tenant] = (tenant_steps.get(tenant, 0)
                                            + int(steps))
        total_steps = sum(tenant_steps.values())
        replayed = self.service_done.get("replayed_member_steps")
        if replayed is None:
            replayed = sum(int(r.get("replayed_member_steps") or 0)
                           for r in self.service_leases)
        out = {
            "requests": len({d.get("id")
                             for d in self.service_dispatches}),
            "admitted": len(self.service_admits),
            "results": statuses,
            "completed": statuses.get("completed", 0),
            "diverged": statuses.get("diverged", 0),
            "rejected": rejects,
            "queue_latency_s": {
                "overall": _lat_stats(qlats),
                "by_priority": {cls: _lat_stats(v)
                                for cls, v in sorted(by_class.items())},
            },
            "ttfs_s": {"warm": _lat_stats(ttfs["warm"]),
                       "cold": _lat_stats(ttfs["cold"])},
            "warm_claimed": bool(warm_admissions),
            "warm_admissions": warm_admissions[:64],
            "warm_leases": len(warm_leases),
            "warm_lease_backend_compiles": warm_compiles,
            "leases": len(self.service_leases),
            "lease_failures": self.service_lease_failures,
            "preemptions": self.service_preemptions,
            "work_lost_to_replay_member_steps": int(replayed or 0),
            "tenant_member_steps": tenant_steps,
            "tenant_share": ({t: s / total_steps
                              for t, s in tenant_steps.items()}
                             if total_steps else {}),
        }
        if self.service_loadgen:
            out["loadgen"] = {
                k: self.service_loadgen.get(k)
                for k in ("seed", "requests", "warm_admissions",
                          "cold_admissions", "preempted_requests",
                          "preempt_bitexact")}
        return out

    def alerts(self):
        """The live-alert summary (:mod:`pystella_tpu.obs.slo` burn-rate
        monitor): per-leg alert/resolve counts, flaps (re-fires after a
        resolve), total and max alert durations, and — the field the
        gate audits — ``unresolved``: alerts still burning when the run
        record ends. An unresolved burn alert beside a post-hoc SLO
        section that claims green is the live/post-hoc contradiction
        the gate refuses as invalid evidence (exit 2). ``None`` when
        the run carried no live SLO telemetry at all (monitor not
        attached — coverage the gate warns about when the baseline had
        it)."""
        if not self.slo_events:
            return None
        return _alert_rollup(self.slo_events)

    def fleet(self):
        """The fleet federation summary (:mod:`pystella_tpu.obs.fleet`
        aggregator over the replica registry): the replica table as of
        the last scrape (each row annotated with heartbeat age and
        per-replica scrape outcomes), the aggregated fleet SLO legs,
        lost replicas, the scrape-success rate, skew/divergence
        findings, and the fleet alert rollup (same shape as
        :meth:`alerts`, built from ``fleet_alert``/``fleet_resolved``).
        The ``coverage`` block is the gate's honesty anchor: a fleet
        claim over a run with lost replicas or failed scrapes is a
        claim over PARTIAL evidence, and ``complete`` says which kind
        this run's record is. ``None`` when the run carried no fleet
        telemetry at all."""
        if not (self.fleet_scrapes or self.fleet_lost
                or self.fleet_slo_events):
            return None
        replicas = {}
        for sc in self.fleet_scrapes:
            for row in sc.get("replicas") or []:
                rid = row.get("replica")
                if rid:
                    replicas[rid] = dict(row)
        lost_rows = []
        for data in self.fleet_lost:
            rid = data.get("replica")
            lost_rows.append({"replica": rid,
                              "reason": data.get("reason"),
                              "age_s": data.get("age_s")})
            if rid:
                replicas.setdefault(rid, {"replica": rid})
                replicas[rid]["status"] = "lost"
                replicas[rid]["lost_reason"] = data.get("reason")
        last = self.fleet_scrapes[-1] if self.fleet_scrapes else {}
        ok = sum(int(sc.get("ok") or 0) for sc in self.fleet_scrapes)
        failed = sum(int(sc.get("failed") or 0)
                     for sc in self.fleet_scrapes)
        attempts = ok + failed
        lost_ids = sorted({r["replica"] for r in lost_rows
                           if r.get("replica")})
        return {
            "replicas": [replicas[rid] for rid in sorted(replicas)],
            "scrapes": len(self.fleet_scrapes),
            "endpoint_ok": ok,
            "endpoint_failed": failed,
            "scrape_success_rate": (ok / attempts if attempts
                                    else None),
            "replicas_lost": lost_rows,
            "dead": last.get("dead"),
            "legs": last.get("legs"),
            "alerts": (_alert_rollup(self.fleet_slo_events)
                       if self.fleet_slo_events else None),
            "skew": {
                "skewed": any(sc.get("skewed")
                              for sc in self.fleet_scrapes),
                "stacks": last.get("stacks"),
            },
            "divergence": sorted({sig for sc in self.fleet_scrapes
                                  for sig in (sc.get("divergent")
                                              or [])}),
            "announces": len(self.fleet_announces),
            "withdraws": len(self.fleet_withdraws),
            "coverage": {
                "replicas": len(replicas),
                "lost": len(lost_ids),
                "endpoint_failed": failed,
                "complete": not lost_ids and failed == 0,
            },
        }

    def perf(self):
        """The continuous-performance summary (:mod:`pystella_tpu.obs.
        perf` detector + flight recorder): the anomaly rollup per
        program signature (same shape as :meth:`alerts` — the field
        the gate audits is ``anomalies.unresolved``, anomalies still
        open when the run record ends), the latest digest window per
        signature (p50/p95/p99 ms), the flight-recorder captures with
        their Perfetto artifact paths (the ledger link the gate checks
        when anomalies fired), and the straggler attribution from the
        last anomaly that carried one. ``None`` when the run carried
        no continuous-performance telemetry at all (``PYSTELLA_PERF=0``
        or a pre-PR-17 log — coverage the gate warns about when the
        baseline had it)."""
        if not (self.perf_events or self.perf_captures
                or self.perf_digests):
            return None
        # reuse the alert rollup: an anomaly is a fired alert on the
        # leg named by its signature, recovery resolves it
        anomalies = _alert_rollup([
            (("alert" if kind == "anomaly" else "resolved"), ts,
             {**data, "leg": data.get("signature", "step"),
              "value": data.get("ms"),
              "bar": data.get("baseline_ms")})
            for kind, ts, data in self.perf_events])
        digests = {}
        for data in self.perf_digests:
            sig = data.get("signature", "step")
            digests[sig] = {k: data.get(k) for k in
                            ("count", "mean_ms", "p50_ms", "p95_ms",
                             "p99_ms")}
        straggler = None
        for kind, _, data in reversed(self.perf_events):
            if kind == "anomaly" and data.get("straggler"):
                straggler = data["straggler"]
                break
        captures = [{k: data.get(k) for k in
                     ("signature", "reason", "artifact", "logdir",
                      "steps", "suppressed", "error") if k in data}
                    for data in self.perf_captures]
        return {
            "anomalies": anomalies,
            "digests": digests or None,
            "captures": captures,
            "captures_suppressed": max(
                [int(c.get("suppressed") or 0) for c in captures],
                default=0),
            "straggler": straggler,
        }

    def capacity(self):
        """The capacity & goodput summary (:mod:`pystella_tpu.obs.
        capacity`): the per-program footprint table (predicted bytes +
        prediction source) against the observed live watermarks, the
        predicted-vs-peak reconciliation, the headroom series summary,
        memory-aware admission rejections/evictions, OOM forensic
        bundles, and the retire-time chargeback — per-tenant
        chip-second/goodput table plus the overall
        ``goodput = committed member-steps / total chip-seconds``. The
        ``coverage`` block is the gate's honesty anchor: a capacity
        claim over leases with NO watermark samples cannot read as
        ``complete`` (CPU runs degrade to ``predicted_only``). ``None``
        when the run carried no capacity telemetry at all (pre-PR-19
        logs, or the plane disabled)."""
        if not (self.capacity_footprints or self.capacity_watermarks
                or self.capacity_accounts or self.capacity_usage
                or self.capacity_rejects or self.capacity_oom):
            return None
        usage = self.capacity_usage or {}
        footprints = {}
        for data in self.capacity_footprints:
            key = (data.get("label"), data.get("fingerprint"))
            footprints[key] = {
                k: data.get(k) for k in
                ("label", "fingerprint", "predicted_bytes", "source")}
        peaks = [w.get("peak_bytes_in_use")
                 for w in self.capacity_watermarks
                 if isinstance(w.get("peak_bytes_in_use"),
                               (int, float))]
        in_use = [w.get("bytes_in_use") for w in self.capacity_watermarks
                  if isinstance(w.get("bytes_in_use"), (int, float))]
        headroom = [w.get("headroom_frac")
                    for w in self.capacity_watermarks
                    if isinstance(w.get("headroom_frac"), (int, float))]
        coverage = usage.get("coverage") or {
            "leases": None,
            "leases_sampled": None,
            "watermark_samples": len(self.capacity_watermarks),
            "predicted_only": not self.capacity_watermarks,
            "complete": False,
        }
        rejects = {
            "count": len(self.capacity_rejects),
            "signatures": sorted({r.get("signature")
                                  for r in self.capacity_rejects
                                  if r.get("signature")}),
            "last": (self.capacity_rejects[-1]
                     if self.capacity_rejects else None),
        }
        return {
            "footprints": [footprints[k] for k in sorted(
                footprints, key=lambda k: (str(k[0]), str(k[1])))],
            "watermarks": {
                "samples": len(self.capacity_watermarks),
                "peak_bytes_in_use": max(peaks) if peaks else None,
                "max_bytes_in_use": max(in_use) if in_use else None,
                "headroom_frac_max": (max(headroom) if headroom
                                      else None),
            },
            "reconciliation": usage.get("reconciliation"),
            "rejections": rejects,
            "evictions": len(self.capacity_evictions),
            "oom_bundles": [d.get("path") for d in self.capacity_oom],
            "tenants": usage.get("tenants"),
            "goodput": usage.get("goodput"),
            "total_chip_s": usage.get("total_chip_s"),
            "committed_steps": usage.get("committed_steps"),
            "waste_chip_s": usage.get("waste_chip_s"),
            "capacity_bytes": usage.get("capacity_bytes"),
            "headroom": usage.get("headroom"),
            "resident_predicted_bytes":
                usage.get("resident_predicted_bytes"),
            "accounts": self.capacity_accounts[-64:],
            "coverage": coverage,
        }

    def latency(self):
        """Request-scoped critical-path latency attribution
        (:mod:`pystella_tpu.obs.spans` over the schema-v2 trace
        stream): per-request phase decomposition percentiles (queue
        wait / admission / compile / chunk compute / checkpoint
        barrier / recovery replay / preempt drain), the dominant-phase
        histogram, the partition audit (phases must sum to the
        measured submit→retire wall), the deadline ledger (miss rate
        per priority class + margin distribution — the gate's
        deadline-miss SLO), and the coverage split (``unassembled``
        names traced requests whose span tree failed to close — the
        gate's coverage-loss warning). ``None`` when the run carried
        no traced request at all (v1 logs, or
        ``PYSTELLA_TRACE_SERVICE=0``)."""
        if not self.span_records:
            return None
        # deferred import: obs.spans has a ``python -m`` entry point,
        # and a module-level import here would put it in sys.modules
        # before runpy executes it (same reason obs/__init__ leaves
        # gate and warmstart out)
        from pystella_tpu.obs import spans as _spans
        summary = _spans.SpanAssembler.from_records(
            self.span_records).summary()
        if summary is not None:
            summary["deadline"]["miss_events"] = \
                self.deadline_miss_events
        return summary

    def _degrading_plan(self):
        """The last remesh_plan that actually changed the mesh
        (``changed`` and ``feasible``), or ``None`` — transport-blip
        recoveries emit ``changed=False`` plans that must not make a
        window read as degraded."""
        for plan in reversed(self.remesh_plans):
            if plan.get("changed") and plan.get("feasible"):
                return plan
        return None

    def _per_chip_throughput(self):
        """The per-chip normalization of the headline throughput —
        and the honesty marker the gate audits: a window that
        re-meshed finished on the SURVIVORS, so its per-chip figure
        uses the POST-remesh step times divided by the degraded
        mesh's device count (``basis: "surviving"``) — never the
        full-mesh-dominated whole-window median over the survivors,
        which would overstate the degraded throughput ~(lost/survived)
        fold. ``None`` rate when no post-remesh samples exist (e.g. a
        drill whose timed loop ran before the remesh); ``None``
        entirely when no device count is known."""
        plan = self._degrading_plan()
        if plan is not None:
            used = plan.get("devices") or plan.get("survivors") or []
            chips = len(used) if isinstance(used, list) else None
            post = (self.samples_ms[self._post_remesh_start:]
                    if self._post_remesh_start is not None else [])
            rate = None
            if post and self.sites:
                p50 = step_stats(post).get("p50_ms")
                if p50:
                    rate = float(self.sites) * 1e3 / p50
            basis = "surviving"
        else:
            rate = self.site_updates_per_s()
            chips = self.env.get("num_devices")
            basis = "all"
        if not chips:
            return None
        return {"chips": int(chips), "basis": basis,
                "site_updates_per_s_per_chip": (rate / chips
                                                if rate else None)}

    # -- report ------------------------------------------------------------

    def report(self):
        """The JSON-safe report dict (``perf_report.json`` schema v1;
        doc/observability.md documents every field)."""
        return {
            "schema": REPORT_SCHEMA_VERSION,
            "generated_ts": time.time(),
            "label": self.label,
            "env": self.env,
            "run": self.meta,
            "steps": self.stats(),
            "samples_ms": [round(x, 6)
                           for x in self.samples_ms[-MAX_SAMPLES:]],
            "throughput": {
                "sites": self.sites,
                "site_updates_per_s": self.site_updates_per_s(),
                "per_chip": self._per_chip_throughput(),
            },
            "roofline": self.roofline(),
            "overlap": self.overlap_summary(),
            "comm": self.comm(),
            "cold_start": self.cold_start(),
            "numerics": self.numerics(),
            "ensemble": self.ensemble(),
            "resilience": self.resilience(),
            "fft": self.fft(),
            "service": self.service(),
            "latency": self.latency(),
            "alerts": self.alerts(),
            "fleet": self.fleet(),
            "perf": self.perf(),
            "capacity": self.capacity(),
            "lint": self.lint,
            "scopes": self.scopes,
            "trace_file": self.trace_file,
            "metrics": self.metrics,
        }

    def write(self, out_dir, stem="perf_report"):
        """Write ``<stem>.json`` + ``<stem>.md`` under ``out_dir``;
        returns the JSON path. Also emits a ``perf_report`` run event
        pointing at it, so the event log records which report a run
        produced."""
        os.makedirs(out_dir, exist_ok=True)
        rep = self.report()
        json_path = os.path.join(out_dir, stem + ".json")
        with open(json_path, "w") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
            f.write("\n")
        with open(os.path.join(out_dir, stem + ".md"), "w") as f:
            f.write(render_markdown(rep))
        _events.emit("perf_report", path=json_path, label=self.label)
        return json_path


def _alert_rollup(events):
    """Per-leg fire/resolve bookkeeping over ``[("alert"|"resolved",
    ts, data), ...]`` — one definition for both the live
    (``slo_alert``) and fleet (``fleet_alert``) vocabularies, so their
    report shapes cannot diverge."""
    by_leg = {}

    def row(leg):
        return by_leg.setdefault(str(leg), {
            "alerts": 0, "resolved": 0, "flaps": 0,
            "total_alert_s": 0.0, "max_alert_s": None,
            "open": None})

    for kind, ts, data in events:
        r = row(data.get("leg"))
        if kind == "alert":
            r["alerts"] += 1
            r["flaps"] = max(0, r["alerts"] - 1)
            r["open"] = {"since_ts": ts,
                         "value": data.get("value"),
                         "bar": data.get("bar"),
                         "burn_fast": data.get("burn_fast"),
                         "burn_slow": data.get("burn_slow")}
        else:
            r["resolved"] += 1
            d = data.get("duration_s")
            if d is None and r["open"] is not None \
                    and isinstance(ts, (int, float)) \
                    and isinstance(r["open"].get("since_ts"),
                                   (int, float)):
                d = ts - r["open"]["since_ts"]
            if isinstance(d, (int, float)):
                r["total_alert_s"] += float(d)
                r["max_alert_s"] = (float(d)
                                    if r["max_alert_s"] is None
                                    else max(r["max_alert_s"],
                                             float(d)))
            r["open"] = None
    unresolved = [{"leg": leg, **r["open"]}
                  for leg, r in sorted(by_leg.items())
                  if r["open"] is not None]
    return {
        "alerts": sum(r["alerts"] for r in by_leg.values()),
        "resolved": sum(r["resolved"] for r in by_leg.values()),
        "flaps": sum(r["flaps"] for r in by_leg.values()),
        "unresolved": unresolved,
        "by_leg": {leg: {k: v for k, v in r.items() if k != "open"}
                   for leg, r in sorted(by_leg.items())},
    }


def _lat_stats(samples_s):
    """Latency-distribution summary in SECONDS (the service section's
    queue-latency / TTFS fields; ``step_stats`` stays the millisecond
    step-time shape): count, mean, p50/p90/p95, max."""
    if not samples_s:
        return {"count": 0}
    s = sorted(float(x) for x in samples_s)
    return {
        "count": len(s),
        "mean_s": sum(s) / len(s),
        "p50_s": percentile(s, 50),
        "p90_s": percentile(s, 90),
        "p95_s": percentile(s, 95),
        "max_s": s[-1],
    }


def _slope(xs, ys):
    """Least-squares slope of ``ys`` against ``xs`` (0.0 for degenerate
    inputs) — the invariant-drift-per-step statistic."""
    n = len(xs)
    if n < 2:
        return 0.0
    mx = sum(xs) / n
    my = sum(ys) / n
    var = sum((x - mx) ** 2 for x in xs)
    if var == 0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / var


def _fmt(x, spec=".4g", none="—"):
    return format(x, spec) if isinstance(x, (int, float)) else none


def render_markdown(rep):
    """Human rendering of a report dict (the ``perf_report.md`` body)."""
    env = rep.get("env", {})
    st = rep.get("steps", {})
    tp = rep.get("throughput", {})
    rf = rep.get("roofline", {})
    lines = [
        f"# Perf report — {rep.get('label') or 'unlabeled run'}",
        "",
        "Generated "
        + time.strftime("%Y-%m-%d %H:%M:%S UTC",
                        time.gmtime(rep.get("generated_ts", 0)))
        + f" · schema v{rep.get('schema')}",
        "",
        "## Environment",
        "",
        f"- jax {env.get('jax')} / jaxlib {env.get('jaxlib')}"
        + (f" / libtpu {env['libtpu']}" if env.get("libtpu") else "")
        + f", python {env.get('python')}",
        f"- platform `{env.get('platform')}`, device kind "
        f"`{env.get('device_kind')}`, {env.get('num_devices')} device(s), "
        f"{env.get('num_processes')} process(es), "
        f"host `{env.get('hostname')}`",
        "",
        "## Step-time distribution",
        "",
        f"{st.get('count', 0)} steps: "
        f"p50 {_fmt(st.get('p50_ms'))} ms, p90 {_fmt(st.get('p90_ms'))} ms, "
        f"p99 {_fmt(st.get('p99_ms'))} ms, MAD {_fmt(st.get('mad_ms'))} ms "
        f"(mean {_fmt(st.get('mean_ms'))}, min {_fmt(st.get('min_ms'))}, "
        f"max {_fmt(st.get('max_ms'))})",
        "",
        "## Throughput",
        "",
        f"- sites/step: {_fmt(tp.get('sites'), ',.0f')}",
        f"- site-updates/s (median step): "
        f"{_fmt(tp.get('site_updates_per_s'), '.4e')}",
        "",
        "## Roofline",
        "",
        f"- bytes/step (XLA arg+out floor): "
        f"{_fmt(rf.get('bytes_per_step'), ',.0f')}",
        f"- achieved {_fmt(rf.get('achieved_gbps'))} GB/s of "
        f"{_fmt(rf.get('peak_gbps'))} GB/s peak -> "
        f"{_fmt(rf.get('fraction_of_peak'), '.1%')} of roofline",
        f"- donated (input->output aliased) bytes: "
        f"{_fmt(rf.get('donated_bytes'), ',.0f')} — HBM the step does "
        "not hold twice (from the step compile's alias analysis)",
        "",
    ]
    kt = rf.get("kernel_tiers")
    if kt:
        lines += ["### Kernel tiers dispatched", ""]
        for row in kt.get("dispatched") or []:
            extra = ""
            if row.get("chunk_depth"):
                extra = f", depth {row['chunk_depth']}"
            if isinstance(row.get("bytes_per_step"), (int, float)):
                extra += (f", {row['bytes_per_step']:,.0f} lattice "
                          "bytes/step")
            src = (row.get("autotune") or {}).get("source")
            if src:
                extra += f", blocks via {src}"
            lines.append(f"- {row.get('label')}.{row.get('entrypoint')}"
                         f": **{row.get('tier')}**{extra}")
        cvp = kt.get("chunk_vs_pair")
        if cvp:
            lines.append(
                f"- chunk vs pair: "
                f"{cvp['chunk_bytes_per_step']:,} vs "
                f"{cvp['pair_bytes_per_step']:,} bytes/step -> "
                f"{cvp['traffic_reduction']:.1%} less HBM traffic")
        at = kt.get("autotune") or {}
        lines.append(
            f"- autotune: {at.get('hits', 0)} table hit(s), "
            f"{at.get('mismatches_refused', 0)} stale entr(ies) "
            "refused"
            + (f", table {at['tables'][-1]}" if at.get("tables")
               else ""))
        lines.append("")
    lint = rep.get("lint")
    if lint:
        lines += ["## Lint", ""]
        lines.append(
            f"- static analysis {'PASSED' if lint.get('ok') else '**FAILED**'}"
            f": {_fmt(lint.get('errors'), '.0f', '0')} error(s), "
            f"{_fmt(lint.get('warnings'), '.0f', '0')} warning(s) "
            f"({', '.join(lint.get('checks') or []) or 'no checks'})")
        don = lint.get("donation") or {}
        if don:
            lines.append(
                f"- donation coverage {_fmt(don.get('coverage_pct'), '.1f')}%"
                f" ({_fmt(don.get('aliased_bytes'), ',.0f')} of "
                f"{_fmt(don.get('donatable_bytes'), ',.0f')} donatable "
                f"step-state bytes aliased; "
                f"{_fmt(don.get('wasted_bytes'), ',.0f')} B wasted)")
        for reason in (lint.get("first_errors") or [])[:5]:
            lines.append(f"- {reason}")
        lines.append("")
    ov = rep.get("overlap")
    if ov:
        lines += ["## Communication overlap", ""]
        lines.append(
            f"- halo comm (`{ov.get('comm_scope')}` rows, per-device): "
            f"{_fmt(ov.get('comm_ms'))} ms in the traced window — "
            f"hidden behind interior compute {_fmt(ov.get('hidden_ms'))}"
            f" ms, exposed {_fmt(ov.get('exposed_ms'))} ms")
        if ov.get("interior_ms") is None:
            lines.append(
                "- *(no `halo_overlap_interior` device rows in this "
                "trace — host-span-only captures cannot attribute "
                "hiding, so all comm time counts as exposed)*")
        if ov.get("halo_bytes_per_step"):
            lines.append(
                f"- halo traffic {_fmt(ov['halo_bytes_per_step'], ',.0f')}"
                f" B/call x {_fmt(ov.get('overlapped_calls'), '.0f')} "
                f"overlapped call(s) -> achieved "
                f"~{_fmt(ov.get('achieved_ici_gbps'))} GB/s ICI "
                "(per-device estimate)")
        lines.append("")
    cm = rep.get("comm")
    if cm:
        lines += ["## Modeled vs measured communication", ""]
        for leg in cm.get("legs") or []:
            row = (f"- {leg.get('target')} ({leg.get('class') or '—'}): "
                   f"modeled {_fmt(leg.get('modeled_bytes'), ',.0f')} B")
            if leg.get("measured_bytes") is not None:
                row += (f", measured "
                        f"{_fmt(leg.get('measured_bytes'), ',.0f')} B "
                        f"({leg.get('measured_source')}) -> "
                        f"{_fmt(leg.get('excess_pct'), '+.1f')}% vs "
                        f"model"
                        + ("" if leg.get("within") in (None, True)
                           else " **EXCESS**"))
            else:
                row += " (model-only: no measured counter this run)"
            lines.append(row)
        if not cm.get("covered"):
            lines.append("- *(no leg carries both a model and a "
                         "measured counter — comm not covered)*")
        lines.append("")
    cs = rep.get("cold_start")
    if cs:
        lines += ["## Cold start", ""]
        ph = cs.get("phases") or {}
        # drivers report different phase sets (bench smoke: import/
        # build, TPU payload: dial, examples: setup) — render whatever
        # this run measured, in pipeline order, instead of a fixed
        # key list that dashes out the dial/setup share
        order = ("import_s", "dial_s", "setup_s", "build_s", "trace_s",
                 "compile_s", "first_dispatch_s")
        keys = ([k for k in order if k in ph]
                + sorted(k for k in ph if k not in order))
        parts = ", ".join(
            f"{k[:-2].replace('_', ' ') if k.endswith('_s') else k} "
            f"{_fmt(ph.get(k))}" for k in keys)
        lines.append(
            f"- time to first step: "
            f"{_fmt(cs.get('time_to_first_step_s'))} s"
            + (f" ({parts} s)" if parts else ""))
        ca = cs.get("cache") or {}
        lines.append(
            f"- compilation cache: "
            + (f"`{ca.get('dir')}` — {_fmt(ca.get('hits'), '.0f', '0')} "
               f"hit(s) / {_fmt(ca.get('misses'), '.0f', '0')} miss(es)"
               f" (hit rate {_fmt(ca.get('hit_rate'), '.1%')})"
               if ca.get("dir") else "not wired "
               "(set PYSTELLA_COMPILE_CACHE_DIR)"))
        ws = cs.get("warmstart") or {}
        if ws.get("claimed"):
            arts = ws.get("artifacts") or []
            ok = sum(1 for a in arts if a.get("match"))
            bad = [a for a in arts if a.get("match") is False]
            lines.append(
                f"- warm start: {ok} AOT artifact(s) loaded"
                + (f", **{len(bad)} fingerprint mismatch(es)**"
                   if bad else ""))
            for a in bad[:5]:
                lines.append(f"  - `{a.get('label')}`: "
                             f"{a.get('reason') or 'mismatch'}")
        falls = ws.get("fallbacks") or []
        if falls:
            lines.append(
                f"- {len(falls)} stale artifact(s) refused (honest "
                "cold fallback)")
            for a in falls[:5]:
                lines.append(f"  - `{a.get('label')}`: "
                             f"{a.get('reason') or 'mismatch'}")
        compiles = cs.get("compiles") or []
        if compiles:
            lines += ["", "| program | trace s | compile s | cache |",
                      "|---|---|---|---|"]
            for c in compiles[:12]:
                hit = c.get("cache_hit")
                tag = "hit" if hit else ("miss" if hit is False else "—")
                lines.append(
                    f"| `{c.get('label')}` | {_fmt(c.get('trace_s'))} "
                    f"| {_fmt(c.get('compile_s'))} | {tag} |")
            if len(compiles) > 12:
                lines.append(f"| … {len(compiles) - 12} more | | | |")
        lines.append("")
    nm = rep.get("numerics")
    if nm:
        lines += ["## Numerics health", ""]
        for name, row in sorted((nm.get("invariants") or {}).items()):
            lines.append(
                f"- invariant `{name}`: {_fmt(row.get('first'), '.6g')} "
                f"-> {_fmt(row.get('last'), '.6g')} over "
                f"{row.get('n')} sample(s), drift "
                f"{_fmt(row.get('drift_per_step'), '.3e')}/step")
        lines.append(
            f"- {_fmt(nm.get('health_checks'), '.0f', '0')} health "
            f"check(s), sentinel overhead "
            f"{_fmt(nm.get('sentinel_overhead_pct'), '.2f')}% of step "
            "time (host-side; the in-graph reductions are inside the "
            "step samples themselves)")
        for d in nm.get("diverged") or []:
            lines.append(
                f"- **DIVERGED** at step {d.get('step')}: "
                f"{d.get('fields')}"
                + (f" (invariant `{d['offending_invariant']}`)"
                   if d.get("offending_invariant") else ""))
        for b in nm.get("forensic_bundles") or []:
            lines.append(f"- forensic bundle: `{b}`")
        lines.append("")
    en = rep.get("ensemble")
    if en:
        lines += ["## Ensemble", ""]
        lines.append(
            f"- {_fmt(en.get('member_steps'), ',.0f')} member-steps in "
            f"{_fmt(en.get('wall_s'))} s -> "
            f"{_fmt(en.get('member_steps_per_s'))} member-steps/s"
            + (f" ({_fmt(en['member_steps_per_s_per_device'])} per "
               "device)" if en.get("member_steps_per_s_per_device")
               else ""))
        lines.append(
            f"- batch size {_fmt(en.get('size'), '.0f')}, mean "
            f"occupancy {_fmt(en.get('occupancy_mean'), '.1%')}, "
            f"{_fmt(en.get('members_completed'), '.0f', '0')} member(s) "
            f"completed over {_fmt(en.get('runs'), '.0f')} driver "
            "run(s)")
        ch = en.get("chunks") or {}
        if ch.get("count"):
            lines.append(
                f"- {ch['count']} batched dispatch(es): p50 "
                f"{_fmt(ch.get('p50_ms'))} ms, p90 "
                f"{_fmt(ch.get('p90_ms'))} ms per chunk")
        nev = en.get("evictions") or 0
        lines.append(f"- {nev} member eviction(s)")
        for e in (en.get("eviction_records") or [])[:8]:
            lines.append(
                f"  - member {e.get('member')} (scenario "
                f"`{e.get('scenario')}`) at step {e.get('step')}: "
                f"{e.get('fields')}")
        lines.append("")
    rz = rep.get("resilience")
    if rz:
        lines += ["## Resilience", ""]
        n = rz.get("n_incidents") or 0
        lines.append(
            f"- {n} incident(s) detected, "
            f"{_fmt(rz.get('resolved'), '.0f', '0')} recovered "
            f"(MTTR mean {_fmt(rz.get('mttr_s_mean'))} s, max "
            f"{_fmt(rz.get('mttr_s_max'))} s), "
            f"{_fmt(rz.get('steps_replayed'), '.0f', '0')} step(s) "
            f"replayed over "
            f"{_fmt(rz.get('recovery_attempts'), '.0f', '0')} recovery "
            "attempt(s)")
        if rz.get("consistent") is False:
            lines.append(
                "- **INCONSISTENT**: the supervisor claims "
                f"{rz.get('claimed_incidents')} incident(s) but the "
                f"event log records {n} — the gate refuses this report")
        incs = rz.get("incidents") or []
        if incs:
            lines += ["", "| kind | detected at | restored to | MTTR s "
                          "| replayed | attempts |",
                      "|---|---|---|---|---|---|"]
            for i in incs[:12]:
                lines.append(
                    f"| {i.get('kind')} | {i.get('detected_at_step')} "
                    f"| {i.get('restored_step')} "
                    f"| {_fmt(i.get('mttr_s'))} "
                    f"| {i.get('steps_replayed')} "
                    f"| {i.get('attempts')} |")
            lines.append("")
        ck = rz.get("checkpoints") or {}
        lines.append(
            f"- checkpoints: {_fmt(ck.get('saved'), '.0f', '0')} "
            f"scheduled, {_fmt(ck.get('durable'), '.0f', '0')} durable "
            f"(cadence {_fmt(ck.get('cadence_steps'), '.0f')} steps), "
            f"{_fmt(ck.get('fallbacks'), '.0f', '0')} walk-back "
            f"fallback(s); durability barriers "
            f"{_fmt(ck.get('barrier_s'))} s"
            + (f" ({_fmt(ck.get('barrier_pct_of_wall'), '.2f')}% of "
               "supervised wall time)"
               if ck.get("barrier_pct_of_wall") is not None else ""))
        if rz.get("faults_injected"):
            lines.append(
                f"- {rz['faults_injected']} fault(s) INJECTED by the "
                "harness (a drill, not weather)")
        if rz.get("preempted"):
            lines.append("- run **preempted** (drained to a durable "
                         "checkpoint; resume with the supervisor)")
        deg = rz.get("degraded")
        if isinstance(deg, dict):
            for d in (deg.get("events") or [])[:4]:
                lines.append(f"- **degraded** at step {d.get('step')}: "
                             f"{d.get('note')}")
            if deg.get("new_mesh"):
                total = ((deg.get("devices_used") or 0)
                         + (deg.get("lost_devices") or 0))
                lines.append(
                    f"- re-mesh: {deg.get('old_mesh')} -> "
                    f"{deg.get('new_mesh')} "
                    f"({_fmt(deg.get('devices_used'), '.0f')} of "
                    f"{_fmt(total, '.0f')} devices)")
            post = deg.get("post_remesh")
            if post:
                lines.append(
                    "- post-remesh: p50 "
                    f"{_fmt(post.get('p50_ms'))} ms/step over "
                    f"{post.get('samples')} sample(s), "
                    f"{_fmt(post.get('site_updates_per_s_per_surviving_chip'), '.3e')}"
                    " site-updates/s per SURVIVING chip")
        elif deg:  # pre-remesh-library reports: a bare event list
            for d in deg[:4]:
                lines.append(f"- **degraded** at step {d.get('step')}: "
                             f"{d.get('note')}")
        lines.append("")
    sv = rep.get("service")
    if sv:
        lines += ["## Service", ""]
        ql = (sv.get("queue_latency_s") or {})
        overall = ql.get("overall") or {}
        lines.append(
            f"- {_fmt(sv.get('requests'), '.0f', '0')} request(s) "
            f"dispatched over {_fmt(sv.get('leases'), '.0f', '0')} "
            f"lease(s): {_fmt(sv.get('completed'), '.0f', '0')} "
            f"completed, {_fmt(sv.get('diverged'), '.0f', '0')} "
            f"diverged, "
            f"{_fmt(sum((sv.get('rejected') or {}).values()), '.0f', '0')}"
            f" rejected"
            + (f" ({', '.join(f'{k}: {v}' for k, v in sorted((sv.get('rejected') or {}).items()))})"
               if sv.get("rejected") else ""))
        lines.append(
            f"- queue latency: p50 {_fmt(overall.get('p50_s'))} s, "
            f"p95 {_fmt(overall.get('p95_s'))} s over "
            f"{_fmt(overall.get('count'), '.0f', '0')} dispatch(es)")
        for cls, row in sorted((ql.get("by_priority") or {}).items()):
            lines.append(
                f"  - class {cls}: p50 {_fmt(row.get('p50_s'))} s, "
                f"p95 {_fmt(row.get('p95_s'))} s "
                f"({row.get('count')} dispatch(es))")
        tf = sv.get("ttfs_s") or {}
        warm_t, cold_t = tf.get("warm") or {}, tf.get("cold") or {}
        lines.append(
            f"- time-to-first-step: warm p50 "
            f"{_fmt(warm_t.get('p50_s'))} s "
            f"({_fmt(warm_t.get('count'), '.0f', '0')} lease(s)), "
            f"cold p50 {_fmt(cold_t.get('p50_s'))} s "
            f"({_fmt(cold_t.get('count'), '.0f', '0')} lease(s))")
        lines.append(
            f"- warm path: {_fmt(sv.get('warm_leases'), '.0f', '0')} "
            f"warm lease(s), "
            f"{_fmt(sv.get('warm_lease_backend_compiles'), '.0f', '0')} "
            "backend compile(s) on them (the contract is ZERO)"
            + ("" if not sv.get("warm_lease_backend_compiles") else
               " — **dispatch-never-compile violated**"))
        bad_warm = [a for a in sv.get("warm_admissions") or []
                    if a.get("fingerprint_ok") is False]
        if bad_warm:
            lines.append(
                f"- **{len(bad_warm)} warm admission(s) over "
                "mismatched fingerprints** — the gate refuses this "
                "report")
        lines.append(
            f"- {_fmt(sv.get('preemptions'), '.0f', '0')} "
            f"preemption(s), "
            f"{_fmt(sv.get('work_lost_to_replay_member_steps'), '.0f', '0')}"
            f" member-step(s) lost to replay, "
            f"{_fmt(sv.get('lease_failures'), '.0f', '0')} lease "
            "failure(s)")
        shares = sv.get("tenant_share") or {}
        if shares:
            lines.append("- tenant occupancy: " + ", ".join(
                f"{t} {_fmt(f, '.1%')}"
                for t, f in sorted(shares.items())))
        lg = sv.get("loadgen")
        if lg:
            lines.append(
                f"- loadgen (seed {lg.get('seed')}): "
                f"{_fmt(lg.get('requests'), '.0f', '0')} request(s), "
                f"{_fmt(lg.get('warm_admissions'), '.0f', '0')} warm / "
                f"{_fmt(lg.get('cold_admissions'), '.0f', '0')} cold "
                "admission(s), preempted-resume bit-exact: "
                f"{lg.get('preempt_bitexact')}")
        lines.append("")
    lat = rep.get("latency")
    if lat:
        lines += ["## Latency (request critical path)", ""]
        wall = lat.get("wall_s") or {}
        lines.append(
            f"- {_fmt(lat.get('assembled'), '.0f', '0')} of "
            f"{_fmt(lat.get('traced'), '.0f', '0')} traced request(s) "
            f"assembled; submit→retire wall p50 "
            f"{_fmt(wall.get('p50_s'))} s, p95 {_fmt(wall.get('p95_s'))}"
            " s")
        if lat.get("unassembled"):
            n_bad = lat.get("unassembled_total")
            if not isinstance(n_bad, int):
                n_bad = len(lat["unassembled"])
            lines.append(
                f"- **{n_bad} traced request(s) "
                "failed to assemble** (coverage loss; see "
                "`latency.unassembled`)")
        chk = lat.get("phase_sum_check") or {}
        if chk.get("max_rel_err") is not None:
            lines.append(
                f"- partition audit: phases sum to the wall within "
                f"{_fmt(chk['max_rel_err'], '.2%')} worst-case "
                f"(tolerance {_fmt(chk.get('tolerance'), '.0%')}: "
                f"{'OK' if chk.get('ok') else '**VIOLATED**'})")
        phases = lat.get("phases_s") or {}
        if phases:
            lines += ["", "| phase | requests | p50 s | p95 s | max s |",
                      "|---|---|---|---|---|"]
            for name, row in sorted(
                    phases.items(),
                    key=lambda kv: -(kv[1].get("p50_s") or 0.0)):
                lines.append(
                    f"| `{name}` | {row.get('count')} "
                    f"| {_fmt(row.get('p50_s'))} "
                    f"| {_fmt(row.get('p95_s'))} "
                    f"| {_fmt(row.get('max_s'))} |")
            lines.append("")
        dom = lat.get("dominant_phase") or {}
        if dom:
            lines.append("- dominant phase: " + ", ".join(
                f"`{p}` ×{n}" for p, n in sorted(
                    dom.items(), key=lambda kv: -kv[1])))
        dl = lat.get("deadline") or {}
        if dl.get("deadlined"):
            rate = dl.get("miss_rate")
            lines.append(
                f"- deadlines: {dl.get('missed')} of "
                f"{dl.get('deadlined')} deadlined request(s) missed "
                f"({_fmt(rate, '.0%')}); margin p50 "
                f"{_fmt((dl.get('margin_s') or {}).get('p50_s'))} s")
            for cls, row in sorted((dl.get("by_priority") or {}).items()):
                lines.append(
                    f"  - class {cls}: {row.get('missed')}/"
                    f"{row.get('deadlined')} missed "
                    f"({_fmt(row.get('miss_rate'), '.0%')})")
        lines.append("")
    al = rep.get("alerts")
    if al:
        lines += ["## SLO alerts (live burn-rate monitor)", ""]
        lines.append(
            f"- {_fmt(al.get('alerts'), '.0f', '0')} alert(s) fired, "
            f"{_fmt(al.get('resolved'), '.0f', '0')} resolved, "
            f"{_fmt(al.get('flaps'), '.0f', '0')} flap(s) "
            "(re-fires after a resolve)")
        for rec in al.get("unresolved") or []:
            lines.append(
                f"- **UNRESOLVED at exit**: `{rec.get('leg')}` burning "
                f"at {_fmt(rec.get('value'))} vs bar "
                f"{_fmt(rec.get('bar'))} — the gate refuses this "
                "report if its post-hoc SLO section claims green")
        for leg, r in sorted((al.get("by_leg") or {}).items()):
            lines.append(
                f"  - `{leg}`: {r.get('alerts')} fired / "
                f"{r.get('resolved')} resolved, total "
                f"{_fmt(r.get('total_alert_s'))} s alerting"
                + (f" (max {_fmt(r.get('max_alert_s'))} s)"
                   if r.get("max_alert_s") is not None else ""))
        lines.append("")
    pf = rep.get("perf")
    if pf:
        lines += ["## Continuous performance (obs.perf)", ""]
        an = pf.get("anomalies") or {}
        lines.append(
            f"- {_fmt(an.get('alerts'), '.0f', '0')} anomaly(ies) "
            f"fired, {_fmt(an.get('resolved'), '.0f', '0')} recovered, "
            f"{_fmt(an.get('flaps'), '.0f', '0')} flap(s)")
        for rec in an.get("unresolved") or []:
            lines.append(
                f"- **UNRESOLVED at exit**: `{rec.get('leg')}` at "
                f"{_fmt(rec.get('value'))} ms vs baseline "
                f"{_fmt(rec.get('bar'))} ms — the gate refuses this "
                "report if its step-time verdict claims green")
        for sig, d in sorted((pf.get("digests") or {}).items()):
            lines.append(
                f"  - `{sig}` digest: p50 {_fmt(d.get('p50_ms'))} / "
                f"p95 {_fmt(d.get('p95_ms'))} / "
                f"p99 {_fmt(d.get('p99_ms'))} ms over "
                f"{_fmt(d.get('count'), '.0f')} step(s)")
        st = pf.get("straggler")
        if st:
            slow = st.get("slowest") or {}
            lines.append(
                f"- straggler attribution: host {slow.get('host')} at "
                f"{_fmt(slow.get('mean_ms'))} ms vs fleet median "
                f"{_fmt(st.get('median_ms'))} ms "
                f"(skew {_fmt(st.get('skew'))}"
                + (", **skewed**)" if st.get("skewed") else ")"))
        for cap in pf.get("captures") or []:
            art = cap.get("artifact")
            lines.append(
                f"- flight-recorder capture (`{cap.get('signature')}`, "
                f"{cap.get('steps')} step(s)): "
                + (f"`{art}`" if art else "no artifact ("
                   + str(cap.get("error")
                         or "profiler produced no trace") + ")"))
        sup = pf.get("captures_suppressed")
        if sup:
            lines.append(f"- {sup} capture request(s) rate-limit "
                         "suppressed (one trace per cooldown)")
        lines.append("")
    fl = rep.get("fleet")
    if fl:
        lines += ["## Fleet (replica registry + federation)", ""]
        cov = fl.get("coverage") or {}
        lines.append(
            f"- {_fmt(cov.get('replicas'), '.0f', '0')} replica(s) "
            f"seen, {_fmt(cov.get('lost'), '.0f', '0')} lost, "
            f"{_fmt(fl.get('scrapes'), '.0f', '0')} aggregation "
            f"pass(es), scrape success "
            f"{_fmt(fl.get('scrape_success_rate'), '.0%')} "
            f"({'complete' if cov.get('complete') else 'PARTIAL'} "
            "coverage)")
        rows = fl.get("replicas") or []
        if rows:
            lines += ["", "| replica | status | heartbeat age s "
                      "| queue | fingerprint |", "|---|---|---|---|---|"]
            for row in rows:
                lines.append(
                    f"| `{row.get('replica')}` | {row.get('status')} "
                    f"| {_fmt(row.get('age_s'))} "
                    f"| {_fmt(row.get('queue_depth'), '.0f')} "
                    f"| `{row.get('fingerprint') or '—'}` |")
            lines.append("")
        for rec in fl.get("replicas_lost") or []:
            lines.append(
                f"- **replica lost**: `{rec.get('replica')}` "
                f"({rec.get('reason')}) — the fleet verdict is "
                "degraded, not silently averaged over the survivors")
        legs = fl.get("legs") or {}
        if legs:
            lines += ["", "| fleet leg | value | bar | alerting |",
                      "|---|---|---|---|"]
            for name, leg in sorted(legs.items()):
                lines.append(
                    f"| `{name}` | {_fmt(leg.get('value_fast'))} "
                    f"| {_fmt(leg.get('bar'))} "
                    f"| {'YES' if leg.get('alerting') else 'no'} |")
            lines.append("")
        fal = fl.get("alerts")
        if fal:
            lines.append(
                f"- fleet alerts: {_fmt(fal.get('alerts'), '.0f', '0')} "
                f"fired, {_fmt(fal.get('resolved'), '.0f', '0')} "
                f"resolved, {_fmt(fal.get('flaps'), '.0f', '0')} "
                "flap(s)")
            for rec in fal.get("unresolved") or []:
                lines.append(
                    f"- **UNRESOLVED at exit**: fleet `{rec.get('leg')}` "
                    f"burning at {_fmt(rec.get('value'))} vs bar "
                    f"{_fmt(rec.get('bar'))}")
        skew = fl.get("skew") or {}
        if skew.get("skewed"):
            lines.append(
                f"- **version/flag SKEW**: {skew.get('stacks')} "
                "distinct compiler stacks across live replicas")
        if fl.get("divergence"):
            lines.append(
                "- **warm-fingerprint divergence**: "
                + ", ".join(f"`{s}`" for s in fl["divergence"]))
        lines.append("")
    cap = rep.get("capacity")
    if cap:
        lines += ["## Capacity & goodput (obs.capacity)", ""]
        cov = cap.get("coverage") or {}
        wm = cap.get("watermarks") or {}
        lines.append(
            f"- {_fmt(wm.get('samples'), '.0f', '0')} watermark "
            f"sample(s) over {_fmt(cov.get('leases'), '.0f')} "
            f"lease(s) ("
            + ("complete coverage" if cov.get("complete") else
               ("predicted-only — stat-less backend"
                if cov.get("predicted_only") else "PARTIAL coverage"))
            + ")")
        rec = cap.get("reconciliation")
        if rec:
            lines.append(
                f"- reconciliation: predicted "
                f"{_fmt(rec.get('predicted_bytes'), ',.0f')} B vs peak "
                f"{_fmt(rec.get('peak_bytes_in_use'), ',.0f')} B in use "
                f"(rel err {_fmt(rec.get('rel_err'), '.1%')})")
        fps = cap.get("footprints") or []
        if fps:
            lines += ["", "| program | fingerprint | predicted bytes "
                      "| source |", "|---|---|---|---|"]
            for row in fps:
                lines.append(
                    f"| `{row.get('label')}` "
                    f"| `{row.get('fingerprint') or '—'}` "
                    f"| {_fmt(row.get('predicted_bytes'), ',.0f')} "
                    f"| {row.get('source')} |")
            lines.append("")
        rej = cap.get("rejections") or {}
        if rej.get("count"):
            last = rej.get("last") or {}
            lines.append(
                f"- **{rej['count']} CapacityExceeded rejection(s)** "
                f"({', '.join(f'`{s}`' for s in rej.get('signatures') or [])}) "
                f"— last: predicted "
                f"{_fmt(last.get('predicted_bytes'), ',.0f')} B over "
                f"budget {_fmt(last.get('budget_bytes'), ',.0f')} B")
        if cap.get("evictions"):
            lines.append(
                f"- {cap['evictions']} warm-pool eviction(s) under the "
                "queue-behind-eviction policy")
        for path in cap.get("oom_bundles") or []:
            lines.append(f"- **OOM forensic bundle**: `{path}`")
        tenants = cap.get("tenants") or {}
        if tenants:
            lines += ["", "| tenant | requests | chip-s | waste chip-s "
                      "| committed steps | goodput steps/chip-s |",
                      "|---|---|---|---|---|---|"]
            for name in sorted(tenants):
                row = tenants[name]
                lines.append(
                    f"| `{name}` | {_fmt(row.get('requests'), '.0f')} "
                    f"| {_fmt(row.get('chip_s'))} "
                    f"| {_fmt(row.get('waste_chip_s'))} "
                    f"| {_fmt(row.get('committed_steps'), '.0f')} "
                    f"| {_fmt(row.get('goodput'))} |")
            lines.append("")
        if cap.get("goodput") is not None:
            lines.append(
                f"- goodput: **{_fmt(cap.get('goodput'))} committed "
                f"member-steps per chip-second** "
                f"({_fmt(cap.get('committed_steps'), '.0f', '0')} steps "
                f"/ {_fmt(cap.get('total_chip_s'))} chip-s, "
                f"{_fmt(cap.get('waste_chip_s'))} chip-s replay+drain "
                "waste)")
        lines.append("")
    ff = rep.get("fft")
    if ff:
        lines += ["## FFT / spectra", ""]
        st_f = ff.get("ms") or {}
        lines.append(
            f"- scheme `{ff.get('scheme')}`: "
            f"{_fmt(ff.get('calls'), '.0f', '0')} spectra call(s), p50 "
            f"{_fmt(st_f.get('p50_ms'))} ms (p90 "
            f"{_fmt(st_f.get('p90_ms'))}, MAD {_fmt(st_f.get('mad_ms'))})")
        mo = ff.get("model")
        if mo:
            lines.append(
                f"- flops model (5 N log₂ N × {mo.get('nfields')} "
                f"field(s) at {mo.get('grid_shape')}): "
                f"{_fmt(mo.get('model_flops'), '.3e')} flops -> "
                f"{_fmt(mo.get('achieved_gflops'))} GFLOP/s achieved")
            lines.append(
                f"- stage-traffic roofline: "
                f"{_fmt(mo.get('model_bytes'), ',.0f')} B modeled -> "
                f"{_fmt(mo.get('achieved_gbps'))} GB/s of "
                f"{_fmt(mo.get('peak_gbps'))} GB/s peak "
                f"({_fmt(mo.get('fraction_of_peak'), '.1%')} of "
                "roofline)")
        stg = ff.get("stages") or {}
        rows = [(k, v) for k, v in stg.items() if v]
        if rows:
            lines += ["", "| scope | count | total ms | per-device ms |",
                      "|---|---|---|---|"]
            for name, row in rows:
                lines.append(
                    f"| `{name}` | {row.get('count')} "
                    f"| {_fmt(row.get('total_ms'))} "
                    f"| {_fmt(row.get('total_ms_per_device'))} |")
            lines.append("")
        if ff.get("transpose_exposed_ms") is not None:
            lines.append(
                f"- transposes: {_fmt(ff.get('transpose_hidden_ms'))} "
                "ms hidden behind local FFT stages, "
                f"{_fmt(ff.get('transpose_exposed_ms'))} ms exposed "
                "(per-device)")
        lines.append("")
    lines += [
        "## Per-scope breakdown",
        "",
    ]
    scopes = rep.get("scopes") or {}
    if scopes:
        lines += ["| scope | count | total ms | mean ms |",
                  "|---|---|---|---|"]
        for name, row in sorted(
                scopes.items(),
                key=lambda kv: -kv[1].get("total_ms", 0.0)):
            lines.append(
                f"| `{name}` | {row.get('count')} "
                f"| {_fmt(row.get('total_ms'))} "
                f"| {_fmt(row.get('mean_ms'))} |")
        if rep.get("trace_file"):
            lines += ["", f"Trace: `{rep['trace_file']}`"]
    else:
        lines.append("*(no trace captured — per-scope durations "
                     "unavailable; rerun with `--profile`)*")
    lines.append("")
    return "\n".join(lines)
