"""Causal spans over the run-event stream: request-scoped tracing.

PR 12's scenario service emits a flat JSONL record (``service_request``
/ ``service_admit`` / ``service_dispatch`` / ... keyed by request id),
so "where did this request's latency go?" meant hand-joining events.
This module closes that gap the way ``obs.trace`` closed the profiler
gap: the schema-v2 ``trace``/``span``/``parent`` fields
(:mod:`pystella_tpu.obs.events`) make every emitted event a node in a
per-request causal tree, and the :class:`SpanAssembler` reconstructs

- the **span tree** per request: a root ``service_request_span``
  (submit → retire) with ``service_lease_span`` children (one per lease
  the request rode — a preempted request keeps ONE trace id across all
  of them), and leaf spans for every attributable cost inside a lease
  (checkpoint barriers, recovery replay, the preemption drain);
- the **critical-path decomposition**: the submit→retire wall time
  partitioned into the :data:`PHASES` vocabulary — queue wait,
  admission, backend compile, chunk compute, checkpoint barrier,
  recovery replay, preemption drain. The phases are a *partition by
  construction* (compute is the lease residual after the measured
  inner costs), so they sum to the measured wall time; the summary
  records the worst relative error so the property is auditable, not
  assumed;
- the **deadline ledger**: per-request ``margin_s`` (retire vs
  ``deadline_ts``, recorded hit or miss by
  :class:`~pystella_tpu.service.results.ResultEmitter`) and miss rates
  per priority class — the report's ``latency`` section and the gate's
  deadline-miss SLO consume exactly this.

The assembled timeline exports as a Perfetto-loadable trace file
(:meth:`SpanAssembler.export_perfetto`) whose span names are registered
trace scopes (:mod:`pystella_tpu.obs.scope`), so hardware profiler
captures and service traces read through one parser
(:func:`pystella_tpu.obs.trace.scope_durations` folds both).

Stdlib-only and jax-free, like ``obs.events``: the bench orchestrator
and offline analysis load it by file. CLI::

    python -m pystella_tpu.obs.spans --events run_events.jsonl \
        [--perfetto service_trace.json] [--trace <id>]

Old (v1) logs carry no trace fields: every reader here tolerates their
absence and simply assembles nothing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["PHASES", "RequestTrace", "SpanAssembler", "main"]

#: the critical-path phase vocabulary, in lifecycle order. Every name
#: is a registered trace scope (obs.scope), so Perfetto exports fold
#: through obs.trace.scope_durations like hardware captures do.
PHASES = (
    "service_admission",          # submit -> admission verdict
    "service_queue_wait",         # queued behind the scheduler (per leg)
    "service_compile",            # cold lease: build+trace+compile paid
    "service_chunk_compute",      # supervised chunk loop (residual)
    "service_checkpoint_barrier",  # durability-barrier waits
    "service_recovery_replay",    # device-loss/numerics recovery (MTTR)
    "service_preempt_drain",      # drain to a durable checkpoint
)

#: event kinds that terminate a request's root span
_TERMINAL_KINDS = ("member_result", "service_reject")


def _get(ev, key):
    return ev.get(key) if isinstance(ev, dict) else None


def _data(ev):
    d = ev.get("data")
    return d if isinstance(d, dict) else {}


def _num(x, default=0.0):
    return float(x) if isinstance(x, (int, float)) else default


def _stats(samples):
    """Latency summary in seconds — the ledger's ``_lat_stats`` shape,
    so the ``latency`` and ``service`` sections quantify identically.
    Imported lazily: the ledger imports this module (inside
    ``latency()``), so a module-level import back would be fragile."""
    from pystella_tpu.obs.ledger import _lat_stats
    return _lat_stats([x for x in samples
                       if isinstance(x, (int, float))])


class RequestTrace:
    """One request's assembled span tree + critical path.

    Attributes: ``trace`` (the trace id), ``request_id``, ``tenant``,
    ``priority``, ``signature``, ``status``, ``submit_ts`` /
    ``retire_ts`` / ``wall_s``, ``phases`` (phase name → seconds, a
    partition of the wall), ``spans`` (flat list of
    ``{name, span, parent, t0, dur_s}`` rows, root first), ``leases``
    (lease span ids in ride order), and the deadline fields
    (``deadline_ts`` / ``margin_s`` / ``deadline_missed``, ``None``
    for undeadlined requests). ``assembled`` is False (with
    ``problems``) when the tree cannot be closed — e.g. the request
    never retired in the ingested window.
    """

    def __init__(self, trace):
        self.trace = trace
        self.request_id = None
        self.tenant = None
        self.priority = None
        self.signature = None
        self.status = None
        self.submit_ts = None
        self.retire_ts = None
        self.wall_s = None
        self.phases = {}
        self.spans = []
        self.leases = []
        self.deadline_ts = None
        self.margin_s = None
        self.deadline_missed = None
        self.assembled = False
        self.problems = []

    @property
    def dominant_phase(self):
        if not self.phases:
            return None
        return max(self.phases, key=lambda p: self.phases[p])

    @property
    def phase_sum_s(self):
        return sum(self.phases.values())

    def phase_sum_rel_err(self):
        """|Σ phases − wall| / wall — the partition-audit statistic
        (``None`` for an unassembled or zero-wall tree)."""
        if not self.assembled or not self.wall_s:
            return None
        return abs(self.phase_sum_s - self.wall_s) / self.wall_s

    def as_row(self):
        return {
            "id": self.request_id, "trace": self.trace,
            "tenant": self.tenant, "priority": self.priority,
            "status": self.status,
            "wall_s": (round(self.wall_s, 6)
                       if self.wall_s is not None else None),
            "phases_s": {p: round(s, 6)
                         for p, s in self.phases.items()},
            "dominant_phase": self.dominant_phase,
            "leases": len(self.leases),
            "deadline_missed": self.deadline_missed,
            "margin_s": (round(self.margin_s, 6)
                         if self.margin_s is not None else None),
        }


class SpanAssembler:
    """Reconstruct per-request span trees from an event stream.

    Build with :meth:`from_events` (reads the whole rotated family, so
    a request whose spans straddle a ``rotate_bytes`` boundary still
    assembles) or :meth:`from_records` (already-loaded dicts). The
    heavy lifting happens once in :meth:`assemble`; :meth:`summary`
    and :meth:`export_perfetto` derive from it.
    """

    def __init__(self, records):
        self.records = [r for r in records if isinstance(r, dict)]
        self._by_trace = {}
        self._by_span = {}
        self._by_parent = {}
        for ev in self.records:
            trace = ev.get("trace")
            if trace is not None:
                self._by_trace.setdefault(str(trace), []).append(ev)
            span = ev.get("span")
            if span is not None:
                self._by_span.setdefault(str(span), []).append(ev)
            parent = ev.get("parent")
            if parent is not None and parent != span:
                self._by_parent.setdefault(str(parent), []).append(ev)
        self._trees = None

    @classmethod
    def from_records(cls, records):
        return cls(records)

    @classmethod
    def from_events(cls, path):
        """Load from a JSONL event log — the whole rotated family,
        oldest first, so one request's spans reassemble across
        rotation boundaries (loaded by file to stay importable in the
        jax-free orchestrator)."""
        from pystella_tpu.obs import events as _events
        return cls(_events.read_events(path, include_rotated=True))

    # -- assembly ------------------------------------------------------------

    def _span_events(self, span, kind=None):
        """Events belonging to a span: ``span`` field matches, or the
        event opened a child span under it (``parent`` matches) — the
        recovery incidents open child spans, and their costs must stay
        attributable to the lease. Index lookups only: assembly over a
        long-lived service's rotated family must stay linear in the
        record count."""
        out = list(self._by_span.get(str(span), []))
        out += self._by_parent.get(str(span), [])
        if kind is not None:
            out = [ev for ev in out if ev.get("kind") == kind]
        return sorted(out, key=lambda ev: _num(ev.get("ts")))

    def assemble(self):
        """``{trace_id: RequestTrace}`` for every trace id the stream
        carries (memoized)."""
        if self._trees is not None:
            return self._trees
        self._trees = {t: self._assemble_one(t, evs)
                       for t, evs in sorted(self._by_trace.items())}
        return self._trees

    def _assemble_one(self, trace, events):
        tree = RequestTrace(trace)
        events = sorted(events, key=lambda ev: _num(ev.get("ts")))
        submit = next((ev for ev in events
                       if ev.get("kind") == "service_request"), None)
        admit = next((ev for ev in events
                      if ev.get("kind") == "service_admit"), None)
        terminal = [ev for ev in events
                    if ev.get("kind") in _TERMINAL_KINDS]
        dispatches = [ev for ev in events
                      if ev.get("kind") == "service_dispatch"]
        requeues = [ev for ev in events
                    if ev.get("kind") == "service_requeue"]
        if submit is None:
            tree.problems.append("no service_request event in the "
                                 "ingested window")
            return tree
        sdata = _data(submit)
        tree.request_id = sdata.get("id")
        tree.tenant = sdata.get("tenant")
        tree.priority = sdata.get("priority")
        tree.signature = sdata.get("signature")
        tree.submit_ts = _num(submit.get("ts"))
        root = submit.get("span") or f"root:{trace}"
        if not terminal:
            tree.problems.append(
                "no terminal event (member_result / service_reject) — "
                "request still in flight, or its retire rotated away")
            return tree
        last = terminal[-1]
        tree.retire_ts = _num(last.get("ts"))
        tree.status = (_data(last).get("status")
                       if last.get("kind") == "member_result"
                       else "rejected")
        tree.wall_s = max(0.0, tree.retire_ts - tree.submit_ts)
        tree.spans.append({"name": "service_request_span", "span": root,
                           "parent": None, "t0": tree.submit_ts,
                           "dur_s": tree.wall_s})
        phases = {p: 0.0 for p in PHASES}

        admit_ts = _num(admit.get("ts")) if admit else tree.submit_ts
        admit_ts = min(max(admit_ts, tree.submit_ts), tree.retire_ts)
        phases["service_admission"] = admit_ts - tree.submit_ts
        if phases["service_admission"] > 0:
            tree.spans.append({
                "name": "service_admission", "span": f"{root}.admit",
                "parent": root, "t0": tree.submit_ts,
                "dur_s": phases["service_admission"]})

        if tree.status == "rejected" or not dispatches:
            # a rejected (or never-dispatched) request: the whole wall
            # is ingestion — fold any residual into admission so the
            # partition property holds for every assembled tree
            phases["service_admission"] = tree.wall_s
            tree.phases = phases
            tree.assembled = True
            return tree

        # one segment per lease leg: [seg_start -> dispatch -> seg_end]
        # where seg_start is the submit (first leg) or the requeue that
        # returned the request to the queue, and seg_end is the next
        # requeue or the retire
        seg_starts = [admit_ts] + [_num(rq.get("ts")) for rq in requeues]
        seg_ends = [_num(rq.get("ts")) for rq in requeues] \
            + [tree.retire_ts]
        for i, disp in enumerate(dispatches):
            dts = _num(disp.get("ts"))
            start = seg_starts[i] if i < len(seg_starts) else dts
            end = seg_ends[i] if i < len(seg_ends) else tree.retire_ts
            end = max(end, dts)
            lease_span = disp.get("span")
            lease_rec = None
            if lease_span is not None:
                tree.leases.append(lease_span)
                recs = self._span_events(lease_span, "service_lease")
                lease_rec = _data(recs[-1]) if recs else None
            # a cold lease's build+compile ran between the queue pop
            # and the dispatch stamp: split it out of the wait
            cold_s = _num((lease_rec or {}).get("cold_build_s"))
            cold_s = min(cold_s, max(0.0, dts - start))
            wait_s = max(0.0, dts - start - cold_s)
            phases["service_queue_wait"] += wait_s
            phases["service_compile"] += cold_s
            if wait_s > 0:
                tree.spans.append({
                    "name": "service_queue_wait",
                    "span": f"{root}.q{i}", "parent": root,
                    "t0": start, "dur_s": wait_s})
            if cold_s > 0:
                tree.spans.append({
                    "name": "service_compile",
                    "span": f"{root}.c{i}", "parent": root,
                    "t0": dts - cold_s, "dur_s": cold_s})
            seg_s = max(0.0, end - dts)
            inner = 0.0
            if lease_span is not None and seg_s > 0:
                tree.spans.append({
                    "name": "service_lease_span", "span": lease_span,
                    "parent": root, "t0": dts, "dur_s": seg_s})
                inner = self._lease_inner(tree, phases, lease_span,
                                          dts, end, seg_s)
            compute_s = max(0.0, seg_s - inner)
            phases["service_chunk_compute"] += compute_s
            if compute_s > 0:
                # the exported span carries the RESIDUAL duration, so
                # folding the Perfetto file through scope_durations
                # agrees with the phase decomposition instead of
                # double-counting the barrier/recovery/drain children
                tree.spans.append({
                    "name": "service_chunk_compute",
                    "span": f"{lease_span or root}.compute{i}",
                    "parent": lease_span or root,
                    "t0": dts, "dur_s": compute_s})
        tree.phases = phases
        tree.assembled = True
        self._deadline(tree, sdata, terminal[-1])
        return tree

    def _lease_inner(self, tree, phases, lease_span, t0, t1, seg_s):
        """Attribute the measurable inner costs of one lease leg
        (barriers, recoveries, the drain) to their phases + spans;
        returns their sum, capped at the segment so the compute
        residual stays a partition."""
        inner = 0.0
        rows = (
            ("checkpoint_durable", "wait_s",
             "service_checkpoint_barrier"),
            ("run_resumed", "mttr_s", "service_recovery_replay"),
            ("run_preempted", "drain_s", "service_preempt_drain"),
        )
        for kind, field, phase in rows:
            for ev in self._span_events(lease_span, kind):
                ts = _num(ev.get("ts"))
                if not (t0 - 1e-6 <= ts <= t1 + 1e-6):
                    continue
                if kind == "run_resumed" and not _data(ev).get(
                        "incident"):
                    continue  # restart-resumes are not recovery cost
                dur = _num(_data(ev).get(field))
                dur = min(dur, max(0.0, seg_s - inner))
                if dur <= 0:
                    continue
                phases[phase] += dur
                inner += dur
                tree.spans.append({
                    "name": phase, "span": ev.get("span") or lease_span,
                    "parent": lease_span, "t0": ts - dur, "dur_s": dur})
        return inner

    def _deadline(self, tree, sdata, last):
        ldata = _data(last)
        deadline_ts = ldata.get("deadline_ts")
        if deadline_ts is None and isinstance(
                sdata.get("deadline_s"), (int, float)):
            deadline_ts = tree.submit_ts + float(sdata["deadline_s"])
        if deadline_ts is None:
            return
        tree.deadline_ts = float(deadline_ts)
        margin = ldata.get("margin_s")
        tree.margin_s = (float(margin)
                         if isinstance(margin, (int, float))
                         else tree.deadline_ts - tree.retire_ts)
        missed = ldata.get("deadline_missed")
        tree.deadline_missed = (bool(missed) if missed is not None
                                else tree.margin_s < 0.0)

    # -- reports -------------------------------------------------------------

    def summary(self, max_requests=64, tolerance=0.05):
        """The ``latency`` report-section payload: per-phase
        percentiles over assembled requests, the dominant-phase
        histogram, the deadline ledger per priority class, the
        partition audit, and the coverage split (``unassembled`` names
        the traces whose tree failed to close — the gate's
        coverage-loss warning keys on it). ``None`` when the stream
        carries no traced request at all."""
        trees = self.assemble()
        if not trees:
            return None
        ok = [t for t in trees.values() if t.assembled]
        bad = [t for t in trees.values() if not t.assembled]
        phase_samples = {p: [] for p in PHASES}
        dominant = {}
        walls, errs = [], []
        deadlined, missed, margins = [], [], []
        by_cls = {}
        for t in ok:
            walls.append(t.wall_s)
            for p in PHASES:
                if t.phases.get(p, 0.0) > 0:
                    phase_samples[p].append(t.phases[p])
            dom = t.dominant_phase
            if dom:
                dominant[dom] = dominant.get(dom, 0) + 1
            err = t.phase_sum_rel_err()
            if err is not None:
                errs.append(err)
            if t.deadline_missed is not None:
                deadlined.append(t)
                margins.append(t.margin_s)
                cls = str(t.priority)
                row = by_cls.setdefault(cls, {"deadlined": 0,
                                              "missed": 0})
                row["deadlined"] += 1
                if t.deadline_missed:
                    missed.append(t)
                    row["missed"] += 1
        for row in by_cls.values():
            row["miss_rate"] = row["missed"] / row["deadlined"]
        return {
            "traced": len(trees),
            "assembled": len(ok),
            "unassembled": [
                {"trace": t.trace, "id": t.request_id,
                 "problems": t.problems} for t in bad[:16]],
            "unassembled_total": len(bad),
            "wall_s": _stats(walls),
            "phases_s": {p: _stats(v)
                         for p, v in phase_samples.items() if v},
            "dominant_phase": dict(sorted(dominant.items())),
            "requests": [t.as_row() for t in
                         sorted(ok, key=lambda t: t.submit_ts or 0.0)
                         [:max_requests]],
            "phase_sum_check": {
                "max_rel_err": max(errs) if errs else None,
                "tolerance": tolerance,
                "ok": (max(errs) <= tolerance) if errs else None,
            },
            "deadline": {
                "deadlined": len(deadlined),
                "missed": len(missed),
                "miss_rate": (len(missed) / len(deadlined)
                              if deadlined else None),
                "by_priority": by_cls,
                "margin_s": _stats(margins),
            },
        }

    def export_perfetto(self, path):
        """Write the assembled service timeline as a Perfetto/Chrome
        ``traceEvents`` file: one complete-span (``ph="X"``) row per
        span, one timeline row (``tid``) per request, span names from
        the registered scope vocabulary — load it at ``ui.perfetto.dev``
        next to a hardware capture, or fold it through
        :func:`pystella_tpu.obs.trace.scope_durations` like any other
        trace. Returns the path (``None`` when nothing assembled)."""
        trees = [t for t in self.assemble().values() if t.assembled]
        if not trees:
            return None
        t_origin = min(t.submit_ts for t in trees)
        events = []
        for tid, tree in enumerate(
                sorted(trees, key=lambda t: t.submit_ts), start=1):
            events.append({
                "ph": "M", "pid": 1, "tid": tid, "name": "thread_name",
                "args": {"name": f"request {tree.request_id} "
                                 f"({tree.tenant}, p{tree.priority})"}})
            for span in tree.spans:
                events.append({
                    "ph": "X", "pid": 1, "tid": tid, "cat": "service",
                    "name": span["name"],
                    "ts": (span["t0"] - t_origin) * 1e6,
                    "dur": max(span["dur_s"], 0.0) * 1e6,
                    "args": {"trace": tree.trace,
                             "request": tree.request_id,
                             "span": span["span"],
                             "parent": span["parent"]}})
        out = {"traceEvents": events, "displayTimeUnit": "ms"}
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(out, f)
        return path


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m pystella_tpu.obs.spans",
        description="assemble request-scoped span trees from a run-"
                    "event log (rotated families included) and report "
                    "critical-path latency / export a Perfetto "
                    "timeline")
    p.add_argument("--events", required=True,
                   help="run-event JSONL path (the rotated family is "
                        "read automatically)")
    p.add_argument("--perfetto", default=None,
                   help="write the assembled service timeline here "
                        "(default: the registered PYSTELLA_TRACE_EXPORT "
                        "when set)")
    p.add_argument("--trace", default=None,
                   help="print one trace's span tree instead of the "
                        "summary")
    args = p.parse_args(argv)

    asm = SpanAssembler.from_events(args.events)
    if args.trace:
        tree = asm.assemble().get(args.trace)
        if tree is None:
            print(f"spans: no trace {args.trace!r} in {args.events}",
                  file=sys.stderr)
            return 1
        print(json.dumps({"trace": tree.trace, "row": tree.as_row(),
                          "spans": tree.spans,
                          "problems": tree.problems},
                         indent=1, sort_keys=True))
        return 0
    summary = asm.summary()
    if summary is None:
        print(f"spans: no traced requests in {args.events}",
              file=sys.stderr)
        return 1
    perfetto = args.perfetto
    if perfetto is None:
        from pystella_tpu import config as _config
        perfetto = _config.getenv("PYSTELLA_TRACE_EXPORT")
    if perfetto:
        out = asm.export_perfetto(perfetto)
        summary["perfetto"] = out
    print(json.dumps(summary, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
