"""Noise-aware perf regression gate over two ``perf_report.json`` files.

::

    python -m pystella_tpu.obs.gate --baseline old.json --current new.json

Exit codes (CI and the armed-hardware-revalidation scripts key on them):

====  ====================================================================
0     pass (no regression beyond noise, evidence valid)
1     regression: current median step time exceeds baseline by more than
      the threshold AND more than ``mad_k`` robust sigmas of noise — or
      a NUMERICS regression: a sentinel invariant's drift slope exceeds
      ``drift_factor`` x the baseline's (constraint drift worse than
      baseline fails CI the same way a slow step does) — or a
      COLD-START regression: time-to-first-step exceeds the baseline's
      by both ``cold_start_factor`` and ``cold_start_floor`` seconds —
      or an ENSEMBLE regression: batched member throughput
      (member-steps/s) drops more than ``ensemble_threshold_pct`` below
      the baseline's — or a SPECTRAL regression: the ``fft`` section's
      spectra p50 ms/call exceeds the baseline's by more than
      ``fft_threshold_pct`` — or a SERVICE SLO regression: the
      ``service`` section's queue-latency p95 (or warm-lease
      time-to-first-step p50) exceeds the baseline's by both the
      configured factor and floor — or a DEADLINE-MISS SLO regression:
      the ``latency`` section's deadline-miss rate exceeds the
      baseline's by both ``latency_miss_factor`` and
      ``latency_miss_floor`` (``--no-latency`` opts out; traced
      requests whose span tree fails to assemble degrade to a
      coverage-loss warning) — or a FLEET SLO regression: the
      ``fleet`` section's aggregated queue-p95 or warm-TTFS exceeds
      the baseline's by both the configured factor and floor
      (``--no-fleet`` opts out) — or a COMM EXCESS: a ``comm`` leg's
      measured collective traffic exceeds the dataflow lint tier's
      static model by more than ``comm_excess_pct`` (the model is an
      upper bound on what the program's collectives can move per
      invocation; measured above it means traffic the model does not
      attribute — ``--no-comm`` opts out)
2     invalid evidence: the contamination detector flagged the run
      (outlier burst / bimodal step times — the round-5 concurrent-probe
      signature), the report has no step samples, the run DIVERGED (a
      sentinel trip in the ``numerics`` section — broken step times
      prove nothing), the report CLAIMS warm start over AOT artifacts
      whose fingerprints mismatch the live compiler stack, the
      ``service`` section claims warm ADMISSIONS over mismatched
      fingerprints (the leases did not dispatch the programs the
      admission contract names), the report claims fewer incidents
      than its ``resilience`` event record carries (a clean headline
      over a degraded fleet), the report's ``alerts`` section carries a
      live burn alert UNRESOLVED at exit while the matching post-hoc
      SLO section claims green (the live and post-hoc halves
      contradict; ``--no-alerts`` opts out, alert-FLAP growth merely
      warns), the report's ``perf`` section carries a ``perf_anomaly``
      UNRESOLVED at exit while the post-hoc step-time verdict claims
      green (same contradiction for the continuous-performance plane;
      ``--no-perf`` opts out), the report's ``fleet`` section claims COMPLETE fleet
      coverage while its own scrape record shows lost replicas or
      failed scrapes (fleet aggregates over the survivors are partial
      evidence; an HONESTLY-partial fleet record is annotated
      degraded instead), the report's ``comm`` section claims
      modeled-vs-measured coverage (``covered: true``) while no leg
      actually carries a static model (a coverage claim with nothing
      behind it — the dataflow lint tier never ran, or the section
      was assembled by hand), or baseline and current were measured on
      different hardware. Exception: a
      run that recorded AND recovered REAL (non-harness-injected)
      incidents (``resilience`` section,
      :mod:`pystella_tpu.resilience`) keeps its evidence —
      regressions and contamination-like bursts measured across the
      recovery stalls are ANNOTATED as degraded (warnings +
      ``verdict["degraded"]``) rather than failed or refused; a
      harness DRILL (``faults_injected`` covers the incident count)
      annotates without softening any verdict
3     missing or unreadable baseline (suppress with
      ``--allow-missing-baseline``, e.g. on a branch's first run)
4     unreadable current report / bad usage
====  ====================================================================

The comparison is ``median +- k*MAD``, not single wall-clock numbers: a
regression must clear both a relative threshold (``--threshold-pct``,
default 10%) and a noise bar (``--mad-k`` Gaussian-consistent sigmas,
default 3) before the gate fails, so ordinary scheduler jitter cannot
flip CI, and a real 20% step-time regression reliably does.

The contamination detector automates what round 5 did by hand (a fresh
hardware run was invalidated because a concurrent probe stole the chip
mid-measurement): a burst of consecutive outlier steps, an excessive
outlier fraction, or a bimodal step-time distribution marks the run
``invalid_evidence`` — *neither pass nor fail*, because a contaminated
measurement can prove nothing in either direction.

The module body is stdlib-only on purpose (report comparison must not
require a working accelerator stack), but the ``python -m`` entry point
imports the ``pystella_tpu`` package — and therefore jax — like any
in-repo CI environment has. A truly jax-free supervisor should call
:func:`compare_reports` from a by-file module load (the trick
``bench.py`` uses for ``obs/events.py``), loading ``ledger.py`` the
same way first.
"""

from __future__ import annotations

import argparse
import json
import sys

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs.ledger import mad as _mad
from pystella_tpu.obs.ledger import percentile as _percentile

__all__ = ["detect_contamination", "compare_reports", "load_report",
           "main"]

#: MAD -> Gaussian-consistent sigma
MAD_SIGMA = 1.4826


def load_report(path):
    """Parse one ``perf_report.json``; raises ``OSError``/``ValueError``
    on unreadable input (callers map these to exit codes)."""
    with open(path) as f:
        rep = json.load(f)
    if not isinstance(rep, dict) or "steps" not in rep:
        raise ValueError(f"{path}: not a perf report (no 'steps' key)")
    return rep


def detect_contamination(samples_ms, outlier_k=5.0, rel_floor=0.25,
                         burst_limit=4, frac_limit=0.10,
                         check_bimodal=True):
    """Flag step-time samples that look contaminated by concurrent load.

    An *outlier* is a step slower than
    ``median + max(outlier_k * 1.4826 * MAD, rel_floor * median)`` (the
    relative floor keeps a quantized, near-zero-MAD distribution from
    flagging ordinary jitter). The run is contaminated when

    - outliers form a consecutive burst of ``burst_limit`` or more (a
      probe holding the device for a stretch — the round-5 signature),
    - outliers exceed ``frac_limit`` of all samples, or
    - with ``check_bimodal``, the distribution is bimodal: a 2-means
      split finds two clusters, each holding >= 20% of samples,
      separated by far more than the within-cluster spread (device
      timesharing alternating fast/slow).

    :func:`compare_reports` arms this detector for ACCELERATOR reports
    (``check_contamination="auto"``): OS scheduling on a shared CPU
    host legitimately stalls and multi-modalizes millisecond step times
    (measured on the smoke bench), and the median-based comparison
    absorbs that by construction, while an accelerator's step times are
    tight unless someone else holds the chip.

    Returns a dict: ``contaminated`` (bool), ``reasons`` (list of
    strings), plus the measured diagnostics.
    """
    out = {"contaminated": False, "reasons": [], "n_samples":
           len(samples_ms), "outlier_fraction": 0.0, "max_burst": 0,
           "threshold_ms": None}
    if len(samples_ms) < 8:
        # too few samples to characterize noise; detection is a no-op
        # (the gate separately rejects EMPTY reports as invalid)
        return out
    s = sorted(samples_ms)
    med = _percentile(s, 50)
    sigma = MAD_SIGMA * (_mad(s) or 0.0)
    thresh = med + max(outlier_k * sigma, rel_floor * med)
    out["threshold_ms"] = thresh

    flags = [x > thresh for x in samples_ms]
    nout = sum(flags)
    out["outlier_fraction"] = nout / len(flags)
    burst = longest = 0
    for f in flags:
        burst = burst + 1 if f else 0
        longest = max(longest, burst)
    out["max_burst"] = longest

    if longest >= burst_limit:
        out["reasons"].append(
            f"outlier burst: {longest} consecutive steps above "
            f"{thresh:.3f} ms (limit {burst_limit})")
    if out["outlier_fraction"] > frac_limit:
        out["reasons"].append(
            f"outlier fraction {out['outlier_fraction']:.1%} above "
            f"{frac_limit:.0%}")

    if check_bimodal:
        lo_c, hi_c, lo_n, hi_n, gap, spread = _two_means(samples_ms)
        minority = min(lo_n, hi_n) / len(samples_ms)
        if (minority >= 0.2
                and gap > max(6 * MAD_SIGMA * spread, rel_floor * med)):
            out["reasons"].append(
                f"bimodal step times: clusters at {lo_c:.3f} / "
                f"{hi_c:.3f} ms ({lo_n}/{hi_n} samples)")
    out["contaminated"] = bool(out["reasons"])
    return out


def _two_means(xs, iters=16):
    """1-D 2-means: ``(lo_center, hi_center, lo_n, hi_n, gap,
    within_cluster_mad)``."""
    s = sorted(xs)
    lo, hi = float(s[0]), float(s[-1])
    if lo == hi:
        return lo, hi, len(s), 0, 0.0, 0.0
    for _ in range(iters):
        cut = (lo + hi) / 2
        a = [x for x in s if x <= cut]
        b = [x for x in s if x > cut]
        if not a or not b:
            break
        nlo, nhi = sum(a) / len(a), sum(b) / len(b)
        if (nlo, nhi) == (lo, hi):
            break
        lo, hi = nlo, nhi
    a = [x for x in s if x <= (lo + hi) / 2]
    b = [x for x in s if x > (lo + hi) / 2]
    devs = [abs(x - lo) for x in a] + [abs(x - hi) for x in b]
    return lo, hi, len(a), len(b), hi - lo, (_mad(devs) or 0.0)


def _env_comparable(base_env, cur_env):
    """Hardware identity check: a baseline measured on different silicon
    proves nothing about the current run (the round-5 failure mode was
    exactly a CPU-fallback number standing in for a TPU claim)."""
    mismatches = []
    for key in ("platform", "device_kind"):
        b, c = base_env.get(key), cur_env.get(key)
        if b is not None and c is not None and b != c:
            mismatches.append(f"{key}: baseline {b!r} vs current {c!r}")
    return mismatches


def compare_reports(baseline, current, threshold_pct=10.0, mad_k=3.0,
                    outlier_k=5.0, burst_limit=4, frac_limit=0.10,
                    allow_env_mismatch=False,
                    check_contamination="auto", check_numerics=True,
                    drift_factor=10.0, drift_floor=1e-12,
                    check_lint=True, check_cold_start=True,
                    cold_start_factor=1.5, cold_start_floor=5.0,
                    check_ensemble=True, ensemble_threshold_pct=20.0,
                    check_resilience=True,
                    check_fft=True, fft_threshold_pct=25.0,
                    check_comm=True, comm_excess_pct=25.0,
                    check_service=True, service_queue_factor=2.5,
                    service_queue_floor_s=0.5,
                    service_ttfs_factor=2.5,
                    service_ttfs_floor_s=1.0,
                    check_latency=True, latency_miss_factor=2.0,
                    latency_miss_floor=0.05, check_alerts=True,
                    check_fleet=True, fleet_queue_factor=2.5,
                    fleet_queue_floor_s=0.5, fleet_ttfs_factor=2.5,
                    fleet_ttfs_floor_s=1.0, check_perf=True,
                    check_capacity=True, goodput_factor=2.0,
                    goodput_floor=1.0, reconciliation_warn_pct=25.0):
    """Pure comparison core (the CLI is a thin wrapper; tests drive
    this). Returns a verdict dict with ``exit_code``.

    ``check_contamination``: ``"auto"`` (default) arms the detector for
    accelerator reports only — on a CPU host the OS scheduler
    legitimately stalls a tail of steps (measured: 12% of smoke steps
    15x slower under this container's scheduler), which the
    MEDIAN-based comparison absorbs by construction, while on a TPU the
    step times are tight unless someone else holds the chip (the
    round-5 scenario the detector exists for). ``"always"`` /
    ``"never"`` force it either way.

    ``check_lint`` (default on): a run whose ``lint`` section records a
    FAILED static analysis (:mod:`pystella_tpu.lint` — donation misses,
    unexpected collectives, host syncs on the step path, ...) is
    invalid evidence (exit 2): its step times measure a program known
    to be off the fast path, so they prove nothing about the code as
    designed. A baseline with lint coverage that the current run lost
    degrades to a warning.

    ``check_cold_start`` (default on): a report whose ``cold_start``
    section *claims* warm start while any loaded artifact's fingerprint
    mismatches is invalid evidence (exit 2 — the run did not execute
    the programs it says it did), and a time-to-first-step more than
    ``cold_start_factor`` x the baseline's AND ``cold_start_floor``
    seconds above it fails the gate like a step-time regression (exit
    1) — cold-start time IS a production metric.

    ``check_numerics`` (default on) extends the gate beyond step times:
    a run whose ``numerics`` section records a sentinel trip is invalid
    evidence (exit 2 — diverged step times prove nothing), and a
    physics-invariant **drift slope** more than ``drift_factor`` times
    the baseline's (each floored at ``drift_floor``/step so a ~zero
    baseline slope cannot make any finite drift a regression) fails the
    gate exactly like a perf regression (exit 1) — a silent numerics
    regression fails CI the same way a slow step does.

    ``check_ensemble`` (default on): when both reports carry an
    ``ensemble`` section (:mod:`pystella_tpu.ensemble` batch totals), a
    **member-throughput** drop of more than ``ensemble_threshold_pct``
    vs the baseline's member-steps/s fails the gate (exit 1) — batched
    population throughput is a first-class production metric, gated
    like single-run step time. Lost ensemble coverage (baseline has the
    section, current does not) degrades to a warning, and an eviction
    count exceeding the baseline's warns too (evictions are legitimate
    per-draw physics, but a jump usually means a broken sampler).

    ``check_resilience`` (default on): the degraded-fleet triage for
    reports carrying a ``resilience`` section
    (:mod:`pystella_tpu.resilience`). A run that **recorded and
    recovered incidents** (device loss, numerics trips) and still
    completed is *degraded, not broken*: its step-time regression and
    contamination-like sample bursts are measured ACROSS the recovery
    stalls, so the gate **annotates** them (warning +
    ``verdict["degraded"]``) instead of failing or refusing — slow
    because the fleet was on fire is a different verdict from slow.
    Only REAL incidents earn that softening: a harness-injected drill
    (``faults_injected`` covers the incident count, e.g. the smoke
    pipeline's scripted device loss) still marks the verdict degraded
    but leaves the regression/contamination verdicts fully armed —
    otherwise the ever-present smoke drill would permanently disarm
    the CI gate.
    The refusal cuts the other way: a report whose supervisor CLAIMS
    fewer incidents than its event log records
    (``resilience.consistent`` false) is hiding a degraded fleet
    behind a clean headline — invalid evidence, exit 2. Lost
    resilience coverage warns, and unresolved incidents (detected but
    never resumed) warn too. Degraded-MODE accounting (the re-mesh
    library, :mod:`pystella_tpu.resilience.remesh`): a report whose
    ``resilience.degraded`` block records a re-mesh but whose
    ``throughput.per_chip`` still normalizes by the full pre-loss
    mesh is claiming full-mesh throughput from a degraded run —
    invalid evidence, exit 2 (the honest figure divides by the
    survivors; the ledger produces it automatically from the
    ``remesh_plan`` record) — and a run that finished degraded
    without any ``remesh_plan`` record warns (unauditable).

    ``check_fleet`` (default on): the federation half of the same
    honesty rule, for reports carrying a ``fleet`` section
    (:mod:`pystella_tpu.obs.fleet`). A report whose fleet coverage
    block claims ``complete`` while its own scrape record shows lost
    replicas or failed scrapes is refused (exit 2) — fleet aggregates
    over the survivors are partial evidence. The honest version of the
    same record (coverage says partial) is annotated
    (``verdict["degraded"]`` + warning), never silently accepted.
    Against a baseline, fleet queue-p95 and fleet warm-TTFS regress
    under the same factor+floor bars as the single-replica service
    legs (exit 1); version/flag skew appearing, warm-fingerprint
    divergence, and fleet-alert flap growth warn. ``--no-fleet`` opts
    out.

    ``check_perf`` (default on): the continuous-performance half of
    the alert-evidence rule, for reports carrying a ``perf`` section
    (:mod:`pystella_tpu.obs.perf`). A ``perf_anomaly`` still
    unresolved when the run record ended — the change-point detector
    watched a sustained step-time shift never recover — beside a GREEN
    post-hoc step-time verdict is the same live/post-hoc contradiction
    as an unresolved burn alert: invalid evidence, exit 2
    (``--no-perf`` opts out). An unresolved anomaly whose post-hoc
    step verdict also failed is corroboration (warning). Anomalies
    that fired with NO flight-recorder capture recorded warn (the
    profiling evidence the plane exists to capture is missing —
    usually ``PYSTELLA_PERF_CAPTURE_DIR`` unset); anomaly-flap growth
    and lost perf coverage warn like the other sections.

    ``check_capacity`` (default on): the capacity-and-goodput half of
    the evidence rule, for reports carrying a ``capacity`` section
    (:mod:`pystella_tpu.obs.capacity`). A report whose capacity
    coverage block claims ``complete`` watermark coverage while
    recording ZERO live watermark samples is refused (exit 2) — a
    full-coverage reconciliation claim with no device readings behind
    it proves nothing. The honest version (coverage says
    ``predicted_only``, the CPU degrade) is annotated
    (``verdict["degraded"]`` + warning), never silently accepted, and
    a predicted-vs-measured reconciliation error beyond
    ``reconciliation_warn_pct`` warns (the footprint model is
    drifting from the device). Against a baseline, **goodput**
    (committed member-steps per chip-second) regresses DOWNWARD: the
    gate fails (exit 1) when current goodput drops below baseline /
    ``goodput_factor`` AND by more than ``goodput_floor``
    steps/chip-s absolute — the factor+floor shape of every other SLO
    leg, with the inequality flipped because higher is better. Waste
    chip-second growth (replay + preempt-drain share) and lost
    capacity coverage warn. ``--no-capacity`` opts out.
    """
    verdict = {"ok": True, "exit_code": 0, "reasons": [],
               "warnings": []}

    cres = current.get("resilience") or {}
    n_incidents = int(cres.get("n_incidents") or 0)
    injected = int(cres.get("faults_injected") or 0)
    if check_resilience and cres and cres.get("consistent") is False:
        verdict.update(ok=False, exit_code=2)
        verdict["reasons"].append(
            "invalid_evidence: run claims "
            f"{cres.get('claimed_incidents')} incident(s) but its "
            f"event record carries {n_incidents} — a clean headline "
            "over a degraded fleet proves nothing; trust the event "
            "log, not the claim")
        return verdict
    # degraded-mode accounting (the re-mesh library,
    # resilience.remesh): a run that finished on a DEGRADED mesh must
    # say so auditable. A recorded remesh whose throughput section
    # still normalizes per pre-loss chip is claiming full-mesh
    # throughput from a degraded run — invalid evidence; a run that
    # degraded (run_degraded) without any remesh_plan record cannot be
    # audited at all and warns.
    deg = cres.get("degraded")
    if check_resilience and isinstance(deg, dict):
        if deg.get("new_mesh"):
            used = deg.get("devices_used")
            rate = (current.get("throughput") or {}).get(
                "site_updates_per_s")
            pc = (current.get("throughput") or {}).get("per_chip")
            if used and rate and (not pc
                                  or pc.get("basis") != "surviving"
                                  or pc.get("chips") != used):
                verdict.update(ok=False, exit_code=2)
                verdict["reasons"].append(
                    "invalid_evidence: run re-meshed to "
                    f"{deg.get('new_mesh')} ({used} surviving "
                    "device(s)) but its throughput claims a "
                    "full-mesh per-chip normalization — a degraded "
                    "run's per-chip figure divides by the SURVIVORS")
                return verdict
        elif deg.get("events") and not deg.get("remesh_plans"):
            verdict["warnings"].append(
                "resilience: the run finished degraded (run_degraded "
                "recorded) without a matching remesh_plan record — "
                "the degraded mesh cannot be audited; use the "
                "RemeshPlanner (or emit remesh_plan from the hook)")
    elif check_resilience and deg:
        # pre-remesh-library reports: a bare run_degraded event list
        verdict["warnings"].append(
            "resilience: the run finished degraded (run_degraded "
            "recorded) without a matching remesh_plan record — "
            "the degraded mesh cannot be audited; use the "
            "RemeshPlanner (or emit remesh_plan from the hook)")
    # ANY recorded incident marks the evidence degraded (annotated) —
    # but only REAL (non-injected) incidents soften the verdicts
    # below. A harness DRILL (faults_injected covers the incident
    # count — e.g. the smoke pipeline's scripted device loss, which
    # runs outside the timed step window) proves the recovery
    # machinery without excusing anything: if every drill-carrying
    # report earned the shield, the regression gate would never fail
    # on smoke evidence again.
    if check_resilience and n_incidents > 0:
        verdict["degraded"] = True
        verdict["warnings"].append(
            f"resilience: {n_incidents} recorded incident(s)"
            + (f" ({min(injected, n_incidents)} harness-injected "
               "drill(s))" if injected else "")
            + " — evidence from a degraded fleet; see the report's "
            "resilience section")
    real_incidents = max(0, n_incidents - injected)
    degraded_evidence = bool(
        check_resilience and real_incidents > 0
        and cres.get("completed") is not False
        and not cres.get("unresolved"))
    if check_resilience and cres.get("unresolved"):
        verdict["warnings"].append(
            f"resilience: {cres['unresolved']} detected incident(s) "
            "never resumed — the run likely died mid-recovery; treat "
            "its samples with care")

    cur_samples = current.get("samples_ms") or []
    cur_steps = current.get("steps") or {}
    if not cur_steps.get("count"):
        verdict.update(ok=False, exit_code=2)
        verdict["reasons"].append(
            "invalid_evidence: current report has no step samples")
        return verdict

    if check_lint:
        cur_lint = current.get("lint")
        if cur_lint and not cur_lint.get("ok", True):
            verdict.update(ok=False, exit_code=2)
            verdict["reasons"].append(
                "invalid_evidence: the run's static analysis FAILED "
                f"({cur_lint.get('errors', '?')} lint error(s)) — the "
                "measured program is known to be off the fast path; "
                "fix the lint findings "
                + (f"({'; '.join(cur_lint['first_errors'][:3])}) "
                   if cur_lint.get("first_errors") else "")
                + "and re-measure")
            return verdict
        if (baseline is not None and baseline.get("lint")
                and not current.get("lint")):
            verdict["warnings"].append(
                "lint: baseline carried a static-analysis verdict but "
                "the current run has none — lint coverage was lost")

    if check_cold_start:
        ws = (current.get("cold_start") or {}).get("warmstart") or {}
        if ws.get("claimed"):
            bad = [a for a in ws.get("artifacts") or []
                   if a.get("match") is False
                   or a.get("bitexact") is False]
            if bad:
                # the report says it ran AOT-loaded programs whose
                # fingerprints do not match the live compiler stack —
                # or whose outputs diverged from the jit reference (the
                # cached-donated-executable failure mode): whatever it
                # measured, it was not the warm path it claims —
                # neither pass nor fail
                verdict.update(ok=False, exit_code=2)
                for a in bad:
                    if a.get("bitexact") is False:
                        verdict["reasons"].append(
                            "invalid_evidence: report claims warm "
                            "start but the loaded artifact computed "
                            "different results than the jit path: "
                            f"{a.get('label')!r} "
                            f"({a.get('fingerprint')})")
                    else:
                        verdict["reasons"].append(
                            "invalid_evidence: report claims warm "
                            "start but the loaded artifact's "
                            "fingerprint mismatches: "
                            f"{a.get('label')!r} "
                            f"({a.get('reason') or a.get('fingerprint')})")
                return verdict
        # refused-stale-artifact fallbacks are HONEST (the mismatched
        # program was never run warm — the driver took the cold jit
        # path by design), so they warn rather than refuse: the
        # operator likely wants to re-export
        for a in (ws.get("fallbacks") or [])[:3]:
            verdict["warnings"].append(
                "warmstart: stale artifact refused, cold fallback "
                f"taken: {a.get('label')!r} "
                f"({a.get('reason') or a.get('fingerprint')})")

    if check_service:
        csv = current.get("service") or {}
        if csv.get("warm_claimed"):
            bad = [a for a in csv.get("warm_admissions") or []
                   if a.get("fingerprint_ok") is False]
            if bad:
                # the report says requests were admitted WARM — served
                # from the ready pool, latency = dispatch — over
                # program fingerprints that do not match the live
                # compiler stack: whatever those leases dispatched, it
                # was not the programs the admission contract names;
                # neither pass nor fail
                verdict.update(ok=False, exit_code=2)
                for a in bad[:5]:
                    verdict["reasons"].append(
                        "invalid_evidence: report claims warm "
                        "admission over a mismatched fingerprint: "
                        f"request {a.get('id')} "
                        f"({a.get('fingerprint')})")
                return verdict
        if csv.get("warm_lease_backend_compiles"):
            # an honest-but-broken warm path: the fingerprints match
            # but the compile ledger recorded backend compiles inside
            # warm leases — the dispatch-never-compile contract
            # regressed; warn loudly (the TTFS comparison below is
            # what fails CI when it costs latency)
            verdict["warnings"].append(
                "service: "
                f"{csv['warm_lease_backend_compiles']} backend "
                "compile(s) recorded inside warm leases — the warm "
                "path is supposed to be pure dispatch; check the "
                "service section's lease records")

    if check_fleet:
        cfl = current.get("fleet") or {}
        cov = cfl.get("coverage") or {}
        lossy = bool((cfl.get("replicas_lost") or [])
                     or (cov.get("endpoint_failed") or 0) > 0)
        if cfl and cov.get("complete") and lossy:
            # the report CLAIMS its fleet numbers cover the whole
            # fleet while its own scrape record shows replicas lost or
            # scrapes failed: whatever the aggregated legs measured,
            # it was the survivors — a full-fleet throughput/SLO claim
            # over partial evidence proves nothing either way
            verdict.update(ok=False, exit_code=2)
            verdict["reasons"].append(
                "invalid_evidence: report claims complete fleet "
                "coverage but its scrape record shows "
                f"{len(cfl.get('replicas_lost') or [])} lost "
                f"replica(s) and {cov.get('endpoint_failed') or 0} "
                "failed scrape(s) — fleet aggregates over the "
                "survivors are partial evidence, not a fleet claim")
            return verdict
        if cfl and lossy:
            # the honest version of the same record: the report SAYS
            # its coverage is partial — degraded evidence, annotated
            # like a recovered incident, never silently accepted
            verdict["degraded"] = True
            lost_ids = sorted({str(r.get("replica"))
                               for r in cfl.get("replicas_lost") or []})
            verdict["warnings"].append(
                "fleet: degraded fleet evidence — "
                f"{len(lost_ids)} replica(s) lost mid-run "
                f"({', '.join(lost_ids) or '?'}), scrape success "
                f"{cfl.get('scrape_success_rate')} — fleet legs "
                "aggregate the survivors; see the report's fleet "
                "section before trusting fleet-wide claims")

    if check_capacity:
        ccap = current.get("capacity") or {}
        ccov = ccap.get("coverage") or {}
        n_samples = ccov.get("watermark_samples")
        if ccap and ccov.get("complete") and not n_samples:
            # the report CLAIMS its footprint reconciliation covered
            # every lease with live watermarks while recording zero
            # device samples: the "measured" side of the ledger never
            # existed, so the reconciliation (and any OOM headroom
            # claim built on it) proves nothing either way
            verdict.update(ok=False, exit_code=2)
            verdict["reasons"].append(
                "invalid_evidence: report claims complete capacity "
                "coverage but records 0 live watermark sample(s) — "
                "a predicted-vs-measured reconciliation with no "
                "device readings is not evidence of headroom")
            return verdict
        if ccap and ccov.get("predicted_only"):
            # the honest CPU degrade: no device.memory_stats() on
            # this host, so the ledger carries predictions only —
            # annotated, never silently accepted as measured headroom
            verdict["degraded"] = True
            verdict["warnings"].append(
                "capacity: predicted-only footprint evidence (no "
                "live watermark samples on this host) — HBM "
                "headroom claims rest on the aval/memory-analysis "
                "model, not device readings")
        rec = ccap.get("reconciliation") or {}
        rel = rec.get("rel_err")
        if isinstance(rel, (int, float)) \
                and abs(rel) > reconciliation_warn_pct / 100.0:
            verdict["warnings"].append(
                "capacity: predicted footprints disagree with the "
                f"measured HBM peak by {abs(rel):.0%} (warn bar "
                f"{reconciliation_warn_pct:g}%) — the footprint "
                "model is drifting from the device; re-arm with "
                "fresh compile records before trusting admission "
                "decisions")

    if check_latency:
        clat = current.get("latency") or {}
        bad_asm = clat.get("unassembled") or []
        n_bad = clat.get("unassembled_total")
        if not isinstance(n_bad, int):
            n_bad = len(bad_asm)  # pre-truncation-marker reports
        if n_bad:
            # traced requests whose span tree failed to close: the
            # latency attribution silently lost coverage — warn (the
            # requests may legitimately still be in flight, so this is
            # evidence quality, not invalid evidence)
            verdict["warnings"].append(
                f"latency: {n_bad} traced request(s) failed to "
                "assemble a span tree — critical-path coverage was "
                "lost; see the report's latency.unassembled list")
        chk = clat.get("phase_sum_check") or {}
        if chk.get("ok") is False:
            err = chk.get("max_rel_err")
            tol = chk.get("tolerance")
            detail = (
                f" (worst rel err {err:.2%} over tolerance {tol:.0%})"
                if isinstance(err, (int, float))
                and isinstance(tol, (int, float)) else "")
            verdict["warnings"].append(
                "latency: the critical-path phases do not sum to the "
                f"measured wall time{detail} — the span record is "
                "internally inconsistent; treat phase attribution "
                "with care")

    cur_num = current.get("numerics") or {}
    if check_numerics and cur_num.get("diverged"):
        # a diverged run's step times measure a broken computation;
        # neither pass nor fail — and the reason points at the bundle
        verdict.update(ok=False, exit_code=2)
        for d in cur_num["diverged"]:
            inv = d.get("offending_invariant")
            verdict["reasons"].append(
                "invalid_evidence: run diverged at step "
                f"{d.get('step')} (fields {d.get('fields')}"
                + (f", invariant {inv!r}" if inv else "") + ")")
        for b in cur_num.get("forensic_bundles") or []:
            verdict["reasons"].append(f"forensic bundle: {b}")
        return verdict

    run_detector = (check_contamination == "always"
                    or (check_contamination == "auto"
                        and (current.get("env") or {}).get(
                            "platform") not in (None, "cpu")))
    if run_detector:
        contamination = detect_contamination(
            cur_samples, outlier_k=outlier_k, burst_limit=burst_limit,
            frac_limit=frac_limit)
        verdict["contamination"] = contamination
        if contamination["contaminated"]:
            if degraded_evidence:
                # a recovery stall IS an outlier burst: across real
                # recorded incidents the detector's signature is
                # expected, so the evidence is degraded (annotated),
                # not refused
                verdict["degraded"] = True
                verdict["warnings"] += [
                    f"degraded fleet ({real_incidents} real recorded "
                    f"incident(s)): contamination-like samples "
                    f"annotated, not refused — {r}"
                    for r in contamination["reasons"]]
            else:
                verdict.update(ok=False, exit_code=2)
                verdict["reasons"] += ["invalid_evidence: " + r
                                       for r in contamination["reasons"]]
                return verdict

    # autotune coverage (ops.autotune): a HARDWARE report whose fused
    # kernels dispatched with no autotune-table hit ran the heuristic
    # blockings — legal, but it means the window either never swept or
    # refused every (stale) entry, and its numbers under-claim what the
    # tuned kernels would do. The lost-coverage pattern: warn, never
    # fail (a CPU/smoke run legitimately has no table).
    kt = ((current.get("roofline") or {}).get("kernel_tiers")) or {}
    if kt:
        fused_rows = [r for r in kt.get("dispatched") or []
                      if r.get("tier") not in (None, "xla")]
        at = kt.get("autotune") or {}
        if ((current.get("env") or {}).get("platform") == "tpu"
                and fused_rows and not at.get("hits")):
            verdict["warnings"].append(
                "autotune-coverage: TPU report dispatched fused "
                "kernels with zero autotune-table hits"
                + (f" ({at.get('mismatches_refused')} stale entr(ies) "
                   "refused)" if at.get("mismatches_refused") else "")
                + " — heuristic blockings measured; sweep this device "
                "kind (python -m pystella_tpu.ops.autotune sweep) so "
                "hardware claims come from tuned kernels")
        elif at.get("mismatches_refused"):
            verdict["warnings"].append(
                f"autotune: {at['mismatches_refused']} stale table "
                "entr(ies) refused this run (version/flag mismatch) — "
                "re-sweep or `python -m pystella_tpu.ops.autotune gc`")

    if baseline is None:
        verdict["warnings"].append("no baseline: contamination check "
                                   "only, no regression comparison")
        return verdict

    env_mismatch = _env_comparable(baseline.get("env") or {},
                                   current.get("env") or {})
    if env_mismatch:
        if allow_env_mismatch:
            verdict["warnings"] += ["env mismatch (allowed): " + m
                                    for m in env_mismatch]
        else:
            verdict.update(ok=False, exit_code=2)
            verdict["reasons"] += [
                "invalid_evidence: measured on different hardware — "
                + m for m in env_mismatch]
            return verdict

    # same silicon but different XLA scheduler/async-collective flags
    # (or halo-overlap policy): the comparison still runs — the flags
    # change scheduling, not what is measured — but the verdict carries
    # a warning, because a latency-hiding-scheduler baseline is not a
    # like-for-like baseline for a run without it
    bflags = (baseline.get("env") or {}).get("xla_flags")
    cflags = (current.get("env") or {}).get("xla_flags")
    if bflags is not None and cflags is not None and bflags != cflags:
        diffs = sorted(k for k in set(bflags) | set(cflags)
                       if bflags.get(k) != cflags.get(k))
        verdict["warnings"].append(
            "XLA scheduler/overlap flags differ between baseline and "
            "current (comparison kept, but treat deltas with care): "
            + ", ".join(
                f"{k}: {bflags.get(k)!r} vs {cflags.get(k)!r}"
                for k in diffs))

    base_steps = baseline.get("steps") or {}
    base_p50 = base_steps.get("p50_ms")
    cur_p50 = cur_steps.get("p50_ms")
    if not isinstance(base_p50, (int, float)) or not isinstance(
            cur_p50, (int, float)):
        verdict.update(ok=False, exit_code=2)
        verdict["reasons"].append(
            "invalid_evidence: missing p50_ms in baseline or current")
        return verdict

    # the compared statistic is each run's MEDIAN, so the noise bar is
    # the standard error of a median (1.2533 * sigma / sqrt(n), sigma
    # from the Gaussian-consistent MAD), both runs combined in
    # quadrature — more steps legitimately tighten the bar
    def _median_se(steps):
        n = steps.get("count") or 1
        return 1.2533 * MAD_SIGMA * (steps.get("mad_ms") or 0.0) \
            / n ** 0.5

    noise_ms = mad_k * (_median_se(base_steps) ** 2
                        + _median_se(cur_steps) ** 2) ** 0.5
    delta = cur_p50 - base_p50
    rel = delta / base_p50 if base_p50 else 0.0
    verdict["comparison"] = {
        "baseline_p50_ms": base_p50, "current_p50_ms": cur_p50,
        "delta_ms": delta, "delta_pct": 100.0 * rel,
        "noise_bar_ms": noise_ms, "threshold_pct": threshold_pct,
    }
    if rel * 100.0 > threshold_pct and delta > noise_ms:
        if degraded_evidence:
            # a throughput drop measured across a REAL recorded
            # incident is the cost of the recovery, not (necessarily)
            # of the code: annotate so a human reads it next to the
            # incident table, instead of failing CI on a fleet that
            # was on fire. Drill-only runs do NOT take this branch.
            verdict["degraded"] = True
            verdict["warnings"].append(
                f"degraded fleet ({real_incidents} real recorded "
                "incident(s)): "
                f"median step time {cur_p50:.3f} ms is "
                f"{100 * rel:+.1f}% vs baseline {base_p50:.3f} ms — "
                "annotated, not gated; re-measure on a quiet fleet "
                "before trusting either direction")
        else:
            verdict.update(ok=False, exit_code=1)
            verdict["reasons"].append(
                f"regression: median step time {cur_p50:.3f} ms is "
                f"{100 * rel:+.1f}% vs baseline {base_p50:.3f} ms "
                f"(threshold {threshold_pct:.0f}%, noise bar "
                f"{noise_ms:.3f} ms)")
    elif rel * 100.0 < -threshold_pct and -delta > noise_ms:
        verdict["warnings"].append(
            f"improvement: median step time {100 * rel:+.1f}% vs "
            "baseline — consider refreshing the baseline")

    if check_numerics:
        _compare_numerics(verdict, baseline, current,
                          drift_factor=drift_factor,
                          drift_floor=drift_floor)
    if check_cold_start:
        _compare_cold_start(verdict, baseline, current,
                            factor=cold_start_factor,
                            floor_s=cold_start_floor)
    if check_ensemble:
        _compare_ensemble(verdict, baseline, current,
                          threshold_pct=ensemble_threshold_pct)
    if check_fft:
        _compare_fft(verdict, baseline, current,
                     threshold_pct=fft_threshold_pct)
    if check_comm:
        _check_comm(verdict, baseline, current,
                    excess_pct=comm_excess_pct)
    if check_service:
        _compare_service(verdict, baseline, current,
                         queue_factor=service_queue_factor,
                         queue_floor_s=service_queue_floor_s,
                         ttfs_factor=service_ttfs_factor,
                         ttfs_floor_s=service_ttfs_floor_s)
    if check_latency:
        _compare_latency(verdict, baseline, current,
                         miss_factor=latency_miss_factor,
                         miss_floor=latency_miss_floor)
    if check_fleet:
        _compare_fleet(verdict, baseline, current,
                       queue_factor=fleet_queue_factor,
                       queue_floor_s=fleet_queue_floor_s,
                       ttfs_factor=fleet_ttfs_factor,
                       ttfs_floor_s=fleet_ttfs_floor_s)
    if check_capacity:
        _compare_capacity(verdict, baseline, current,
                          goodput_factor=goodput_factor,
                          goodput_floor=goodput_floor)
    if check_resilience and (baseline or {}).get("resilience") \
            and not current.get("resilience"):
        verdict["warnings"].append(
            "resilience: baseline carried a resilience section but the "
            "current run has none — incident/checkpoint coverage was "
            "lost")
    if check_alerts:
        _check_alerts(verdict, baseline, current)
    if check_perf:
        _check_perf(verdict, baseline, current)
    return verdict


def _check_alerts(verdict, baseline, current):
    """Live-alert consistency audit (mutates ``verdict`` in place; runs
    AFTER the post-hoc SLO comparisons because it needs their
    outcomes). The ``alerts`` report section
    (:mod:`pystella_tpu.obs.slo` via the ledger) is the live half of
    each SLO; the post-hoc sections are the other. The two must agree:

    - an **unresolved-at-exit burn alert** for a leg whose post-hoc
      verdict came out GREEN is a live/post-hoc contradiction — the
      monitor watched the SLO burn until the record ended while the
      report claims the SLO held, so one of them is wrong and the
      evidence proves nothing either way: invalid evidence, exit 2
      (``--no-alerts`` opts out). An unresolved alert whose post-hoc
      leg ALSO failed is consistent (the gate already failed; the
      alert is corroboration, noted as a warning).
    - **alert-flap growth** (more fire→resolve→fire churn than the
      baseline recorded) warns: a flapping SLO is a bar sitting on the
      noise floor or a service oscillating around saturation — either
      deserves an operator before it deserves a page.
    - lost coverage (baseline carried an ``alerts`` section, current
      does not) warns like every other section."""
    cal = current.get("alerts") or {}
    bal = (baseline or {}).get("alerts") or {}
    if bal and not cal:
        verdict["warnings"].append(
            "alerts: baseline carried a live-alert (SLO burn) section "
            "but the current run has none — live SLO coverage was "
            "lost; attach the SLOMonitor (obs.slo)")
        return
    if not cal:
        return
    reasons = verdict.get("reasons") or []
    # which post-hoc legs came out green (no failing reason / no
    # recorded incidents)? keyed by the monitor's leg names
    post_hoc_green = {
        "queue_p95": not any("queue-latency p95" in r for r in reasons),
        "warm_ttfs": not any("warm time-to-first-step" in r
                             for r in reasons),
        "deadline_miss": not any("deadline-miss SLO regression" in r
                                 for r in reasons),
        "incident_rate": not (current.get("resilience")
                              or {}).get("n_incidents"),
    }
    for rec in cal.get("unresolved") or []:
        leg = str(rec.get("leg"))
        if post_hoc_green.get(leg, True):
            verdict.update(ok=False, exit_code=2)
            verdict["reasons"].append(
                f"invalid_evidence: live burn alert {leg!r} was still "
                f"firing when the run record ended (value "
                f"{rec.get('value')} vs bar {rec.get('bar')}) but the "
                "post-hoc SLO section claims green — the live and "
                "post-hoc halves contradict; trust neither")
        else:
            verdict["warnings"].append(
                f"alerts: unresolved live burn alert {leg!r} "
                "corroborates the failed post-hoc verdict for the "
                "same SLO")
    b_flaps = bal.get("flaps")
    c_flaps = cal.get("flaps")
    if isinstance(b_flaps, int) and isinstance(c_flaps, int) \
            and c_flaps > b_flaps:
        verdict["warnings"].append(
            f"alerts: {c_flaps} alert flap(s) vs {b_flaps} in the "
            "baseline — an SLO oscillating around its bar; check the "
            "report's alerts section before trusting either verdict")
    verdict["alerts"] = {
        "alerts": cal.get("alerts"), "resolved": cal.get("resolved"),
        "flaps": c_flaps, "unresolved": len(cal.get("unresolved") or []),
    }


def _check_perf(verdict, baseline, current):
    """Continuous-performance consistency audit (mutates ``verdict``
    in place; runs AFTER the step-time comparison because it needs its
    outcome). The ``perf`` report section
    (:mod:`pystella_tpu.obs.perf` via the ledger) is the live
    change-point record of the same step times the post-hoc median
    comparison gates; the two must agree:

    - an **unresolved-at-exit** ``perf_anomaly`` beside a GREEN
      post-hoc step-time verdict is a live/post-hoc contradiction —
      the detector watched a sustained shift never recover while the
      report claims step times held: invalid evidence, exit 2
      (``--no-perf`` opts out). Unresolved beside an already-failed
      step verdict is corroboration (warning).
    - anomalies that fired with **no flight-recorder capture**
      recorded warn: the plane's whole point is profiling evidence
      captured while the regression was live
      (``PYSTELLA_PERF_CAPTURE_DIR`` probably unset).
    - **anomaly-flap growth** vs the baseline and lost perf coverage
      warn like the alert section's equivalents."""
    cpf = current.get("perf") or {}
    bpf = (baseline or {}).get("perf") or {}
    if bpf and not cpf:
        verdict["warnings"].append(
            "perf: baseline carried a continuous-performance section "
            "but the current run has none — change-point coverage was "
            "lost (PYSTELLA_PERF=0?)")
        return
    if not cpf:
        return
    can = cpf.get("anomalies") or {}
    reasons = verdict.get("reasons") or []
    step_green = not any("median step time" in r for r in reasons)
    for rec in can.get("unresolved") or []:
        leg = str(rec.get("leg"))
        if step_green:
            verdict.update(ok=False, exit_code=2)
            verdict["reasons"].append(
                f"invalid_evidence: perf anomaly {leg!r} was still "
                f"open when the run record ended ({rec.get('value')} "
                f"ms vs baseline {rec.get('bar')} ms) but the "
                "post-hoc step-time verdict claims green — the "
                "change-point detector and the report contradict; "
                "trust neither")
        else:
            verdict["warnings"].append(
                f"perf: unresolved anomaly {leg!r} corroborates the "
                "failed post-hoc step-time verdict")
    if can.get("alerts") and not cpf.get("captures"):
        verdict["warnings"].append(
            f"perf: {can['alerts']} anomaly(ies) fired but no "
            "flight-recorder capture was recorded — set "
            "PYSTELLA_PERF_CAPTURE_DIR so the next regression "
            "profiles itself")
    b_flaps = (bpf.get("anomalies") or {}).get("flaps")
    c_flaps = can.get("flaps")
    if isinstance(b_flaps, int) and isinstance(c_flaps, int) \
            and c_flaps > b_flaps:
        verdict["warnings"].append(
            f"perf: {c_flaps} anomaly flap(s) vs {b_flaps} in the "
            "baseline — a detector oscillating around its threshold; "
            "check the report's perf section before trusting either "
            "verdict")
    verdict["perf"] = {
        "anomalies": can.get("alerts"),
        "recovered": can.get("resolved"),
        "flaps": c_flaps,
        "unresolved": len(can.get("unresolved") or []),
        "captures": len(cpf.get("captures") or []),
    }


def _compare_fft(verdict, baseline, current, threshold_pct=25.0):
    """Spectra-throughput comparison (mutates ``verdict`` in place):
    the current ``fft.ms.p50_ms`` — the median per-call wall time of
    the run's spectra outputs (:mod:`pystella_tpu.fourier.pencil`'s
    report section) — must stay within ``threshold_pct`` of the
    baseline's. Spectra are the dominant cost of any run that outputs
    them (the 241 ms/call gw-spectra-256³ headline vs a sub-ms step),
    so a spectral-tier regression fails CI like a slow step does. The
    threshold is wider than the step gate's: a spectra call is one
    sample per output cadence, not thousands per run. Coverage loss
    (baseline had an ``fft`` section, current does not) degrades to a
    warning; a scheme CHANGE between reports warns too — a pencil-tier
    baseline is not a like-for-like baseline for a replicate-tier
    run."""
    bff = (baseline or {}).get("fft") or {}
    cff = current.get("fft") or {}
    if bff and not cff:
        verdict["warnings"].append(
            "fft: baseline carried a spectral (fft) section but the "
            "current run has none — spectra-throughput coverage was "
            "lost")
        return
    if not bff or not cff:
        return
    bs, cs = bff.get("scheme"), cff.get("scheme")
    if bs is not None and cs is not None and bs != cs:
        verdict["warnings"].append(
            f"fft: transform scheme changed between reports (baseline "
            f"{bs!r} vs current {cs!r}) — spectra times are compared, "
            "but the tiers move different bytes")
    b = (bff.get("ms") or {}).get("p50_ms")
    c = (cff.get("ms") or {}).get("p50_ms")
    if not isinstance(b, (int, float)) or b <= 0:
        return
    if not isinstance(c, (int, float)):
        verdict["warnings"].append(
            "fft: baseline tracked a spectra p50 ms/call but the "
            "current run's fft section carries none — "
            "spectra-throughput coverage was lost")
        return
    slow_pct = 100.0 * (c - b) / b
    verdict["fft"] = {
        "baseline_p50_ms": b, "current_p50_ms": c,
        "slowdown_pct": slow_pct, "threshold_pct": threshold_pct,
    }
    if slow_pct > threshold_pct:
        verdict.update(ok=False, exit_code=max(verdict["exit_code"], 1))
        verdict["reasons"].append(
            f"fft regression: spectra p50 {c:.4g} ms/call is "
            f"{slow_pct:.1f}% above baseline {b:.4g} (threshold "
            f"{threshold_pct:g}%) — check the fft section's per-stage "
            "rows and transpose exposed time")
    elif -slow_pct > threshold_pct:
        verdict["warnings"].append(
            f"fft improvement: spectra p50 {-slow_pct:.1f}% below "
            "baseline — consider refreshing the baseline")


def _check_comm(verdict, baseline, current, excess_pct=25.0):
    """Modeled-vs-measured communication check (mutates ``verdict``
    in place) over the current report's ``comm`` section — the
    ledger's join of the dataflow lint tier's static comm model
    against the run's measured collective traffic.

    Three verdicts. A leg whose measured bytes exceed its modeled
    bytes by more than ``excess_pct`` fails (exit 1): the model counts
    every collective the compiled program CAN issue per invocation, so
    measured traffic above it is traffic the model does not attribute
    — an extra collective the partitioner materialized after the
    audit, or a byte counter measuring a different program than the
    one modeled. A ``comm`` section claiming ``covered: true`` while
    no leg carries a static model is refused (exit 2): coverage means
    modeled AND measured sides joined, so the claim is unsupportable —
    the dataflow tier never ran, or the section was assembled by hand.
    Coverage loss (baseline's comm was covered, current's is absent or
    uncovered) degrades to a warning, like every lost-coverage
    pattern here. Reports predating the section (no ``comm`` key and
    no claim) pass through untouched."""
    ccm = current.get("comm")
    bcm = (baseline or {}).get("comm") or {}
    if not ccm:
        if bcm.get("covered"):
            verdict["warnings"].append(
                "comm: baseline carried a covered modeled-vs-measured "
                "comm section but the current run has none — "
                "communication coverage was lost")
        return
    legs = ccm.get("legs") or []
    modeled_legs = [leg for leg in legs
                    if isinstance(leg.get("modeled_bytes"), (int, float))
                    and leg["modeled_bytes"] > 0]
    if ccm.get("covered") and not modeled_legs:
        verdict.update(ok=False, exit_code=2)
        verdict["reasons"].append(
            "invalid_evidence: report claims modeled-vs-measured comm "
            "coverage (comm.covered) but no leg carries a static "
            "model — a coverage claim with no model behind it; run "
            "the dataflow lint tier (python -m pystella_tpu.lint) or "
            "drop the claim")
        return
    checked = []
    for leg in modeled_legs:
        meas = leg.get("measured_bytes")
        if not isinstance(meas, (int, float)):
            continue
        modeled = float(leg["modeled_bytes"])
        over = 100.0 * (meas / modeled - 1.0)
        checked.append({
            "target": leg.get("target"), "class": leg.get("class"),
            "modeled_bytes": modeled, "measured_bytes": float(meas),
            "excess_pct": over,
        })
        if over > excess_pct:
            verdict.update(ok=False,
                           exit_code=max(verdict["exit_code"], 1))
            verdict["reasons"].append(
                f"comm excess: {leg.get('target')} "
                f"({leg.get('class')}) measured {meas:,.0f} B per "
                f"invocation is {over:.1f}% above the static model's "
                f"{modeled:,.0f} B (threshold {excess_pct:g}%) — "
                "collective traffic the model does not attribute; "
                "re-audit the program or find the unmodeled "
                "collective")
    if checked:
        verdict["comm"] = {"legs": checked,
                           "excess_threshold_pct": excess_pct}
    if bcm.get("covered") and not ccm.get("covered"):
        verdict["warnings"].append(
            "comm: baseline's comm section was covered (modeled and "
            "measured joined) but the current run's is not — "
            "communication coverage was lost")


def _compare_service(verdict, baseline, current, queue_factor=2.5,
                     queue_floor_s=0.5, ttfs_factor=2.5,
                     ttfs_floor_s=1.0):
    """Scenario-service SLO comparison (mutates ``verdict`` in place):
    two production latency metrics from the ``service`` report section
    (:mod:`pystella_tpu.service`), each gated by a relative factor AND
    an absolute floor — service latencies on a small smoke mix are
    single-sample-scale and jitter with host load, so a pure ratio
    would flap:

    - **queue-p95**: the overall p95 queue latency (submit ->
      dispatch). A regression means the scheduler is falling behind
      the offered load — the user-facing SLO.
    - **warm TTFS**: the warm leases' median time-to-first-step. The
      warm pool's whole contract is dispatch-never-compile; warm TTFS
      drifting toward cold TTFS means requests are paying compiles
      again.

    Coverage loss (baseline had a ``service`` section, current does
    not) degrades to a warning. The warm-over-mismatched-fingerprints
    refusal runs earlier, before any baseline is consulted."""
    bsv = (baseline or {}).get("service") or {}
    csv = current.get("service") or {}
    if bsv and not csv:
        verdict["warnings"].append(
            "service: baseline carried a service section but the "
            "current run has none — queue/TTFS SLO coverage was lost")
        return
    if not bsv or not csv:
        return
    compared = {}

    def _leg(name, b, c, factor, floor_s, what):
        if not isinstance(b, (int, float)) or b < 0 \
                or not isinstance(c, (int, float)):
            if isinstance(b, (int, float)) and c is None:
                verdict["warnings"].append(
                    f"service: baseline tracked {what} but the "
                    "current run's service section carries none — "
                    "SLO coverage was lost")
            return
        compared[name] = {"baseline_s": b, "current_s": c,
                          "factor": factor, "floor_s": floor_s}
        if c > b * factor and c - b > floor_s:
            verdict.update(ok=False,
                           exit_code=max(verdict["exit_code"], 1))
            verdict["reasons"].append(
                f"service SLO regression: {what} {c:.3g} s vs "
                f"baseline {b:.3g} s (allowed factor {factor:g}, "
                f"floor {floor_s:g} s) — see the report's service "
                "section")
        elif b > c * factor and b - c > floor_s:
            verdict["warnings"].append(
                f"service improvement: {what} {c:.3g} s vs baseline "
                f"{b:.3g} s — consider refreshing the baseline")

    _leg("queue_p95",
         ((bsv.get("queue_latency_s") or {}).get("overall")
          or {}).get("p95_s"),
         ((csv.get("queue_latency_s") or {}).get("overall")
          or {}).get("p95_s"),
         queue_factor, queue_floor_s, "queue-latency p95")
    _leg("warm_ttfs",
         ((bsv.get("ttfs_s") or {}).get("warm") or {}).get("p50_s"),
         ((csv.get("ttfs_s") or {}).get("warm") or {}).get("p50_s"),
         ttfs_factor, ttfs_floor_s, "warm time-to-first-step p50")
    if compared:
        verdict["service"] = compared


def _compare_fleet(verdict, baseline, current, queue_factor=2.5,
                   queue_floor_s=0.5, ttfs_factor=2.5,
                   ttfs_floor_s=1.0):
    """Fleet SLO comparison (mutates ``verdict`` in place): the fleet
    ``legs`` of the ``fleet`` report section
    (:mod:`pystella_tpu.obs.fleet` — each leg's windowed value at the
    last aggregation pass, computed over EVERY replica's samples), held
    to the same factor+floor bars as the single-replica service legs.
    Also the fleet hygiene warnings: version/flag skew appearing when
    the baseline fleet had none, warm-fingerprint divergence (the
    hard precondition for cross-replica warm-artifact reuse), and
    fleet-alert flap growth. Coverage loss (baseline had a fleet
    section, current does not) degrades to a warning. The
    partial-evidence refusal and the degraded annotation run earlier,
    before any baseline is consulted."""
    bfl = (baseline or {}).get("fleet") or {}
    cfl = current.get("fleet") or {}
    if bfl and not cfl:
        verdict["warnings"].append(
            "fleet: baseline carried a fleet section but the current "
            "run has none — fleet SLO coverage was lost")
        return
    if not cfl:
        return
    # hygiene findings need no baseline: skew and divergence are
    # absolute properties of THIS fleet
    if (cfl.get("skew") or {}).get("skewed") \
            and not (bfl.get("skew") or {}).get("skewed"):
        verdict["warnings"].append(
            "fleet: version/flag SKEW across live replicas "
            f"({(cfl.get('skew') or {}).get('stacks')} distinct "
            "compiler stacks) — fleet aggregates mix incomparable "
            "programs; align the stacks before trusting fleet legs")
    if cfl.get("divergence"):
        verdict["warnings"].append(
            "fleet: warm-fingerprint divergence across replicas for "
            f"signature(s) {', '.join(cfl['divergence'])} — the same "
            "signature is served by different programs; do not share "
            "warm artifacts across this fleet")
    if not bfl:
        return
    compared = {}

    def _leg(name, factor, floor_s, what):
        b = ((bfl.get("legs") or {}).get(name) or {}).get("value_fast")
        c = ((cfl.get("legs") or {}).get(name) or {}).get("value_fast")
        if not isinstance(b, (int, float)) or b < 0 \
                or not isinstance(c, (int, float)):
            if isinstance(b, (int, float)) and c is None:
                verdict["warnings"].append(
                    f"fleet: baseline tracked {what} but the current "
                    "run's fleet section carries none — fleet SLO "
                    "coverage was lost")
            return
        compared[name] = {"baseline_s": b, "current_s": c,
                          "factor": factor, "floor_s": floor_s}
        if c > b * factor and c - b > floor_s:
            verdict.update(ok=False,
                           exit_code=max(verdict["exit_code"], 1))
            verdict["reasons"].append(
                f"fleet SLO regression: {what} {c:.3g} s vs "
                f"baseline {b:.3g} s (allowed factor {factor:g}, "
                f"floor {floor_s:g} s) — see the report's fleet "
                "section")
        elif b > c * factor and b - c > floor_s:
            verdict["warnings"].append(
                f"fleet improvement: {what} {c:.3g} s vs baseline "
                f"{b:.3g} s — consider refreshing the baseline")

    _leg("queue_p95", queue_factor, queue_floor_s,
         "fleet queue-latency p95")
    _leg("warm_ttfs", ttfs_factor, ttfs_floor_s,
         "fleet warm time-to-first-step p50")
    b_flaps = (bfl.get("alerts") or {}).get("flaps")
    c_flaps = (cfl.get("alerts") or {}).get("flaps")
    if isinstance(b_flaps, int) and isinstance(c_flaps, int) \
            and c_flaps > b_flaps:
        verdict["warnings"].append(
            f"fleet: {c_flaps} fleet alert flap(s) vs {b_flaps} in "
            "the baseline — a fleet SLO oscillating around its bar")
    if compared:
        verdict["fleet"] = compared


def _compare_capacity(verdict, baseline, current, goodput_factor=2.0,
                      goodput_floor=1.0):
    """Goodput comparison (mutates ``verdict`` in place): the current
    ``capacity.goodput`` — committed member-steps per chip-second
    leased (:mod:`pystella_tpu.obs.capacity` attribution over the
    span phases × chips) — held to the same factor+floor shape as the
    service SLO legs, with the inequality FLIPPED: goodput regresses
    downward, so the gate fails (exit 1) when current drops below
    baseline / ``goodput_factor`` AND by more than ``goodput_floor``
    steps/chip-s absolute. Waste chip-second growth (replay +
    preempt-drain share of the leased chip time) warns against the
    baseline, and coverage loss (baseline had a capacity section,
    current does not) degrades to a warning. The partial-evidence
    refusal and the predicted-only annotation run earlier, before any
    baseline is consulted."""
    bcap = (baseline or {}).get("capacity") or {}
    ccap = current.get("capacity") or {}
    if bcap and not ccap:
        verdict["warnings"].append(
            "capacity: baseline carried a capacity section but the "
            "current run has none — HBM-footprint/goodput coverage "
            "was lost")
        return
    if not ccap or not bcap:
        return
    b = bcap.get("goodput")
    c = ccap.get("goodput")
    if isinstance(b, (int, float)) and b > 0 \
            and isinstance(c, (int, float)):
        verdict["capacity"] = {
            "baseline_goodput": b, "current_goodput": c,
            "factor": goodput_factor, "floor": goodput_floor}
        if c < b / goodput_factor and b - c > goodput_floor:
            verdict.update(ok=False,
                           exit_code=max(verdict["exit_code"], 1))
            verdict["reasons"].append(
                f"goodput regression: {c:.3g} committed "
                f"steps/chip-s vs baseline {b:.3g} (allowed factor "
                f"{goodput_factor:g}, floor {goodput_floor:g}) — "
                "chips are burning on waste (replay, drain, idle "
                "leases); see the report's capacity section")
        elif c > b * goodput_factor and c - b > goodput_floor:
            verdict["warnings"].append(
                f"goodput improvement: {c:.3g} steps/chip-s vs "
                f"baseline {b:.3g} — consider refreshing the "
                "baseline")
    elif isinstance(b, (int, float)) and c is None:
        verdict["warnings"].append(
            "capacity: baseline tracked goodput but the current "
            "run's capacity section carries none — chip-second "
            "attribution coverage was lost")
    b_waste = bcap.get("waste_chip_s")
    c_waste = ccap.get("waste_chip_s")
    if isinstance(b_waste, (int, float)) \
            and isinstance(c_waste, (int, float)) \
            and c_waste > 2.0 * b_waste and c_waste - b_waste > 1.0:
        verdict["warnings"].append(
            f"capacity: {c_waste:.3g} waste chip-second(s) (replay + "
            f"preempt-drain) vs {b_waste:.3g} in the baseline — "
            "recovery/eviction churn is eating leased chip time")


def _compare_latency(verdict, baseline, current, miss_factor=2.0,
                     miss_floor=0.05):
    """Deadline-miss SLO comparison (mutates ``verdict`` in place):
    the current ``latency.deadline.miss_rate`` — the fraction of
    deadlined requests that retired after their deadline
    (:mod:`pystella_tpu.obs.spans` /
    :class:`~pystella_tpu.service.results.ResultEmitter`) — must stay
    within ``miss_factor`` × the baseline's AND within ``miss_floor``
    absolute above it before the gate fails (exit 1). Both bars, like
    the other service SLOs: a smoke mix deadlines a handful of
    requests, so one flipped verdict moves the rate by a whole
    quantum — the floor keeps that honest while a real scheduler
    regression (misses doubling AND growing by 5+ points) reliably
    fails. Coverage loss (baseline had a ``latency`` section or a
    deadline ledger, current does not) degrades to a warning; the
    unassembled-span-tree warning runs earlier, before any baseline
    is consulted."""
    blat = (baseline or {}).get("latency") or {}
    clat = current.get("latency") or {}
    if blat and not clat:
        verdict["warnings"].append(
            "latency: baseline carried a latency (critical-path) "
            "section but the current run has none — deadline-miss SLO "
            "coverage was lost")
        return
    if not blat or not clat:
        return
    bdl = blat.get("deadline") or {}
    cdl = clat.get("deadline") or {}
    b = bdl.get("miss_rate")
    c = cdl.get("miss_rate")
    if isinstance(b, (int, float)) and c is None:
        verdict["warnings"].append(
            "latency: baseline tracked a deadline-miss rate but the "
            "current run deadlined no requests — deadline-miss SLO "
            "coverage was lost")
        return
    if not isinstance(b, (int, float)) or not isinstance(
            c, (int, float)):
        return
    verdict["latency"] = {
        "baseline_miss_rate": b, "current_miss_rate": c,
        "baseline_missed": bdl.get("missed"),
        "current_missed": cdl.get("missed"),
        "miss_factor": miss_factor, "miss_floor": miss_floor,
    }
    if c > b * miss_factor and c - b > miss_floor:
        verdict.update(ok=False, exit_code=max(verdict["exit_code"], 1))
        verdict["reasons"].append(
            f"deadline-miss SLO regression: miss rate {c:.1%} "
            f"({cdl.get('missed')}/{cdl.get('deadlined')} deadlined "
            f"request(s)) vs baseline {b:.1%} (allowed factor "
            f"{miss_factor:g}, floor {miss_floor:g}) — see the "
            "report's latency section for the dominant phase behind "
            "the misses")
    elif b > c * miss_factor and b - c > miss_floor:
        verdict["warnings"].append(
            f"deadline-miss improvement: miss rate {c:.1%} vs baseline "
            f"{b:.1%} — consider refreshing the baseline")


def _compare_ensemble(verdict, baseline, current, threshold_pct=20.0):
    """Member-throughput comparison (mutates ``verdict`` in place): the
    current ``ensemble.member_steps_per_s`` must stay within
    ``threshold_pct`` of the baseline's. The threshold is wider than
    the step-time gate's because a driver run's wall time includes
    host-side queue management (occupancy changes jitter it); a real
    batching regression (a lost vmap, a per-member re-trace) costs far
    more than 20%. Coverage loss and eviction-count growth degrade to
    warnings."""
    ben = (baseline or {}).get("ensemble") or {}
    cen = current.get("ensemble") or {}
    if ben and not cen:
        verdict["warnings"].append(
            "ensemble: baseline carried an ensemble section but the "
            "current run has none — member-throughput coverage was "
            "lost")
        return
    # eviction growth is independent of the throughput metric: it must
    # warn even when either run's rate is missing (a driver that died
    # mid-run still counted its member_evicted events)
    bev, cev = ben.get("evictions"), cen.get("evictions")
    if isinstance(bev, int) and isinstance(cev, int) and cev > bev:
        verdict["warnings"].append(
            f"ensemble: {cev} member eviction(s) vs {bev} in the "
            "baseline — more bad draws than the baseline configuration "
            "produced")
    b = ben.get("member_steps_per_s")
    c = cen.get("member_steps_per_s")
    if not isinstance(b, (int, float)) or b <= 0:
        return
    if not isinstance(c, (int, float)):
        # the section exists (chunk/eviction events landed) but the
        # throughput metric is gone — a driver that died mid-run never
        # emits ensemble_done; a baseline-gated metric must not vanish
        # silently
        verdict["warnings"].append(
            "ensemble: baseline tracked member_steps_per_s but the "
            "current run's ensemble section carries none — "
            "member-throughput coverage was lost")
        return
    drop_pct = 100.0 * (b - c) / b
    verdict["ensemble"] = {
        "baseline_member_steps_per_s": b,
        "current_member_steps_per_s": c,
        "drop_pct": drop_pct, "threshold_pct": threshold_pct,
    }
    if drop_pct > threshold_pct:
        verdict.update(ok=False, exit_code=max(verdict["exit_code"], 1))
        verdict["reasons"].append(
            f"ensemble regression: member throughput {c:.4g} "
            f"member-steps/s is {drop_pct:.1f}% below baseline "
            f"{b:.4g} (threshold {threshold_pct:g}%) — check batch "
            "occupancy and the chunk-dispatch distribution in the "
            "report's ensemble section")
    elif -drop_pct > threshold_pct:
        verdict["warnings"].append(
            f"ensemble improvement: member throughput {-drop_pct:.1f}% "
            "above baseline — consider refreshing the baseline")


def _compare_cold_start(verdict, baseline, current, factor=1.5,
                        floor_s=5.0):
    """Time-to-first-step comparison (mutates ``verdict`` in place): a
    regression must clear BOTH the relative factor and the absolute
    floor — cold start on a small smoke run jitters by seconds
    (interpreter + jax import), so a pure ratio would flap. Coverage
    loss (baseline had a ``cold_start`` section, current does not)
    degrades to a warning."""
    bcs = (baseline or {}).get("cold_start") or {}
    ccs = current.get("cold_start") or {}
    b = bcs.get("time_to_first_step_s")
    c = ccs.get("time_to_first_step_s")
    if bcs and not ccs:
        verdict["warnings"].append(
            "cold_start: baseline carried a cold-start section but the "
            "current run has none — cold-start coverage was lost")
        return
    if (isinstance(b, (int, float)) and b > 0
            and not isinstance(c, (int, float))):
        # the current run has compile telemetry but never measured a
        # time-to-first-step (driver crashed pre-step, or a custom
        # driver without the cold_start event) — the metric the
        # baseline gated on is GONE, which must be visible, not a
        # silent pass
        verdict["warnings"].append(
            "cold_start: baseline carried a time-to-first-step but the "
            "current run's cold_start section has none — cold-start "
            "coverage was lost")
        return
    if not isinstance(b, (int, float)) or not isinstance(
            c, (int, float)) or b <= 0:
        return
    verdict["cold_start"] = {
        "baseline_s": b, "current_s": c,
        "factor": factor, "floor_s": floor_s,
    }
    if c > b * factor and c - b > floor_s:
        verdict.update(ok=False, exit_code=max(verdict["exit_code"], 1))
        verdict["reasons"].append(
            f"cold-start regression: time-to-first-step {c:.1f} s vs "
            f"baseline {b:.1f} s (allowed factor {factor:g}, floor "
            f"{floor_s:g} s) — check the compile table and cache hit "
            "rate in the report's cold_start section")
    elif b > c * factor and b - c > floor_s:
        verdict["warnings"].append(
            f"cold-start improvement: {c:.1f} s vs baseline {b:.1f} s "
            "— consider refreshing the baseline")


def _compare_numerics(verdict, baseline, current, drift_factor=10.0,
                      drift_floor=1e-12):
    """Invariant-drift comparison (mutates ``verdict`` in place): for
    every invariant both reports tracked, the current |drift/step| must
    stay within ``drift_factor`` x the baseline's (both floored at
    ``drift_floor``). Invariants only one side tracked degrade to a
    warning — losing numerics coverage should be visible, not fatal."""
    bnum = (baseline.get("numerics") or {}).get("invariants") or {}
    cnum = (current.get("numerics") or {}).get("invariants") or {}
    if not bnum and not cnum:
        return
    if bnum and not cnum:
        verdict["warnings"].append(
            "numerics: baseline tracked invariants "
            f"{sorted(bnum)} but the current run has no numerics "
            "section — sentinel coverage was lost")
        return
    compared = {}
    for name in sorted(set(bnum) & set(cnum)):
        bn = bnum[name].get("n") or 0
        cn = cnum[name].get("n") or 0
        if bn < 2 or cn < 2:
            # a degenerate series yields slope 0.0 (ledger._slope),
            # indistinguishable from a genuinely flat invariant —
            # gating against the bare floor would flag honest roundoff
            verdict["warnings"].append(
                f"numerics: invariant {name!r} has too few samples "
                f"for a drift slope (baseline n={bn}, current "
                f"n={cn}); not compared")
            continue
        b = abs(bnum[name].get("drift_per_step") or 0.0)
        c = abs(cnum[name].get("drift_per_step") or 0.0)
        allowed = drift_factor * max(b, drift_floor)
        compared[name] = {"baseline_drift": b, "current_drift": c,
                          "allowed": allowed}
        if c > allowed:
            verdict.update(ok=False, exit_code=max(
                verdict["exit_code"], 1))
            verdict["reasons"].append(
                f"numerics regression: invariant {name!r} drift "
                f"{c:.3e}/step vs baseline {b:.3e}/step (allowed "
                f"factor {drift_factor:g}, floor {drift_floor:g})")
    for name in sorted(set(bnum) - set(cnum)):
        verdict["warnings"].append(
            f"numerics: invariant {name!r} tracked in the baseline "
            "but not the current run")
    verdict["numerics"] = compared


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m pystella_tpu.obs.gate",
        description="noise-aware perf regression gate over perf_report"
                    ".json files (0 pass, 1 regression, 2 invalid "
                    "evidence, 3 missing baseline)")
    p.add_argument("--baseline", required=True,
                   help="baseline perf_report.json")
    p.add_argument("--current", required=True,
                   help="current perf_report.json")
    p.add_argument("--threshold-pct", type=float, default=10.0,
                   help="relative p50 step-time slowdown that counts as "
                        "a regression (default 10)")
    p.add_argument("--mad-k", type=float, default=3.0,
                   help="noise bar in Gaussian-consistent MAD sigmas a "
                        "regression must also clear (default 3)")
    p.add_argument("--outlier-k", type=float, default=5.0,
                   help="contamination: outlier threshold in sigmas "
                        "above the median (default 5)")
    p.add_argument("--burst", type=int, default=4,
                   help="contamination: consecutive outlier steps that "
                        "invalidate the run (default 4)")
    p.add_argument("--outlier-frac", type=float, default=0.10,
                   help="contamination: outlier fraction that "
                        "invalidates the run (default 0.10)")
    p.add_argument("--check-contamination",
                   choices=("auto", "always", "never"), default="auto",
                   help="auto (default): run the contamination detector "
                        "on accelerator reports only (CPU step times "
                        "are legitimately scheduler-noisy; the median "
                        "comparison absorbs that); always/never force")
    p.add_argument("--drift-factor", type=float, default=10.0,
                   help="numerics: allowed multiple of the baseline's "
                        "invariant drift slope before the gate fails "
                        "(default 10)")
    p.add_argument("--drift-floor", type=float, default=1e-12,
                   help="numerics: drift-per-step floor applied to both "
                        "sides, so a ~zero baseline slope cannot make "
                        "any finite drift a regression (default 1e-12)")
    p.add_argument("--cold-start-factor", type=float, default=1.5,
                   help="cold start: allowed multiple of the baseline's "
                        "time-to-first-step before the gate fails "
                        "(default 1.5)")
    p.add_argument("--cold-start-floor", type=float, default=5.0,
                   help="cold start: absolute seconds a regression must "
                        "also exceed (default 5; small-run cold starts "
                        "jitter by whole seconds)")
    p.add_argument("--ensemble-threshold-pct", type=float, default=20.0,
                   help="ensemble: allowed member-steps/s drop vs the "
                        "baseline before the gate fails (default 20)")
    p.add_argument("--no-ensemble", action="store_true",
                   help="skip the ensemble member-throughput check")
    p.add_argument("--fft-threshold-pct", type=float, default=25.0,
                   help="fft: allowed spectra p50 ms/call slowdown vs "
                        "the baseline before the gate fails (default "
                        "25)")
    p.add_argument("--no-fft", action="store_true",
                   help="skip the spectral-tier (fft section) "
                        "spectra-throughput check")
    p.add_argument("--comm-excess-pct", type=float,
                   default=_config.get_float(
                       "PYSTELLA_GATE_COMM_EXCESS_PCT"),
                   help="comm: allowed measured-over-modeled collective"
                        "-traffic excess before the gate fails "
                        "(default 25, env "
                        "PYSTELLA_GATE_COMM_EXCESS_PCT)")
    p.add_argument("--no-comm", action="store_true",
                   help="skip the modeled-vs-measured communication "
                        "check (comm section)")
    p.add_argument("--service-queue-factor", type=float, default=2.5,
                   help="service: allowed multiple of the baseline's "
                        "queue-latency p95 before the gate fails "
                        "(default 2.5)")
    p.add_argument("--service-queue-floor", type=float, default=0.5,
                   help="service: absolute seconds a queue-p95 "
                        "regression must also exceed (default 0.5)")
    p.add_argument("--service-ttfs-factor", type=float, default=2.5,
                   help="service: allowed multiple of the baseline's "
                        "warm time-to-first-step p50 before the gate "
                        "fails (default 2.5)")
    p.add_argument("--service-ttfs-floor", type=float, default=1.0,
                   help="service: absolute seconds a warm-TTFS "
                        "regression must also exceed (default 1)")
    p.add_argument("--no-service", action="store_true",
                   help="skip the scenario-service checks (queue-p95 / "
                        "warm-TTFS SLO regressions, warm-admission-"
                        "over-mismatched-fingerprints refusal)")
    p.add_argument("--latency-miss-factor", type=float, default=2.0,
                   help="latency: allowed multiple of the baseline's "
                        "deadline-miss rate before the gate fails "
                        "(default 2)")
    p.add_argument("--latency-miss-floor", type=float, default=0.05,
                   help="latency: absolute miss-rate increase a "
                        "regression must also exceed (default 0.05 — "
                        "one flipped verdict on a small smoke mix "
                        "moves the rate by a whole quantum)")
    p.add_argument("--no-latency", action="store_true",
                   help="skip the request-latency checks (deadline-"
                        "miss SLO regression, span-assembly coverage "
                        "warnings)")
    p.add_argument("--fleet-queue-factor", type=float, default=2.5,
                   help="fleet: allowed multiple of the baseline's "
                        "fleet queue-latency p95 before the gate "
                        "fails (default 2.5)")
    p.add_argument("--fleet-queue-floor", type=float, default=0.5,
                   help="fleet: absolute seconds a fleet queue-p95 "
                        "regression must also exceed (default 0.5)")
    p.add_argument("--fleet-ttfs-factor", type=float, default=2.5,
                   help="fleet: allowed multiple of the baseline's "
                        "fleet warm-TTFS p50 before the gate fails "
                        "(default 2.5)")
    p.add_argument("--fleet-ttfs-floor", type=float, default=1.0,
                   help="fleet: absolute seconds a fleet warm-TTFS "
                        "regression must also exceed (default 1)")
    p.add_argument("--no-fleet", action="store_true",
                   help="skip the fleet checks (full-coverage-claim-"
                        "over-lossy-scrapes refusal, degraded-fleet "
                        "annotation, fleet queue-p95/warm-TTFS "
                        "regressions, skew/divergence/flap warnings)")
    p.add_argument("--goodput-factor", type=float, default=2.0,
                   help="capacity: allowed divisor of the baseline's "
                        "goodput (committed steps/chip-s) before the "
                        "gate fails (default 2)")
    p.add_argument("--goodput-floor", type=float, default=1.0,
                   help="capacity: absolute steps/chip-s a goodput "
                        "regression must also exceed (default 1)")
    p.add_argument("--no-capacity", action="store_true",
                   help="skip the capacity checks (complete-coverage-"
                        "with-no-watermarks refusal, predicted-only "
                        "annotation, reconciliation-drift warning, "
                        "goodput regression, waste-chip-second "
                        "growth)")
    p.add_argument("--no-alerts", action="store_true",
                   help="skip the live-alert consistency audit (an "
                        "unresolved burn alert beside a green post-hoc "
                        "SLO section refuses the evidence; alert-flap "
                        "growth warns)")
    p.add_argument("--no-perf", action="store_true",
                   help="skip the continuous-performance consistency "
                        "audit (an unresolved perf_anomaly beside a "
                        "green step-time verdict refuses the "
                        "evidence; missing flight-recorder captures "
                        "and anomaly-flap growth warn)")
    p.add_argument("--no-resilience", action="store_true",
                   help="skip the resilience triage (degraded-fleet "
                        "annotation of regressions/contamination across "
                        "recorded incidents; claims-clean-with-"
                        "incidents refusal)")
    p.add_argument("--no-cold-start", action="store_true",
                   help="skip the cold-start checks (time-to-first-step "
                        "regression, warm-start fingerprint-mismatch "
                        "refusal)")
    p.add_argument("--no-numerics", action="store_true",
                   help="skip the numerics checks (invariant drift, "
                        "diverged-run invalidation)")
    p.add_argument("--no-lint", action="store_true",
                   help="skip the lint check (a failed static analysis "
                        "in the current report's `lint` section refuses "
                        "the evidence)")
    p.add_argument("--allow-missing-baseline", action="store_true",
                   help="exit 0 (after the contamination check) when "
                        "the baseline file does not exist")
    p.add_argument("--allow-env-mismatch", action="store_true",
                   help="downgrade a baseline/current hardware mismatch "
                        "from invalid evidence to a warning")
    args = p.parse_args(argv)

    try:
        current = load_report(args.current)
    except (OSError, ValueError) as e:
        print(f"gate: cannot read current report: {e}", file=sys.stderr)
        return 4

    baseline = None
    try:
        baseline = load_report(args.baseline)
    except (OSError, ValueError) as e:
        if not args.allow_missing_baseline:
            print(f"gate: cannot read baseline: {e} "
                  "(--allow-missing-baseline to tolerate)",
                  file=sys.stderr)
            return 3
        print(f"gate: no baseline ({e}); contamination check only",
              file=sys.stderr)

    verdict = compare_reports(
        baseline, current, threshold_pct=args.threshold_pct,
        mad_k=args.mad_k, outlier_k=args.outlier_k,
        burst_limit=args.burst, frac_limit=args.outlier_frac,
        allow_env_mismatch=args.allow_env_mismatch,
        check_contamination=args.check_contamination,
        check_numerics=not args.no_numerics,
        drift_factor=args.drift_factor, drift_floor=args.drift_floor,
        check_lint=not args.no_lint,
        check_cold_start=not args.no_cold_start,
        cold_start_factor=args.cold_start_factor,
        cold_start_floor=args.cold_start_floor,
        check_ensemble=not args.no_ensemble,
        ensemble_threshold_pct=args.ensemble_threshold_pct,
        check_resilience=not args.no_resilience,
        check_fft=not args.no_fft,
        fft_threshold_pct=args.fft_threshold_pct,
        check_comm=not args.no_comm,
        comm_excess_pct=args.comm_excess_pct,
        check_service=not args.no_service,
        service_queue_factor=args.service_queue_factor,
        service_queue_floor_s=args.service_queue_floor,
        service_ttfs_factor=args.service_ttfs_factor,
        service_ttfs_floor_s=args.service_ttfs_floor,
        check_latency=not args.no_latency,
        latency_miss_factor=args.latency_miss_factor,
        latency_miss_floor=args.latency_miss_floor,
        check_alerts=not args.no_alerts,
        check_perf=not args.no_perf,
        check_fleet=not args.no_fleet,
        fleet_queue_factor=args.fleet_queue_factor,
        fleet_queue_floor_s=args.fleet_queue_floor,
        fleet_ttfs_factor=args.fleet_ttfs_factor,
        fleet_ttfs_floor_s=args.fleet_ttfs_floor,
        check_capacity=not args.no_capacity,
        goodput_factor=args.goodput_factor,
        goodput_floor=args.goodput_floor)

    print(json.dumps(verdict, indent=1, sort_keys=True))
    for w in verdict.get("warnings", []):
        print(f"gate: WARNING: {w}", file=sys.stderr)
    for r in verdict.get("reasons", []):
        print(f"gate: {r}", file=sys.stderr)
    print(f"gate: {'PASS' if verdict['ok'] else 'FAIL'} "
          f"(exit {verdict['exit_code']})", file=sys.stderr)
    # the verdict joins the run record when an event log is configured
    _events.emit("gate_verdict", ok=verdict["ok"],
                 exit_code=verdict["exit_code"],
                 reasons=verdict["reasons"])
    return verdict["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
