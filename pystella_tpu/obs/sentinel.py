"""In-graph numerics health sentinels with asynchronous host polling.

:class:`~pystella_tpu.HealthMonitor`'s original design put a blocking
host sync on the step critical path every N steps: one ``isfinite``
reduction per field, each forced to host before the next step could be
issued. This module is the replacement underneath it — always-on
numerics telemetry with **no forced sync**:

- :class:`Sentinel` computes a compact per-step **health vector**
  (schema v1: per field ``finite`` / ``max_abs`` / ``rms``, plus
  model-level invariant scalars — energy components, Friedmann
  constraint residual) as pure traceable jnp, so it runs *inside* the
  compiled step (``Stepper.step_with_health``,
  ``FusedScalarStepper.multi_step(..., sentinel=...)``) or as one tiny
  fused dispatch right after it (:meth:`SentinelMonitor.observe`). The
  vector is a few dozen bytes; XLA fuses its reductions with the step's
  final writes.
- :class:`SentinelMonitor` is the asynchronous consumer: the driver
  pushes each step's (device-resident) health vector and polls. A poll
  only converts vectors **at least** ``every`` steps behind the newest
  push — values whose computation retired long ago — so the driver loop
  always runs ``>= every`` steps ahead of any device->host transfer and
  the dispatch pipeline never drains. ``flush()`` drains everything
  (end of run, pre-checkpoint).

On a tripped sentinel (non-finite field, magnitude bound, or an
invariant leaving its declared bounds) the monitor emits a ``diverged``
run event carrying the *actual* offending step, hands its ring-buffer
history to the configured :class:`~pystella_tpu.obs.forensics.
ForensicSink` (last-K health vectors, per-field stats history, recent
event-log window, environment fingerprint, last-good-checkpoint
pointer), and raises :class:`SimulationDiverged`.

Host-side cost is accounted in the ``sentinel`` metrics timer; the
ledger reports it as a percentage of step time (``numerics``
section in ``perf_report.json``) and a tier-1 test pins it under 2% of
the smoke payload's step time.
"""

from __future__ import annotations

import collections

import numpy as np

import jax
import jax.numpy as jnp

from pystella_tpu.obs import events as _events
from pystella_tpu.obs import metrics as _metrics

__all__ = ["HEALTH_SCHEMA_VERSION", "Sentinel", "SentinelMonitor",
           "SimulationDiverged"]

#: health-vector layout version (doc/observability.md "Numerics health")
HEALTH_SCHEMA_VERSION = 1

#: per-field statistics, in slot order
FIELD_STATS = ("finite", "max_abs", "rms")


class SimulationDiverged(RuntimeError):
    """Raised when the numerics health check fails: non-finite values,
    a magnitude bound exceeded, or an invariant outside its declared
    bounds. ``step`` is the step the offending state was produced at
    (not the step the check ran at); ``bad_fields`` names the offending
    fields and/or invariants."""

    def __init__(self, step, bad_fields, problems=None):
        self.step = step
        self.bad_fields = tuple(bad_fields)
        self.problems = tuple(problems or ())
        detail = ("; ".join(self.problems) if self.problems
                  else ", ".join(self.bad_fields))
        super().__init__(
            f"numerics health check failed at step {step}: {detail}")


def _max_abs_and_mean_sq(x):
    """``(max|x|, mean(x^2))`` as ONE variadic reduction — a single
    pass over the array instead of two separate reduce ops (XLA does
    not fuse independent reductions over the same input; measured ~1.5x
    on the CPU backend, and on TPU one pass means the health stats ride
    a single read of the state the step just wrote)."""
    x = jnp.asarray(x)
    # reduce over the ORIGINAL axes — an earlier ravel()-then-reduce
    # formulation forced the SPMD partitioner to all-gather every
    # sharded field before the 1-D reshape (a full per-field lattice
    # transfer per health vector), which the IR-tier lint's collective
    # audit caught the first time it ran; the multi-axis reduce keeps
    # the pass shard-local with one tiny scalar all-reduce at the end
    ax = jnp.abs(x)
    sq = jnp.square(x)
    zero = jnp.zeros((), ax.dtype)
    mx, s = jax.lax.reduce(
        (ax, sq), (zero, zero),
        lambda acc, v: (jnp.maximum(acc[0], v[0]), acc[1] + v[1]),
        tuple(range(ax.ndim)))
    return mx, s / x.size


def _leaf_name(path):
    return ".".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def named_leaves(state):
    """``{dotted-path-name: leaf}`` for a state pytree (the field-naming
    convention shared with :class:`~pystella_tpu.HealthMonitor`)."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    return {_leaf_name(path): leaf for path, leaf in leaves}


class Sentinel:
    """Compact per-step health vector of a state pytree (schema v1).

    :arg fields: iterable of state leaf names (dotted paths, see
        :func:`named_leaves`); stored sorted.
    :arg invariants: optional ``{name: fn}`` of model-level invariant
        scalars — each ``fn(state, aux)`` must be traceable jnp
        returning a scalar (``aux`` is the driver-supplied dict of
        background scalars, e.g. ``{"a": ..., "adot": ...}``; may be
        empty). Typical producers:
        :meth:`pystella_tpu.ScalarSector.energy_means` and
        :meth:`pystella_tpu.Expansion.constraint_residual`.
    :arg dtype: output vector dtype (default float32 — the vector is
        telemetry, not arithmetic).

    Layout: for each field name in sorted order, three slots ``finite``
    (1.0 iff every element is finite), ``max_abs``, ``rms``; then one
    slot per invariant in sorted name order. The finite flag derives
    from the reductions themselves (a single NaN/Inf poisons
    ``max_abs``/``rms``), so each field costs one read fused into two
    reductions — no separate ``isfinite`` pass.
    """

    def __init__(self, fields, invariants=None, dtype=jnp.float32):
        self.fields = tuple(sorted(str(f) for f in fields))
        if not self.fields:
            raise ValueError("Sentinel needs at least one field name")
        self.invariants = dict(sorted((invariants or {}).items()))
        self.dtype = jnp.zeros((), dtype).dtype
        self._jit = None

    @classmethod
    def for_state(cls, state, invariants=None, **kwargs):
        """Build from a concrete state pytree's leaf names."""
        return cls(named_leaves(state), invariants, **kwargs)

    @property
    def size(self):
        return len(FIELD_STATS) * len(self.fields) + len(self.invariants)

    @property
    def slot_names(self):
        """Flat slot names, e.g. ``["dfdt.finite", "dfdt.max_abs",
        "dfdt.rms", "f.finite", ..., "constraint"]``."""
        out = [f"{f}.{s}" for f in self.fields for s in FIELD_STATS]
        return out + list(self.invariants)

    # -- the traceable core -------------------------------------------------

    def compute(self, state, aux=None):
        """The health vector of ``state`` — pure traceable jnp, callable
        inside any jitted step. ``aux`` is forwarded to the invariant
        functions."""
        leaves = named_leaves(state)
        missing = [f for f in self.fields if f not in leaves]
        if missing:
            raise KeyError(f"state has no leaves {missing}; sentinel "
                           f"was built for fields {list(self.fields)}")
        parts = []
        for name in self.fields:
            x = leaves[name]
            max_abs, mean_sq = _max_abs_and_mean_sq(x)
            # the flag derives from the reductions — no extra pass —
            # but each leg covers a specific failure: a NaN element
            # always poisons the SUM as NaN (the max alone is not
            # sufficient — XLA max-reductions may drop NaN per IEEE
            # maxNum, which is exactly how the pre-sentinel smoke
            # payload ran NaN for five rounds unnoticed), and an inf
            # element always poisons the MAX. mean_sq == +inf with a
            # finite max is merely x*x overflowing the field dtype
            # (legitimate large-but-finite data, e.g. f32 beyond
            # ~1.8e19) and must NOT read as divergence — so the sum
            # leg only vetoes on NaN.
            finite = jnp.isfinite(max_abs) & ~jnp.isnan(mean_sq)
            parts += [finite.astype(self.dtype),
                      max_abs.astype(self.dtype),
                      jnp.sqrt(mean_sq).astype(self.dtype)]
        aux = aux or {}
        for name, fn in self.invariants.items():
            parts.append(jnp.asarray(fn(state, aux), self.dtype)
                         .reshape(()))
        return jnp.stack(parts)

    def compute_jit(self, state, aux=None):
        """Jitted :meth:`compute` — one tiny fused dispatch, returning a
        device array (NO host sync)."""
        if self._jit is None:
            self._jit = jax.jit(self.compute)
        return self._jit(state, aux or {})

    def compute_members(self, states, aux=None):
        """The member-axis generalization of :meth:`compute` for the
        ensemble tier (:mod:`pystella_tpu.ensemble`): ``states`` is a
        batched state pytree whose leaves carry a leading member axis,
        and the result is a ``(members, size)`` health MATRIX — row i
        is exactly the vector :meth:`compute` would produce for member
        i. Pure traceable jnp (a ``vmap`` of the single-run reductions,
        so each member's pass stays shard-local on a member-sharded
        mesh), callable inside any jitted ensemble step. ``aux`` leaves
        must be batched to the member axis too (or the dict empty)."""
        if aux:
            return jax.vmap(self.compute)(states, aux)
        return jax.vmap(lambda st: self.compute(st, {}))(states)

    def decode_members(self, matrix):
        """Host decode of a ``(members, size)`` health matrix — one
        :meth:`decode` dict per row. The single device->host transfer
        for a matured ensemble health check."""
        m = np.asarray(matrix)
        if m.ndim != 2 or m.shape[1] != self.size:
            raise ValueError(
                f"ensemble health matrix has shape {m.shape}; schema "
                f"v{HEALTH_SCHEMA_VERSION} for this sentinel needs "
                f"(members, {self.size})")
        return [self.decode(row) for row in m]

    # -- host-side decode and checks ----------------------------------------

    def decode(self, vector):
        """Device vector (or numpy array) -> ``{"fields": {name:
        {"finite": bool, "max_abs": float, "rms": float}}, "invariants":
        {name: float}}``. This is the one device->host transfer; on a
        matured vector the computation retired long ago, so it does not
        stall the pipeline."""
        v = np.asarray(vector)
        if v.shape != (self.size,):
            raise ValueError(f"health vector has shape {v.shape}; "
                             f"schema v{HEALTH_SCHEMA_VERSION} for this "
                             f"sentinel needs ({self.size},)")
        ns = len(FIELD_STATS)
        fields = {}
        for i, name in enumerate(self.fields):
            fin, mx, rms = (float(v[ns * i + j]) for j in range(ns))
            fields[name] = {"finite": bool(fin == 1.0), "max_abs": mx,
                            "rms": rms}
        base = ns * len(self.fields)
        invariants = {name: float(v[base + i])
                      for i, name in enumerate(self.invariants)}
        return {"fields": fields, "invariants": invariants}

    def problems(self, decoded, max_abs=None, invariant_bounds=None):
        """Health-check a decoded vector: returns ``(bad_names,
        descriptions)`` — non-finite fields, fields over the ``max_abs``
        magnitude bound, and invariants outside their declared
        ``invariant_bounds`` ``{name: (lo, hi)}`` (either bound may be
        ``None``). Empty lists mean healthy."""
        bad, why = [], []
        for name, st in decoded["fields"].items():
            if not st["finite"]:
                bad.append(name)
                why.append(f"{name}: non-finite values "
                           f"(max_abs={st['max_abs']})")
            elif max_abs is not None and st["max_abs"] > max_abs:
                bad.append(name)
                why.append(f"{name}: |max| {st['max_abs']:.6g} exceeds "
                           f"bound {max_abs:.6g}")
        for name, val in decoded["invariants"].items():
            if not np.isfinite(val):
                bad.append(name)
                why.append(f"invariant {name}: non-finite ({val})")
                continue
            lo, hi = (invariant_bounds or {}).get(name, (None, None))
            if (lo is not None and val < lo) or \
                    (hi is not None and val > hi):
                bad.append(name)
                why.append(f"invariant {name}: {val:.6g} outside "
                           f"bounds ({lo}, {hi})")
        return bad, why


class SentinelMonitor:
    """Asynchronous consumer of per-step health vectors.

    The driver calls :meth:`observe` (compute + enqueue, one tiny
    dispatch, no sync) or :meth:`push` (enqueue a vector an in-graph
    step already produced — ``Stepper.step_with_health`` /
    ``multi_step(..., sentinel=...)``) once per step/chunk, then
    :meth:`poll`. A poll converts only vectors at least ``every`` steps
    behind the newest push, so the driver loop always runs ``>= every``
    steps ahead of any host transfer; :meth:`flush` drains everything.

    :arg sentinel: the :class:`Sentinel` that produced the vectors.
    :arg every: minimum step lag before a vector is host-converted.
    :arg history: ring-buffer capacity of decoded vectors (the forensic
        bundle's last-K history).
    :arg max_abs: optional per-field magnitude bound.
    :arg invariant_bounds: optional ``{name: (lo, hi)}`` invariant
        bounds; leaving them triggers the same trip path as a NaN.
    :arg emit_steps: emit one ``health`` run event per checked vector
        (the smoke bench does; leave off for chatty-averse runs —
        drivers can emit coarser ``health`` events themselves).
    :arg forensics: optional
        :class:`~pystella_tpu.obs.forensics.ForensicSink`; on a trip it
        receives the ring-buffer history before
        :class:`SimulationDiverged` is raised.
    :arg metrics_prefix: prefix for this monitor's metric names. The
        defaults — the ``sentinel`` timer and ``health_checks`` counter
        — feed the ledger's ``numerics`` section (sentinel overhead %
        of step time), so an AUXILIARY monitor running beside the main
        one (e.g. the resilience supervisor's) must use its own names
        (``"supervised"`` -> ``supervised_sentinel`` /
        ``supervised_health_checks``) to keep that section honest,
        exactly like the ensemble tier's ``ensemble_sentinel``.
    """

    def __init__(self, sentinel, every=50, history=64, max_abs=None,
                 invariant_bounds=None, emit_steps=False, label="",
                 forensics=None, metrics_prefix=""):
        self.sentinel = sentinel
        self.every = int(every)
        self.max_abs = max_abs
        self.invariant_bounds = dict(invariant_bounds or {})
        self.emit_steps = bool(emit_steps)
        self.label = label
        self.forensics = forensics
        prefix = f"{metrics_prefix}_" if metrics_prefix else ""
        self._timer_name = prefix + "sentinel"
        self._counter_name = prefix + "health_checks"
        self._pending = collections.deque()   # (step, device vector)
        self.history = collections.deque(maxlen=int(history))
        #: newest step pushed (None before the first push)
        self.newest_step = None
        #: highest step actually health-checked (None before the first)
        self.checked_through = None

    @property
    def pending_steps(self):
        """Steps enqueued but not yet host-checked (newest last)."""
        return [s for s, _ in self._pending]

    def observe(self, step, state, aux=None):
        """Compute the health vector of ``state`` (one tiny jitted
        dispatch, NO host sync) and enqueue it for ``step``."""
        with _metrics.timer(self._timer_name):
            self.push(step, self.sentinel.compute_jit(state, aux))

    def push(self, step, vector):
        """Enqueue a health vector an in-graph step already produced."""
        step = int(step)
        self._pending.append((step, vector))
        self.newest_step = step

    def poll(self):
        """Check every pending vector at least ``every`` steps behind
        the newest push; younger vectors are never touched, so the
        device queue stays ``>= every`` steps ahead of the host.
        Returns the number of vectors checked; raises
        :class:`SimulationDiverged` on the first unhealthy one."""
        n = 0
        while (self._pending and self.newest_step is not None
                and self._pending[0][0] <= self.newest_step
                - self.every):
            self._check_one(*self._pending.popleft())
            n += 1
        return n

    def flush(self):
        """Drain the queue unconditionally (end of run, or immediately
        before trusting the current state — e.g. a checkpoint save).
        Returns the number of vectors checked."""
        n = 0
        while self._pending:
            self._check_one(*self._pending.popleft())
            n += 1
        return n

    def discard(self):
        """Drop every pending (unchecked) vector WITHOUT checking it —
        the recovery path: after a fault rolls the run back, the queue
        describes the corrupted trajectory about to be replayed, and
        checking it would re-trip on history. Returns the number of
        vectors dropped."""
        n = len(self._pending)
        self._pending.clear()
        return n

    def check_sync(self, step, state, aux=None):
        """Synchronous one-off check of ``state`` at ``step`` (the
        legacy :class:`~pystella_tpu.HealthMonitor` contract; does not
        disturb the async queue). Raises on failure, returns the
        decoded vector otherwise."""
        with _metrics.timer(self._timer_name):
            vector = self.sentinel.compute_jit(state, aux)
        return self._check_one(int(step), vector)

    def _check_one(self, step, vector):
        # the "sentinel" timer covers the sentinel machinery (decode —
        # the one host transfer — plus the checks); event-log JSONL
        # writes are I/O of the telemetry sink, not sentinel cost, and
        # stay outside it like every other event emission
        with _metrics.timer(self._timer_name):
            decoded = self.sentinel.decode(vector)
            bad, why = self.sentinel.problems(
                decoded, max_abs=self.max_abs,
                invariant_bounds=self.invariant_bounds)
        self.checked_through = (step if self.checked_through is None
                                else max(self.checked_through, step))
        _metrics.counter(self._counter_name).inc()
        self.history.append({"step": step, **decoded})
        if self.emit_steps:
            _events.emit("health", step=step, label=self.label, **decoded)
        if bad:
            # the forensic record a checkpointed run resumes from:
            # which fields/invariants went bad, and exactly when —
            # written BEFORE the raise so it survives an unhandled crash
            offending = next((n for n in bad
                              if n in self.sentinel.invariants), None)
            _events.emit("diverged", step=step, fields=bad,
                         max_abs=self.max_abs, problems=why,
                         offending_invariant=offending, label=self.label)
            if self.forensics is not None:
                self.forensics.write(
                    step=step, reason="; ".join(why), bad_fields=bad,
                    offending_invariant=offending,
                    history=list(self.history))
            raise SimulationDiverged(step, bad, why)
        return decoded
