"""The scenario service: a persistent, multi-tenant simulation server.

The composition layer over the batch-era subsystems — request ingestion
and fair-share scheduling (:mod:`~pystella_tpu.service.queue`),
warm-pool admission keyed on program fingerprints
(:mod:`~pystella_tpu.service.admission`), the supervised lease loop
over batched populations (:mod:`~pystella_tpu.service.server`),
retire-time streamed analytics (:mod:`~pystella_tpu.service.results`),
and the seeded synthetic load generator
(:mod:`~pystella_tpu.service.loadgen`). Every request carries a
schema-v2 trace id end to end (kept across preempt → requeue), so
:mod:`pystella_tpu.obs.spans` can attribute its latency phase by
phase, and retire time records the deadline verdict (``margin_s``,
``deadline_missed``). ``python -m pystella_tpu.service status``
reconstructs queue depth / occupancy / leases / last retired requests
from the event-log family alone. ``doc/service.md`` documents the
request lifecycle, the scheduling policy knobs, the warm-pool
admission contract, the SLO table, and how to read the report's
``service`` and ``latency`` sections.
"""

from pystella_tpu.service.admission import (
    AdmissionController, AdmissionVerdict, ColdSignature, WarmPool,
    WarmPoolEntry, parse_signature, request_signature)
from pystella_tpu.service.queue import (
    FairShareScheduler, QuotaExceeded, ScenarioRequest)
from pystella_tpu.service.results import ResultEmitter
from pystella_tpu.service.server import ScenarioService
from pystella_tpu.service import loadgen

__all__ = [
    "AdmissionController", "AdmissionVerdict", "ColdSignature",
    "FairShareScheduler", "QuotaExceeded", "ResultEmitter",
    "ScenarioRequest", "ScenarioService", "WarmPool", "WarmPoolEntry",
    "loadgen", "parse_signature", "request_signature",
]
