"""Service ops CLI: reconstruct server state from the event-log family.

::

    python -m pystella_tpu.service status --events run_events.jsonl \
        [--last 10] [--json]
    python -m pystella_tpu.service usage --events run_events.jsonl \
        [--last 10] [--json]

No live server handle required: the scenario service's whole decision
record is its event log (``service_request`` / ``service_dispatch`` /
``service_requeue`` / ``member_result`` / ...), so an operator can ask
"what is the queue depth, who holds the leases, what retired last?"
of a running — or dead — service by replaying the log. Rotated
families (``PYSTELLA_EVENT_ROTATE_MB``) are read whole, oldest first,
exactly like the perf ledger ingests them, and the reconstruction is
scoped to the latest serve loop — everything after the PREVIOUS
loop's ``service_done`` — so a reused log reports the current loop
(including its pre-``serve()`` submissions, which precede the
``service_start`` marker), not a mix of runs.

Retired rows carry each request's trace id (obs schema v2), so the
next hop from "request 7 was slow" is
``python -m pystella_tpu.obs.spans --events <log> --trace <id>``.

``status --follow`` is the live tail: when the registered
``PYSTELLA_LIVE_PORT`` (or ``--url``) names a live telemetry endpoint
(:mod:`pystella_tpu.obs.live`), each tick polls ``/healthz`` + ``/slo``
and prints one line of serve-loop state and SLO burn; when no endpoint
is reachable it falls back to re-reading the rotated event-log family
per tick — the offline reconstruction, repeated — so the same command
tails a live server, a server without the live plane, and a dead one.

``status --fleet`` widens the view from one replica to the whole
fleet: each tick reads the replica registry (``--fleet-dir`` or the
registered ``PYSTELLA_FLEET_DIR``), classifies every record
live/stale/withdrawn by heartbeat age, and polls each live replica's
own endpoint for one serve-loop + SLO line — a per-replica table of
everything currently announced. Combine with ``--follow`` to tail it.

``usage`` is the chargeback view over the SAME reconstruction: the
per-tenant chip-second accounts the capacity monitor
(:mod:`pystella_tpu.obs.capacity`) attributed at serve-loop retire —
chip-seconds leased, waste (replay + preempt-drain), committed
member-steps, goodput — plus every ``CapacityExceeded`` rejection
(never admitted, billed zero). ``status`` (non-follow) additionally
prints one live HBM-headroom line when ``--url`` or the registered
``PYSTELLA_LIVE_PORT`` names a reachable endpoint.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events

__all__ = ["fleet_lines", "follow_line", "reconstruct", "main"]


def reconstruct(events_path):
    """Replay the event-log family into the service's current state:
    ``{queue: [...], queue_depth, tenants: {tenant: {...}}, leases:
    {active, completed, failed}, armed: [...], retired: [...],
    done: {...}, capacity: {accounts, usage, rejects}}``. Pure
    function of the log — ONE reader drives ``status``, ``usage``,
    and the tests (the chargeback view is the same replay, rendered
    from its ``capacity`` key)."""
    all_evs = _events.read_events(events_path, include_rotated=True)
    # deploy-time arming happens BEFORE serve() emits service_start,
    # so the armed-signature list reads the whole log; everything else
    # scopes to the latest serve loop — cut at the END of the PREVIOUS
    # loop (service_done), not at service_start: submissions precede
    # serve() (submit() emits service_request at submit time), and
    # slicing at the start marker would report a mid-run queue as
    # empty, which is exactly the question this view exists to answer
    all_arms = [ev for ev in all_evs
                if ev.get("kind") == "service_arm"]
    evs = all_evs
    starts = [i for i, ev in enumerate(evs)
              if ev.get("kind") == "service_start"]
    if starts:
        dones_before = [i for i in range(starts[-1])
                        if evs[i].get("kind") == "service_done"]
        if dones_before:
            evs = evs[dones_before[-1] + 1:]
    requests = {}      # id -> live request row
    active_leases = {}  # lease id -> row
    completed_leases = []
    failed_leases = []
    armed = []
    retired = []
    tenants = {}
    done = None
    capacity_accounts = []
    capacity_rejects = []
    capacity_usage = None

    def req(rid):
        return requests.setdefault(rid, {"id": rid, "status": "?"})

    def tenant(name):
        return tenants.setdefault(
            str(name), {"queued": 0, "running": 0, "retired": 0,
                        "member_steps": 0})

    for ev in all_arms:
        data = ev.get("data") or {}
        armed.append({"signature": data.get("signature"),
                      "fingerprint": data.get("fingerprint"),
                      "ts": ev.get("ts")})
    for ev in evs:
        kind = ev.get("kind")
        data = ev.get("data") or {}
        rid = data.get("id")
        if kind == "service_request":
            row = req(rid)
            row.update(tenant=data.get("tenant"),
                       signature=data.get("signature"),
                       priority=data.get("priority"),
                       deadline_s=data.get("deadline_s"),
                       submit_ts=ev.get("ts"),
                       trace=ev.get("trace"), status="queued")
        elif kind == "service_reject":
            req(rid).update(status="rejected",
                            reason=data.get("reason"))
        elif kind == "service_dispatch":
            row = req(rid)
            row.update(status="running", lease=data.get("lease"),
                       queue_latency_s=data.get("queue_latency_s"))
            lease = active_leases.setdefault(
                data.get("lease"), {"lease": data.get("lease"),
                                    "requests": [], "since_ts":
                                    ev.get("ts")})
            lease["requests"].append(rid)
        elif kind == "service_requeue":
            req(rid).update(status="queued", lease=None,
                            resumed_steps=data.get("steps_done"))
        elif kind == "service_lease":
            lid = data.get("lease")
            row = active_leases.pop(lid, {"lease": lid, "requests": []})
            row.update(warm=data.get("warm"), chunks=data.get("chunks"),
                       preempted=data.get("preempted"),
                       wall_s=data.get("wall_s"))
            completed_leases.append(row)
            for t, steps in (data.get("tenant_steps") or {}).items():
                tenant(t)["member_steps"] += int(steps)
        elif kind == "service_lease_failed":
            lid = data.get("lease")
            row = active_leases.pop(lid, {"lease": lid, "requests": []})
            row["error"] = data.get("error")
            failed_leases.append(row)
        elif kind == "member_result":
            row = req(rid)
            row.update(status=data.get("status"), lease=None)
            retired.append({"id": rid, "tenant": data.get("tenant"),
                            "status": data.get("status"),
                            "trace": ev.get("trace"),
                            "margin_s": data.get("margin_s"),
                            "deadline_missed":
                                data.get("deadline_missed"),
                            "retire_ts": ev.get("ts")})
        elif kind == "capacity_account":
            capacity_accounts.append(dict(data))
        elif kind == "capacity_reject":
            capacity_rejects.append(dict(data))
        elif kind == "capacity_usage":
            capacity_usage = dict(data)
        elif kind == "service_done":
            done = data
    queue = [r for r in requests.values() if r.get("status") == "queued"]
    for r in requests.values():
        status = r.get("status")
        if status in ("queued", "running") and r.get("tenant"):
            tenant(r["tenant"])[status] += 1
    for row in retired:
        if row.get("tenant"):
            tenant(row["tenant"])["retired"] += 1
    queue.sort(key=lambda r: (-(r.get("priority") or 0),
                              r.get("submit_ts") or 0.0))
    return {
        "queue": queue,
        "queue_depth": len(queue),
        "tenants": tenants,
        "leases": {"active": sorted(active_leases.values(),
                                    key=lambda r: r.get("lease") or 0),
                   "completed": len(completed_leases),
                   "failed": len(failed_leases)},
        "armed": armed,
        "retired": retired,
        "done": done,
        "capacity": {"accounts": capacity_accounts,
                     "usage": capacity_usage,
                     "rejects": capacity_rejects},
    }


def _render(state, last):
    lines = []
    depth = state["queue_depth"]
    leases = state["leases"]
    lines.append(
        f"queue depth {depth} · {len(leases['active'])} active "
        f"lease(s) · {leases['completed']} completed, "
        f"{leases['failed']} failed · "
        f"{len(state['armed'])} armed signature(s)"
        + (" · serve loop FINISHED" if state["done"] else ""))
    if state["armed"]:
        lines.append("armed: " + ", ".join(
            str(a["signature"]) for a in state["armed"]))
    for row in state["queue"][:last]:
        lines.append(
            f"  queued  #{row['id']} {row.get('tenant')} "
            f"p{row.get('priority')} {row.get('signature')}"
            + (f" (resumed at step {row['resumed_steps']})"
               if row.get("resumed_steps") else ""))
    for lease in state["leases"]["active"]:
        lines.append(
            f"  lease {lease.get('lease')} ACTIVE: request(s) "
            f"{lease.get('requests')}")
    if state["tenants"]:
        lines.append("tenants:")
        for name, row in sorted(state["tenants"].items()):
            lines.append(
                f"  {name}: {row['queued']} queued, {row['running']} "
                f"running, {row['retired']} retired, "
                f"{row['member_steps']} member-step(s) served")
    if state["retired"]:
        lines.append(f"last {min(last, len(state['retired']))} "
                     "retired:")
        for row in state["retired"][-last:]:
            margin = row.get("margin_s")
            lines.append(
                f"  #{row['id']} {row.get('tenant')} "
                f"{row.get('status')}"
                + (f" margin {margin:+.3f}s"
                   + (" MISSED" if row.get("deadline_missed") else "")
                   if isinstance(margin, (int, float)) else "")
                + (f" trace {row.get('trace')}"
                   if row.get("trace") else ""))
    return "\n".join(lines)


def _fmt_bytes(n):
    if not isinstance(n, (int, float)):
        return "?"
    return f"{n / 2**20:.1f} MiB"


def _render_usage(state, last):
    """The chargeback view: per-tenant chip-second accounts and
    goodput, rendered from the SAME reconstruction ``status`` uses
    (its ``capacity`` key — one events-family reader, two views)."""
    cap = state.get("capacity") or {}
    usage = cap.get("usage")
    accounts = cap.get("accounts") or []
    rejects = cap.get("rejects") or []
    lines = []
    if not usage and not accounts:
        lines.append(
            "no chip-second accounts in this log — usage is "
            "attributed at serve-loop retire (capacity_usage); the "
            "loop may still be running, or the capacity monitor was "
            "disabled (ScenarioService(capacity=False))")
        if rejects:
            lines.append(f"{len(rejects)} CapacityExceeded "
                         "rejection(s) recorded:")
            for r in rejects[-last:]:
                lines.append(
                    f"  #{r.get('id')} {r.get('tenant')} "
                    f"{r.get('signature')}: {r.get('reason')}")
        return "\n".join(lines)
    if usage:
        goodput = usage.get("goodput")
        lines.append(
            f"{usage.get('requests')} attributed request(s) · "
            f"{usage.get('total_chip_s')} chip-s leased · "
            f"{usage.get('committed_steps')} committed member-step(s)"
            f" · waste {usage.get('waste_chip_s')} chip-s · goodput "
            + (f"{goodput:g} steps/chip-s"
               if isinstance(goodput, (int, float)) else "—"))
        cov = usage.get("coverage") or {}
        if cov.get("predicted_only"):
            lines.append("coverage: PREDICTED-ONLY (no live "
                         "watermark samples on this host)")
        tenants = usage.get("tenants") or {}
        if tenants:
            lines.append("tenant          req  rej  chip-s    waste"
                         "     steps   goodput")
            for name, row in sorted(tenants.items()):
                g = row.get("goodput")
                lines.append(
                    f"{name:<15s} {row.get('requests', 0):>4d} "
                    f"{row.get('rejected', 0):>4d} "
                    f"{row.get('chip_s', 0.0):>8.3f} "
                    f"{row.get('waste_chip_s', 0.0):>8.3f} "
                    f"{row.get('committed_steps', 0):>8d}   "
                    + (f"{g:g}" if isinstance(g, (int, float))
                       else "—"))
    if rejects:
        lines.append(f"{len(rejects)} CapacityExceeded rejection(s) — "
                     "never admitted, zero chip-seconds billed:")
        for r in rejects[-last:]:
            lines.append(
                f"  #{r.get('id')} {r.get('tenant')} "
                f"{r.get('signature')}: predicted "
                f"{_fmt_bytes(r.get('predicted_bytes'))} vs budget "
                f"{_fmt_bytes(r.get('budget_bytes'))}")
    if accounts:
        lines.append(f"last {min(last, len(accounts))} account(s):")
        for a in accounts[-last:]:
            g = a.get("goodput")
            lines.append(
                f"  #{a.get('id')} {a.get('tenant')} "
                f"{a.get('status')}: {a.get('chip_s')} chip-s over "
                f"{a.get('leases')} lease(s), "
                f"{a.get('committed_steps')} step(s)"
                + (f", goodput {g:g}"
                   if isinstance(g, (int, float)) else "")
                + (f", {a.get('replayed_steps')} replayed"
                   if a.get("replayed_steps") else ""))
    return "\n".join(lines)


def _headroom_line(cap):
    """One line of live HBM headroom from ``/healthz``'s ``capacity``
    field (:meth:`CapacityMonitor.live_fields`)."""
    if not cap:
        return ("live capacity: no monitor attached "
                "(ScenarioService(capacity=False))")
    limit = cap.get("capacity_bytes")
    frac = cap.get("headroom_frac")
    line = (f"live capacity: resident predicted "
            f"{_fmt_bytes(cap.get('resident_predicted_bytes'))}"
            + (f" · in use {_fmt_bytes(cap['bytes_in_use'])} (peak "
               f"{_fmt_bytes(cap.get('peak_bytes_in_use'))})"
               if isinstance(cap.get("bytes_in_use"), (int, float))
               else " · no live watermarks (predicted-only host)"))
    if limit:
        line += (f" · budget {_fmt_bytes(limit)} × "
                 f"{cap.get('headroom')}"
                 + (f" · {frac:.0%} of budget used"
                    if isinstance(frac, (int, float)) else ""))
    else:
        line += " · no capacity limit configured"
    return line


def _live_poll(base_url, timeout=2.0):
    """One poll of a live telemetry endpoint: ``(healthz, slo)`` dicts,
    or ``None`` when it is unreachable (the caller falls back to the
    offline reconstruction)."""
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(base_url + "/healthz",
                                    timeout=timeout) as r:
            healthz = json.loads(r.read().decode())
        with urllib.request.urlopen(base_url + "/slo",
                                    timeout=timeout) as r:
            slo = json.loads(r.read().decode())
    except (urllib.error.URLError, OSError, ValueError):
        return None
    return healthz, slo


def follow_line(healthz, slo):
    """One ``--follow`` tick rendered from a live poll."""
    burning = slo.get("alerting") or []
    return (
        f"live: {'SERVING' if healthz.get('serving') else 'idle'} · "
        f"queue {healthz.get('queue_depth')} · lease "
        f"{healthz.get('active_lease') if healthz.get('active_lease') is not None else '—'}"
        f" · {healthz.get('leases_completed')} lease(s) done · slo "
        + (f"BURNING [{', '.join(burning)}]" if burning
           else ("ok" if slo.get("enabled") else "off")))


def fleet_lines(fleet_dir, expire_s=None, poll=_live_poll):
    """One fleet-status tick: read the replica registry, classify every
    record by heartbeat age, and poll each LIVE replica's own endpoint
    for its serve-loop + SLO line. Pure function of the registry plus
    ``poll`` (injectable for tests); returns the rendered lines."""
    from pystella_tpu.service import registry as _registry
    recs = _registry.read_records(fleet_dir, expire_s=expire_s)
    if not recs:
        return [f"fleet: no replica records under {fleet_dir}"]
    live = sum(1 for r in recs if r.get("status") == "live")
    lines = [f"fleet: {live}/{len(recs)} replica(s) live "
             f"({fleet_dir})"]
    for rec in sorted(recs, key=lambda r: str(r.get("replica"))):
        status = rec.get("status")
        age = rec.get("age_s")
        line = (f"  {rec.get('replica')} [{status}]"
                + (f" age {age:.1f}s" if isinstance(age, (int, float))
                   else ""))
        url = rec.get("url")
        if status == "live" and url:
            polled = poll(url)
            line += (" · endpoint UNREACHABLE" if polled is None
                     else " · " + follow_line(*polled))
        elif url:
            line += f" · {url}"
        lines.append(line)
    return lines


def _offline_line(events_path):
    state = reconstruct(events_path)
    leases = state["leases"]
    return (f"offline: queue {state['queue_depth']} · "
            f"{len(leases['active'])} active lease(s) · "
            f"{leases['completed']} completed, {leases['failed']} "
            f"failed · {len(state['retired'])} retired"
            + (" · serve loop FINISHED" if state["done"] else ""))


def _follow(events_path, url, interval, count, fleet_dir=None):
    """The live-tail loop: poll the endpoint when one is configured
    (falling back per tick when it is unreachable — the server may not
    be up yet, or just went down), else re-read the event-log family.
    With ``fleet_dir`` each tick renders the per-replica fleet table
    instead of the single-endpoint line. ``count`` bounds the ticks
    (0 = forever)."""
    ticks = 0
    while True:
        if fleet_dir:
            line = "\n".join(fleet_lines(fleet_dir))
        else:
            line = None
            if url:
                polled = _live_poll(url)
                if polled is not None:
                    line = follow_line(*polled)
            if line is None:
                if not events_path:
                    print("service status --follow: live endpoint "
                          "unreachable and no --events/"
                          "PYSTELLA_EVENT_LOG to fall back to",
                          file=sys.stderr)
                    return 2
                line = _offline_line(events_path)
        print(time.strftime("%H:%M:%S") + " " + line, flush=True)
        ticks += 1
        if count and ticks >= count:
            return 0
        time.sleep(max(0.0, interval))


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m pystella_tpu.service",
        description="scenario-service ops tools (offline "
                    "reconstruction from the event-log family, or a "
                    "live tail against the PYSTELLA_LIVE_PORT "
                    "endpoint)")
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser(
        "status", help="queue depth, tenant occupancy, active leases, "
                       "and the last retired requests (with trace ids)")
    ps.add_argument("--events", default=None,
                    help="run-event JSONL path (default: the registered "
                         "PYSTELLA_EVENT_LOG)")
    ps.add_argument("--last", type=int, default=10,
                    help="retired/queued rows to show (default 10)")
    ps.add_argument("--json", action="store_true",
                    help="print the raw reconstruction instead of the "
                         "rendered view")
    ps.add_argument("--follow", action="store_true",
                    help="live tail: poll the PYSTELLA_LIVE_PORT "
                         "endpoint (/healthz + /slo) each tick, "
                         "falling back to re-reading the event-log "
                         "family when no endpoint answers")
    ps.add_argument("--url", default=None,
                    help="live endpoint base URL override (default "
                         "http://127.0.0.1:$PYSTELLA_LIVE_PORT when "
                         "the port is set)")
    ps.add_argument("--interval", type=float, default=2.0,
                    help="--follow poll interval in seconds (default 2)")
    ps.add_argument("--count", type=int, default=0,
                    help="--follow tick budget, 0 = follow forever "
                         "(default)")
    ps.add_argument("--fleet", action="store_true",
                    help="fleet view: read the replica registry "
                         "(--fleet-dir or PYSTELLA_FLEET_DIR), "
                         "classify every record live/stale/withdrawn "
                         "by heartbeat age, and poll each live "
                         "replica's own endpoint — one row per "
                         "replica; combine with --follow to tail it")
    ps.add_argument("--fleet-dir", default=None,
                    help="replica registry directory (default: the "
                         "registered PYSTELLA_FLEET_DIR)")
    pu = sub.add_parser(
        "usage", help="per-tenant chip-second chargeback: leased "
                      "chip-seconds, waste (replay + drain), "
                      "committed member-steps, and goodput per "
                      "tenant — plus every CapacityExceeded "
                      "rejection (billed zero)")
    pu.add_argument("--events", default=None,
                    help="run-event JSONL path (default: the registered "
                         "PYSTELLA_EVENT_LOG)")
    pu.add_argument("--last", type=int, default=10,
                    help="account/rejection rows to show (default 10)")
    pu.add_argument("--json", action="store_true",
                    help="print the raw capacity reconstruction "
                         "(accounts + usage rollup + rejects) instead "
                         "of the rendered table")
    args = p.parse_args(argv)

    events_path = args.events or _config.getenv("PYSTELLA_EVENT_LOG")
    if args.cmd == "usage":
        if not events_path:
            print("service usage: no --events and no "
                  "PYSTELLA_EVENT_LOG set", file=sys.stderr)
            return 2
        state = reconstruct(events_path)
        if args.json:
            print(json.dumps(state["capacity"], indent=1,
                             sort_keys=True, default=str))
        else:
            print(_render_usage(state, max(1, args.last)))
        return 0
    fleet_dir = None
    if args.fleet or args.fleet_dir:
        fleet_dir = args.fleet_dir or _config.getenv("PYSTELLA_FLEET_DIR")
        if not fleet_dir:
            print("service status --fleet: no --fleet-dir and no "
                  "PYSTELLA_FLEET_DIR set", file=sys.stderr)
            return 2
    if args.follow:
        url = args.url
        if url is None:
            port = _config.get_int("PYSTELLA_LIVE_PORT") or 0
            url = f"http://127.0.0.1:{port}" if port > 0 else None
        return _follow(events_path, url, args.interval, args.count,
                       fleet_dir=fleet_dir)
    if fleet_dir:
        print("\n".join(fleet_lines(fleet_dir)))
        return 0
    if not events_path:
        print("service status: no --events and no PYSTELLA_EVENT_LOG "
              "set", file=sys.stderr)
        return 2
    state = reconstruct(events_path)
    if args.json:
        print(json.dumps(state, indent=1, sort_keys=True, default=str))
    else:
        print(_render(state, max(1, args.last)))
        # a reachable live endpoint upgrades the offline view with the
        # CURRENT HBM headroom (the log only carries retired usage)
        url = args.url
        if url is None:
            port = _config.get_int("PYSTELLA_LIVE_PORT") or 0
            url = f"http://127.0.0.1:{port}" if port > 0 else None
        if url:
            polled = _live_poll(url)
            if polled is not None:
                print(_headroom_line(polled[0].get("capacity")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
