"""The long-lived scenario service: multi-tenant populations, served.

:class:`ScenarioService` is the composition the ROADMAP's production
north-star calls for — the one-shot batch drivers become a persistent
server:

- **ingestion** — :class:`~pystella_tpu.service.queue.ScenarioRequest`
  submissions flow through admission control
  (:mod:`pystella_tpu.service.admission`: warm-pool hit keyed on the
  PR-6 program fingerprints, or the cold-signature policy) into the
  :class:`~pystella_tpu.service.queue.FairShareScheduler` (weighted
  deficit across tenants, priority classes, per-tenant quotas,
  deadline-aware ordering).
- **leases** — each scheduler dispatch leases up to ``slots``
  shape-compatible requests to one batched population: a
  fixed-membership :class:`~pystella_tpu.ensemble.EnsembleStepper`
  group (the ensemble engine's execution tier; the scheduler itself
  plays the refill role the
  :class:`~pystella_tpu.ensemble.EnsembleDriver` queue plays in batch
  runs, and the driver's :meth:`~pystella_tpu.ensemble.EnsembleDriver.
  requeue`/drain primitives are the same contract one level down).
  A pool entry may own a mesh slice (``arm(decomp=)``) — that slice is
  what the lease occupies.
- **supervision** — every lease runs under the PR-8
  :class:`~pystella_tpu.resilience.Supervisor`: chunk-boundary
  checkpoints with the schedule/finalize durability split, device-loss
  triage with restore-from-last-good and bounded replay (work lost to
  replay is accounted per lease), and the preemption drain. A pending
  request of a strictly higher priority class triggers
  ``request_preemption()``; the supervisor drains at the next chunk
  boundary — durable checkpoint, clean return — and the service
  requeues every unfinished request WITH its restored member state, so
  preemption loses no work and the resumed trajectory is
  bit-consistent with an uninterrupted run. A ``planner_factory``
  hooks the PR-11 :class:`~pystella_tpu.resilience.RemeshPlanner` in
  per lease, so device loss on a leased mesh slice degrades instead of
  killing the service; and a lease whose recovery fails is itself
  contained — its requests requeue and the service keeps serving.
- **results** — members retire through the
  :class:`~pystella_tpu.service.results.ResultEmitter`: per-member
  reductions and spectra summaries streamed as ``member_result``
  events, never full field states.
- **telemetry** — every decision is an event (``service_request`` /
  ``service_admit`` / ``service_reject`` / ``service_dispatch`` /
  ``service_lease`` / ``service_preempted`` / ``service_requeue`` /
  ``member_result`` / ``service_done``); the perf ledger's ``service``
  report section and the gate's SLO verdicts (queue-p95, warm TTFS,
  warm-over-mismatched-fingerprints refusal) ingest exactly these
  (``doc/service.md``).

The warm-path latency contract is measurable, not aspirational: each
lease dispatch runs under a :class:`~pystella_tpu.obs.memory.
compile_watch`, and a warm lease records ``backend_compiles == 0`` and
``trace_s == 0.0`` — request latency is dispatch, never compile.
"""

from __future__ import annotations

import os
import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import memory as _memory
from pystella_tpu.obs import metrics as _metrics
from pystella_tpu.service.admission import (
    AdmissionController, WarmPool, parse_signature)
from pystella_tpu.service.queue import FairShareScheduler, QuotaExceeded
from pystella_tpu.service.results import ResultEmitter

__all__ = ["ScenarioService"]


def _ceil_div(a, b):
    return -(-int(a) // int(b))


class _Lease:
    """One dispatched batch: fixed membership, supervised chunk loop.

    Member ``m`` of the batch carries request ``m`` for
    ``m < len(requests)``; the remaining slots step the template state
    as masked ballast (the batch shape is the armed program's). All
    host bookkeeping inside :meth:`step_fn` is a pure function of the
    chunk index — a supervisor replay after a fault recomputes it
    bit-identically instead of double-counting."""

    def __init__(self, service, entry, requests, lease_id, t_origin,
                 cold_build_s=0.0):
        import numpy as np
        from pystella_tpu.ensemble import EnsembleMonitor

        self.service = service
        self.entry = entry
        self.requests = list(requests)
        self.id = int(lease_id)
        self.t_origin = float(t_origin)
        self.cold_build_s = float(cold_build_s)
        self.priority = max(r.priority for r in self.requests)
        self.chunk = service.chunk
        size = entry.ens.size
        self.monitor = EnsembleMonitor(
            entry.sentinel, size, every=1,
            label=f"{service.label}.lease{self.id}",
            max_evictions=size)
        # the tick dtype keeps the chunk SELF-COMPOSING: f64 columns
        # would promote an f32 state inside the RK update under x64,
        # and the next chunk's dispatch would re-trace the warm
        # program (see WarmPoolEntry.tick_dtype)
        td = entry.tick_dtype
        self.start_steps = np.zeros(size, dtype=np.int64)
        self.dt_vec = np.full(size, entry.dt, dtype=td)
        self.params = {n: np.zeros(size, dtype=td)
                       for n in entry.param_names}
        self.finish_chunks = {}
        states = []
        for m, req in enumerate(self.requests):
            if req.resume_state is not None:
                state, draw = req.resume_state, dict(req.params_draw
                                                     or {})
            else:
                state, draw = entry.sample(req.seed)
                req.params_draw = dict(draw or {})
            states.append(state)
            self.start_steps[m] = int(req.resume_step)
            for n in self.params:
                self.params[n][m] = float((draw or {}).get(n, 0.0))
            self.finish_chunks[m] = _ceil_div(
                max(req.remaining_steps, 1), self.chunk)
            self.monitor.set_member(m, params={**(draw or {}),
                                               "seed": req.seed},
                                    scenario=req.signature)
        template_state, template_draw = entry.template
        for m in range(len(self.requests), size):
            states.append(template_state)
            self.monitor.mask_member(m)
            for n in self.params:
                self.params[n][m] = float(
                    (template_draw or {}).get(n, 0.0))
        self.batch0 = entry.stack(states)
        self.n_chunks = max(self.finish_chunks.values())
        self.finished = {}     # member -> host state
        self.diverged = {}     # member -> Eviction
        self.ttfs_s = None
        self.supervisor = None
        self._counted_chunks = 0

    # -- the supervised chunk ------------------------------------------------

    def step_fn(self, batch, i):
        """One supervised step == one batched chunk dispatch."""
        import jax

        # only a NEW chunk advances the service clock: a supervisor
        # REPLAY after a fault re-runs chunk indices the service
        # already counted, and re-counting them would fire scheduled
        # arrivals early and trigger preemption mid-recovery (the
        # lease contract: host bookkeeping is a pure function of i)
        if i >= self._counted_chunks:
            self._counted_chunks = i + 1
            self.service._on_chunk(self)
        entry = self.entry
        t_vec = ((self.start_steps + i * self.chunk)
                 * self.dt_vec).astype(self.dt_vec.dtype)
        new, matrix = entry.ens.multi_step(
            batch, self.chunk, t=t_vec, dt=self.dt_vec,
            rhs_args={n: self.params[n] for n in entry.param_names},
            sentinel=entry.sentinel)
        done = i + 1
        self.monitor.push(done, matrix)
        for ev in self.monitor.poll():
            self._note_eviction(ev)
        if self.ttfs_s is None:
            # the one deliberate sync: time-to-first-step is a
            # PRODUCT metric (the warm-vs-cold split the report
            # gates), so the first chunk's completion is measured
            # honestly rather than at async-dispatch return
            jax.block_until_ready(new)
            self.ttfs_s = time.perf_counter() - self.t_origin
            for req in self.requests:
                if req.ttfs_s is None:
                    req.ttfs_s = self.ttfs_s
        for m, fc in self.finish_chunks.items():
            if fc == done and m not in self.finished \
                    and m not in self.diverged:
                # retire-time health check: the member's final chunks
                # may still sit inside the maturity lag
                ev = self.monitor.check_member_now(m, done)
                if ev is not None:
                    self._note_eviction(ev)
                else:
                    self.finished[m] = entry.ens.take_member(new, m)
        return new

    def _note_eviction(self, ev):
        # a diverged member in a service lease is a FAILED REQUEST
        # (reported to its tenant), never a resample — the sampler is
        # the tenant's, and silently re-rolling their dice would
        # falsify the result stream
        if ev.member < len(self.requests):
            self.diverged.setdefault(ev.member, ev)

    def active_members(self):
        return [m for m in range(len(self.requests))
                if m not in self.finished and m not in self.diverged]

    def tenant_steps(self, final_chunks):
        """Member-steps served per tenant in this lease — a pure
        function of the completed chunk count (replay-safe)."""
        out = {}
        for m, req in enumerate(self.requests):
            chunks = min(self.finish_chunks[m], int(final_chunks))
            steps = chunks * self.chunk
            out[req.tenant] = out.get(req.tenant, 0) + steps
        return out


class ScenarioService:
    """A persistent, multi-tenant simulation server (module docstring).

    :arg checkpoint_dir: root directory for the per-lease durable
        checkpoints (the preemption drain and device-loss recovery
        both live here).
    :arg slots: batch members per lease (default: registered
        ``PYSTELLA_SERVICE_SLOTS``).
    :arg chunk: steps per batched dispatch (default:
        ``PYSTELLA_SERVICE_CHUNK``); preemption latency and checkpoint
        cadence are multiples of it.
    :arg scheduler / pool / admission / results: injectable policy
        objects (defaults built from the registry).
    :arg store: optional :class:`~pystella_tpu.obs.warmstart.
        WarmstartStore` the admission controller audits warm
        admissions against.
    :arg preempt: enable priority preemption (default:
        ``PYSTELLA_SERVICE_PREEMPT``).
    :arg checkpoint_chunks: supervisor checkpoint interval in chunks.
    :arg faults: optional :class:`~pystella_tpu.resilience.
        FaultInjector` threaded into every lease's supervisor (drills).
    :arg retry: :class:`~pystella_tpu.resilience.RetryPolicy` for lease
        recovery.
    :arg planner_factory: optional ``planner_factory(lease, entry) ->
        RemeshPlanner | None`` — the PR-11 degraded-continuation hook
        for leases holding a real mesh slice.
    :arg cold_policy: admission cold policy override
        (``PYSTELLA_SERVICE_COLD_POLICY``).
    :arg slo: optional :class:`~pystella_tpu.obs.slo.SLOMonitor`
        subscribed to the process event log for the duration of
        :meth:`serve` (live burn-rate alerts; the registered
        ``PYSTELLA_LIVE_PORT`` endpoint serves its state at ``/slo``).
        When the live endpoint is on and no monitor was given, a
        default one is built.
    :arg capacity: optional :class:`~pystella_tpu.obs.capacity.
        CapacityMonitor` (default-built; ``False`` disables the
        capacity plane). Threaded into the admission controller for
        the memory budget, polled per chunk for live watermarks,
        consulted on a RESOURCE_EXHAUSTED lease failure for the OOM
        forensic bundle, and finalized at the end of the serve loop
        into per-tenant chip-second accounts (``capacity_usage``).
    :arg label: tag carried on every event.
    """

    def __init__(self, checkpoint_dir, slots=None, chunk=None,
                 scheduler=None, pool=None, admission=None, store=None,
                 results=None, preempt=None, checkpoint_chunks=2,
                 faults=None, retry=None, planner_factory=None,
                 cold_policy=None, slo=None, capacity=None,
                 label="service", live_port=None, fleet_id=None):
        self.checkpoint_dir = os.path.abspath(str(checkpoint_dir))
        self.slots = int(slots if slots is not None
                         else _config.get_int("PYSTELLA_SERVICE_SLOTS"))
        self.chunk = int(chunk if chunk is not None
                         else _config.get_int("PYSTELLA_SERVICE_CHUNK"))
        if self.slots < 1 or self.chunk < 1:
            raise ValueError("slots and chunk must be >= 1")
        self.scheduler = scheduler or FairShareScheduler()
        self.pool = pool or WarmPool()
        self.store = store
        if capacity is None:
            from pystella_tpu.obs.capacity import CapacityMonitor
            capacity = CapacityMonitor()
        self.capacity = capacity or None    # False -> disabled
        if self.capacity is not None:
            # subscribe NOW, not at serve(): submissions and arming
            # precede the serve loop, and retire-time chip-second
            # attribution needs their service_request root spans in
            # the monitor's buffer (subscribe is idempotent — the
            # _live_begin re-subscribe covers a reconfigured log)
            _events.get_log().subscribe(self.capacity.handle)
        self.admission = admission or AdmissionController(
            self.pool, store=store, cold_policy=cold_policy,
            capacity=self.capacity)
        self.results = results or ResultEmitter(label=label)
        if preempt is None:
            preempt = _config.get_bool("PYSTELLA_SERVICE_PREEMPT")
        self.preempt_enabled = bool(preempt)
        self.checkpoint_chunks = int(checkpoint_chunks)
        self.faults = faults
        self.retry = retry
        self.planner_factory = planner_factory
        self.slo = slo
        self.live_server = None
        # live_port overrides PYSTELLA_LIVE_PORT for THIS replica: an
        # int binds that port, "auto" an ephemeral one — two
        # in-process replicas (the fleet drill) cannot share one env
        # var. fleet_id likewise pins the registry record identity.
        self.live_port = live_port
        self.fleet_id = fleet_id
        self.fleet_registry = None
        self.label = str(label)
        self._models = {}
        self._arrivals = []          # (due_total_chunks, request)
        self._total_chunks = 0
        self._lease_seq = 0
        self._serving = False
        self._active_lease = None
        self._last_chunk_ts = None
        self.last_chunk_member_steps_per_s = None
        self.totals = {
            "submitted": 0, "admitted": 0, "rejected": {},
            "completed": 0, "diverged": 0, "preemptions": 0,
            "leases": 0, "lease_failures": 0,
            "replayed_member_steps": 0, "tenant_steps": {},
        }

    # -- model / pool management --------------------------------------------

    def register_model(self, name, builder):
        """Register a scenario model: ``builder(grid_shape, decomp) ->
        (stepper, sample, dt)`` with ``sample(seed) -> (state, params)``
        one member's IC draw and scalar parameter dict."""
        self._models[str(name)] = builder
        return self

    def arm(self, signature, decomp=None, invariants=None):
        """Arm the warm pool for ``signature`` (build + trace + compile
        + one warm dispatch, OFF any request's latency path when called
        at deploy time). ``decomp`` is the mesh slice the signature's
        leases will occupy."""
        model = parse_signature(signature)[0]
        builder = self._models.get(model)
        if builder is None:
            raise KeyError(
                f"no model {model!r} registered (signature "
                f"{signature!r}); register_model() first")
        entry = self.pool.arm(signature, builder, slots=self.slots,
                              chunk=self.chunk, decomp=decomp,
                              invariants=invariants)
        if self.capacity is not None:
            self.capacity.note_armed(signature, entry)
        return entry

    # -- ingestion -----------------------------------------------------------

    def submit(self, request):
        """Admit + enqueue one request; returns the
        :class:`~pystella_tpu.service.admission.AdmissionVerdict`
        (falsy == rejected, with the typed reason)."""
        self.totals["submitted"] += 1
        _metrics.counter("service.submitted").inc()
        verdict = self.admission.admit(request)
        if not verdict.admitted:
            return self._reject(request, verdict,
                                verdict.kind or "cold_signature")
        try:
            self.scheduler.submit(request)
        except QuotaExceeded as e:
            verdict.admitted = False
            verdict.reason = str(e)
            return self._reject(request, verdict, "quota")
        self.totals["admitted"] += 1
        request.warm = verdict.warm
        request.fingerprint = verdict.fingerprint
        request.fingerprint_ok = verdict.fingerprint_ok
        # the root span of the request's trace: submission + admission
        # verdict (obs.spans assembles submit -> retire from here)
        with _events.tracing(trace=request.trace_id,
                             span=request.span_id):
            _events.emit("service_request", id=request.id,
                         tenant=request.tenant,
                         signature=request.signature,
                         priority=request.priority, nsteps=request.nsteps,
                         seed=request.seed, deadline_s=request.deadline_s,
                         label=self.label)
            _events.emit("service_admit", id=request.id,
                         tenant=request.tenant, warm=verdict.warm,
                         fingerprint=verdict.fingerprint,
                         fingerprint_ok=verdict.fingerprint_ok,
                         reason=verdict.reason, label=self.label)
        return verdict

    def _reject(self, request, verdict, reason_kind):
        request.status = "rejected"
        reasons = self.totals["rejected"]
        reasons[reason_kind] = reasons.get(reason_kind, 0) + 1
        with _events.tracing(trace=request.trace_id,
                             span=request.span_id):
            _events.emit("service_request", id=request.id,
                         tenant=request.tenant,
                         signature=request.signature,
                         priority=request.priority, nsteps=request.nsteps,
                         seed=request.seed, deadline_s=request.deadline_s,
                         label=self.label)
            _events.emit("service_reject", id=request.id,
                         tenant=request.tenant,
                         signature=request.signature,
                         reason=reason_kind, detail=verdict.reason,
                         label=self.label)
        return verdict

    def schedule_arrival(self, after_chunks, request):
        """Deterministic mid-run arrival: submit ``request`` once the
        service has dispatched ``after_chunks`` total chunks (the load
        generator's preemption forcing; a live deployment just calls
        :meth:`submit` from its frontend)."""
        self._arrivals.append((int(after_chunks), request))
        return self

    def _poll_arrivals(self):
        due = [r for k, r in self._arrivals
               if self._total_chunks >= k]
        self._arrivals = [(k, r) for k, r in self._arrivals
                          if self._total_chunks < k]
        for r in due:
            self.submit(r)
        return due

    def _on_chunk(self, lease):
        """Called by the lease at every chunk boundary: count it, admit
        any due arrivals, and trigger the preemption drain when a
        strictly higher priority class is now waiting. Also the live
        throughput gauge's heartbeat: the wall time between two chunk
        boundaries over the batch's member-steps is the
        last-chunk member-steps/s the ``/metrics`` endpoint exposes."""
        now = time.perf_counter()
        if self._last_chunk_ts is not None and now > self._last_chunk_ts:
            steps = lease.chunk * lease.entry.ens.size
            self.last_chunk_member_steps_per_s = \
                steps / (now - self._last_chunk_ts)
            _metrics.gauge("service.member_steps_per_s").set(
                self.last_chunk_member_steps_per_s)
            # feed the continuous-performance plane: the per-step wall
            # time of this chunk, filed under one service-wide
            # signature so the dispatch loop is a perf_anomaly source
            # like every StepTimer-owning driver (obs.perf; no-op when
            # PYSTELLA_PERF=0)
            from pystella_tpu.obs import perf as _perf
            _perf.observe(
                "service.chunk",
                (now - self._last_chunk_ts) * 1e3 / max(1, lease.chunk))
        self._last_chunk_ts = now
        _metrics.counter("service.chunks").inc()
        self._total_chunks += 1
        if self.capacity is not None:
            # per-chunk live HBM watermark (no-op on stat-less
            # backends — coverage then reads predicted_only, honestly)
            self.capacity.poll_watermark(lease=lease.id,
                                         step=self._total_chunks)
        self._poll_arrivals()
        if (self.preempt_enabled and lease.supervisor is not None
                and self.scheduler.has_priority_above(lease.priority)):
            lease.supervisor.request_preemption()

    # -- the live operations plane -------------------------------------------

    def live_status(self):
        """A consistent-enough point-in-time view for the live
        telemetry endpoint (:mod:`pystella_tpu.obs.live`), safe to call
        from the scrape thread while the serve loop runs: queue depth
        overall / per priority class / per tenant, the active lease and
        its supervisor's drain state, warm-pool entries split by live
        fingerprint match, and the last chunk's member-steps/s. Reads
        are snapshot-copied list/dict walks — no locks are taken, so a
        scrape can never stall a dispatch."""
        queue = list(getattr(self.scheduler, "_queue", []))
        by_class, by_tenant = {}, {}
        for r in queue:
            cls = str(r.priority)
            by_class[cls] = by_class.get(cls, 0) + 1
            by_tenant[r.tenant] = by_tenant.get(r.tenant, 0) + 1
        pool_ok = pool_stale = 0
        for sig in self.pool.signatures():
            entry = self.pool.get(sig)
            try:
                ok = bool(entry is not None and entry.fingerprint_ok())
            except Exception:  # noqa: BLE001 — a scrape never raises
                ok = False
            pool_ok, pool_stale = (pool_ok + ok, pool_stale + (not ok))
        lease = self._active_lease
        supervisor = None
        if lease is not None and lease.supervisor is not None:
            supervisor = {
                "lease": lease.id,
                "draining": getattr(lease.supervisor,
                                    "_preempt_signum", None) is not None,
                "members": len(lease.requests),
                "finished": len(lease.finished),
                "diverged": len(lease.diverged),
            }
        return {
            "serving": self._serving,
            "queue_depth": len(queue),
            "queue_by_priority": by_class,
            "queue_by_tenant": by_tenant,
            "active_lease": None if lease is None else lease.id,
            "active_leases": 0 if lease is None else 1,
            "supervisor": supervisor,
            "leases_completed": self.totals["leases"],
            "lease_failures": self.totals["lease_failures"],
            "completed": self.totals["completed"],
            "preemptions": self.totals["preemptions"],
            "warm_pool": {"ok": pool_ok, "stale": pool_stale},
            "last_chunk_member_steps_per_s":
                self.last_chunk_member_steps_per_s,
            "capacity": (self.capacity.live_fields()
                         if self.capacity is not None else None),
        }

    def _live_begin(self):
        """Bring the opt-in live plane up around one serve loop: build
        a default SLO monitor when the endpoint is on and none was
        given, subscribe the monitor to the process event log (the
        in-process push channel), and start the ``PYSTELLA_LIVE_PORT``
        endpoint. Returns the subscribed-monitor flag for
        :meth:`_live_end`."""
        port = self.live_port
        if port is None:
            port = _config.get_int("PYSTELLA_LIVE_PORT") or 0
        enabled = port == "auto" or int(port) > 0
        if enabled and self.slo is None:
            from pystella_tpu.obs import slo as _slo
            self.slo = _slo.SLOMonitor(label=self.label)
        attached = False
        if self.slo is not None:
            _events.get_log().subscribe(self.slo.handle)
            attached = True
        if self.capacity is not None:
            # the capacity monitor rides the same push channel: it
            # buffers the span stream for retire-time attribution and
            # upgrades footprints from byte-bearing compile events
            _events.get_log().subscribe(self.capacity.handle)
        if enabled:
            from pystella_tpu.obs import live as _live
            self.live_server = _live.start_from_env(
                service=self, slo=self.slo, label=self.label,
                port=port)
        fleet_dir = _config.getenv("PYSTELLA_FLEET_DIR")
        if fleet_dir:
            from pystella_tpu.service import registry as _registry
            self.fleet_registry = _registry.ReplicaRegistry(
                fleet_dir, replica_id=self.fleet_id,
                status_fn=lambda: _registry.service_status_record(self),
                label=self.label)
            url = (self.live_server.url()
                   if self.live_server is not None else None)
            self.fleet_registry.announce(url=url)
        return attached

    def _live_end(self, attached):
        """Tear the live plane down (final monitor evaluation first, so
        an alert that should resolve by aging does before the record
        closes)."""
        if self.slo is not None:
            self.slo.evaluate()
        if attached:
            _events.get_log().unsubscribe(self.slo.handle)
        if self.capacity is not None:
            _events.get_log().unsubscribe(self.capacity.handle)
        if self.fleet_registry is not None:
            # a no-op after kill(): a "crashed" drill replica must not
            # tombstone itself on the way out
            self.fleet_registry.withdraw()
            self.fleet_registry = None
        if self.live_server is not None:
            self.live_server.close()
            self.live_server = None

    # -- serving -------------------------------------------------------------

    def serve(self, max_leases=None):
        """Drain the queue (and any scheduled arrivals): dispatch
        leases until idle. Returns the service summary dict (also
        emitted as ``service_done``). While the loop runs, the opt-in
        live plane (``PYSTELLA_LIVE_PORT`` endpoint + SLO burn-rate
        monitor) is up; both come down with the loop."""
        attached = self._live_begin()
        self._serving = True
        try:
            return self._serve_loop(max_leases)
        finally:
            self._serving = False
            self._live_end(attached)

    def _serve_loop(self, max_leases):
        _events.emit("service_start", label=self.label,
                     slots=self.slots, chunk=self.chunk,
                     preempt=self.preempt_enabled,
                     cold_policy=self.admission.cold_policy,
                     quota=self.scheduler.quota)
        leases = 0
        while max_leases is None or leases < max_leases:
            if not self.scheduler.pending and self._arrivals:
                # idle service: pending arrivals are admitted now
                # rather than waiting on chunks that will never run
                for _k, r in self._arrivals:
                    self.submit(r)
                self._arrivals = []
            if not self.scheduler.pending:
                break
            self._run_lease()
            leases += 1
        summary = {
            "label": self.label,
            "leases": self.totals["leases"],
            "lease_failures": self.totals["lease_failures"],
            "submitted": self.totals["submitted"],
            "admitted": self.totals["admitted"],
            "completed": self.totals["completed"],
            "diverged": self.totals["diverged"],
            "rejected": dict(self.totals["rejected"]),
            "preemptions": self.totals["preemptions"],
            "replayed_member_steps":
                self.totals["replayed_member_steps"],
            "tenant_steps": dict(self.totals["tenant_steps"]),
        }
        if self.capacity is not None:
            try:
                usage = self.capacity.finalize_usage(label=self.label)
            except Exception as e:  # noqa: BLE001 — chargeback is
                # telemetry; its failure must never kill a clean drain
                _events.emit("obs_subscriber_error",
                             subscriber="capacity.finalize_usage",
                             error=f"{type(e).__name__}: {e}")
                usage = None
            if usage is not None:
                summary["goodput"] = usage.get("goodput")
                summary["total_chip_s"] = usage.get("total_chip_s")
        _events.emit("service_done", **summary)
        return summary

    def _run_lease(self):
        requests = self.scheduler.dispatch(self.slots)
        if not requests:
            return None
        if all(r.trace_id is None for r in requests):
            # PYSTELLA_TRACE_SERVICE=0: the whole layer opts out —
            # events stay v1-shaped (no span fields) and the ledger
            # never collects a span stream to assemble
            return self._run_lease_traced(requests, None)
        # one causal span per lease, shared by every request riding it:
        # the whole lease body runs inside its tracing context, so the
        # supervisor's chunk loop, checkpoint barriers, recovery and
        # drain events all inherit the lease span — obs.spans attaches
        # them to every member trace through the dispatch records below
        lease_span = _events.new_span_id()
        with _events.tracing(span=lease_span):
            return self._run_lease_traced(requests, lease_span)

    def _run_lease_traced(self, requests, lease_span):
        t_origin = time.perf_counter()
        signature = requests[0].signature
        self._lease_seq += 1
        lease_id = self._lease_seq
        entry = self.pool.get(signature)
        cold_build_s = 0.0
        if entry is None or not entry.fingerprint_ok():
            # the cold path: the request queue waits behind this
            # build+compile, and ONLY this lease pays it — the entry
            # then serves every later lease warm (the service_arm event
            # inherits the lease span, so the compile is attributable)
            t_build0 = time.perf_counter()
            entry = self.arm(signature)
            cold_build_s = time.perf_counter() - t_build0
        lease_warm = cold_build_s == 0.0
        now = time.time()
        for r in requests:
            r.dispatch_ts = now
            # recomputed at EVERY dispatch against the original
            # submit_ts: a preempted request's re-dispatch reports its
            # cumulative wait (the requeue contract — the SLO must see
            # the time spent parked behind the higher class, not just
            # the pre-preemption wait)
            r.queue_latency_s = max(0.0, now - (r.submit_ts or now))
            r.status = "running"
            _metrics.counter("service.dispatches").inc()
            with _events.tracing(trace=r.trace_id, parent=r.span_id):
                _events.emit("service_dispatch", id=r.id,
                             tenant=r.tenant,
                             priority=r.priority, lease=lease_id,
                             queue_latency_s=round(r.queue_latency_s, 6),
                             warm=r.warm, resumed=r.resume_step > 0,
                             label=self.label)
        lease = _Lease(self, entry, requests, lease_id, t_origin,
                       cold_build_s=cold_build_s)
        self.totals["leases"] += 1
        _metrics.counter("service.leases").inc()
        self._active_lease = lease
        # the chunk-rate gauge measures within-lease cadence only: the
        # inter-lease gap (retire, checkpointing, a cold build) is not
        # compute, so the first chunk of a new lease must not divide
        # by it
        self._last_chunk_ts = None
        try:
            with _memory.compile_watch(f"service.lease{lease_id}") as w:
                try:
                    rep = self._supervised_run(lease)
                except Exception as e:  # noqa: BLE001 — service survives
                    self._lease_failed(lease, e)
                    return None
        finally:
            self._active_lease = None
        backend_compiles = w.backend_compiles
        replayed = (rep["steps_replayed"] * self.chunk
                    * max(1, len(lease.active_members())
                          + len(lease.finished)))
        self.totals["replayed_member_steps"] += replayed
        tenant_steps = lease.tenant_steps(rep["final_step"])
        for tenant, steps in tenant_steps.items():
            self.totals["tenant_steps"][tenant] = \
                self.totals["tenant_steps"].get(tenant, 0) + steps
        _events.emit(
            "service_lease", lease=lease_id, signature=signature,
            priority=lease.priority, requests=len(requests),
            chips=self._lease_chips(entry),
            warm=lease_warm, ttfs_s=lease.ttfs_s,
            cold_build_s=round(cold_build_s, 4),
            trace_s=round(w.trace_seconds, 4),
            compile_s=round(w.compile_seconds, 4),
            backend_compiles=backend_compiles,
            chunks=rep["final_step"], preempted=rep["preempted"],
            incidents=rep["incidents"],
            replayed_member_steps=replayed,
            tenant_steps=tenant_steps,
            wall_s=round(rep["wall_s"], 4), label=self.label)
        if rep["preempted"]:
            self._requeue_preempted(lease, rep)
        self._emit_results(lease)
        return rep

    @staticmethod
    def _lease_chips(entry):
        """Chips a lease against ``entry`` holds — the mesh slice's
        device count (1 on the single-device tier). The chip-second
        accounts (:mod:`pystella_tpu.obs.capacity`) bill phases x this."""
        decomp = getattr(entry, "decomp", None)
        if decomp is not None:
            try:
                return int(decomp.mesh.devices.size)
            except Exception:  # noqa: BLE001 — telemetry only
                pass
        return 1

    def _supervised_run(self, lease):
        from pystella_tpu import Checkpointer
        from pystella_tpu.resilience import Supervisor

        planner = (self.planner_factory(lease, lease.entry)
                   if self.planner_factory is not None else None)
        ck_dir = os.path.join(self.checkpoint_dir,
                              f"lease{lease.id}")
        with Checkpointer(ck_dir, max_to_keep=2) as ck:
            sup = Supervisor(
                lease.step_fn, ck, lease.n_chunks, monitor=None,
                checkpoint_every=self.checkpoint_chunks,
                faults=self.faults, retry=self.retry, planner=planner,
                install_sigterm=False, keep_initial=True,
                label=f"{self.label}.lease{lease.id}")
            lease.supervisor = sup
            return sup.run(lease.batch0, resume=False)

    def _lease_failed(self, lease, error):
        """A lease whose supervision gave up (recovery budget, a
        deterministic program bug...) is contained: its unfinished
        requests requeue — losing at most that lease's work — the
        failure is an event, and the service keeps serving. Each
        request carries a failure budget: after two failed leases it
        is reported ``failed`` to its tenant instead of requeued, so a
        request that deterministically kills its lease cannot spin the
        service forever."""
        self.totals["lease_failures"] += 1
        _events.emit("service_lease_failed", lease=lease.id,
                     signature=lease.entry.signature,
                     error=f"{type(error).__name__}: {error}",
                     label=self.label)
        if self.capacity is not None:
            from pystella_tpu.obs import capacity as _capacity
            if _capacity.is_resource_exhausted(error):
                # an allocator OOM got past admission: bundle the
                # resident footprint table, the watermark series, and
                # the decision that let it through (PR-4 forensics)
                try:
                    self.capacity.write_oom_bundle(
                        os.path.join(self.checkpoint_dir, "forensics"),
                        error, signature=lease.entry.signature,
                        lease=lease.id, label=self.label)
                except Exception as e:  # noqa: BLE001 — forensics are
                    # best-effort; the requeue below must still run
                    _events.emit("forensic_failed",
                                 reason=f"{type(e).__name__}: {e}",
                                 label=self.label)
        for m in lease.active_members():
            req = lease.requests[m]
            req.failures += 1
            if req.failures >= 2:
                req.status = "failed"
                self.totals["diverged"] += 1
                self.results.emit(req, None, status="failed",
                                  lease=lease.id)
                continue
            req.status = "queued"
            self.scheduler.requeue(req)
            # the failure-requeue is a span boundary like the
            # preemption one: without it the request's next queue wait
            # would be unattributable (obs.spans uses requeue events
            # as segment starts)
            with _events.tracing(trace=req.trace_id,
                                 parent=req.span_id):
                _events.emit("service_requeue", id=req.id,
                             tenant=req.tenant, lease=lease.id,
                             steps_done=req.resume_step,
                             reason="lease_failed", label=self.label)
        self._emit_results(lease)

    def _requeue_preempted(self, lease, rep):
        """The drain half of preempt-without-losing-work: the
        supervisor already took the durable checkpoint; every
        unfinished member's restored state re-enters the queue and its
        next lease resumes the same trajectory."""
        self.totals["preemptions"] += 1
        _metrics.counter("service.preemptions").inc()
        requeued = []
        for m in lease.active_members():
            req = lease.requests[m]
            req.resume_state = lease.entry.ens.take_member(
                rep["state"], m)
            req.resume_step = int(lease.start_steps[m]
                                  + rep["final_step"] * lease.chunk)
            req.status = "preempted"
            self.scheduler.requeue(req)
            requeued.append(req.id)
            # the SAME trace id re-enters the queue: the requeued
            # request's next lease extends this trace, which is what
            # lets obs.spans attribute the full cross-lease wall
            with _events.tracing(trace=req.trace_id,
                                 parent=req.span_id):
                _events.emit("service_requeue", id=req.id,
                             tenant=req.tenant, lease=lease.id,
                             steps_done=req.resume_step,
                             reason="preempted", label=self.label)
        _events.emit("service_preempted", lease=lease.id,
                     requeued=requeued, at_chunk=rep["final_step"],
                     checkpoint=rep.get("last_good"), label=self.label)

    def _emit_results(self, lease):
        for m, state in sorted(lease.finished.items()):
            req = lease.requests[m]
            req.status = "completed"
            self.totals["completed"] += 1
            _metrics.counter("service.completed").inc()
            self.results.emit(req, state, status="completed",
                              lease=lease.id)
        for m, ev in sorted(lease.diverged.items()):
            req = lease.requests[m]
            req.status = "diverged"
            self.totals["diverged"] += 1
            self.results.emit(req, None, status="diverged",
                              lease=lease.id,
                              diverged_fields=ev.fields)
