"""Replica registry: the fleet's membership plane, stdlib-only.

A fleet is N :class:`~pystella_tpu.service.ScenarioService` replicas
serving as one logical service. Before anything can be aggregated,
routed, or compared across them, something has to answer *who is in
the fleet right now* — and answer it without a coordination service,
because the serving path must not grow a dependency. This module is
that answer, built on the one primitive every deployment already
shares: a directory.

**Writer side.** Each serving replica owns one JSON record file
``<PYSTELLA_FLEET_DIR>/<replica_id>.json`` and rewrites it atomically
(tmp file + ``os.replace``) at the registered
``PYSTELLA_FLEET_HEARTBEAT_S`` cadence. The record carries everything
a fleet reader needs to aggregate or to refuse to: the replica id,
the live-endpoint URL (:mod:`pystella_tpu.obs.live` — the URL is
valid at announce time because the endpoint binds its port in its
constructor), the device kind, the jax/jaxlib/libtpu version triple
plus scheduler-relevant flag fingerprint (digested into one
``fingerprint`` skew key), the warm-pool signature fingerprints (the
safety precondition for cross-replica warm-artifact reuse), the queue
depth, and the serving state. A replica that exits cleanly writes a
final tombstone (``withdrawn: true``) so readers can tell a shutdown
from a crash; a crashed replica simply stops beating, and readers
expire its record by heartbeat age (``PYSTELLA_FLEET_EXPIRE_S``).

**Reader side.** :func:`read_records` returns every parseable record
annotated with its heartbeat age and a derived ``status`` —
``"live"``, ``"stale"`` (expired heartbeat: presumed crashed), or
``"withdrawn"``. :class:`~pystella_tpu.obs.fleet.FleetAggregator` and
``python -m pystella_tpu.service status --fleet`` both read through
this one function so membership semantics cannot fork.

Opt-in end to end: :meth:`ScenarioService.serve
<pystella_tpu.service.ScenarioService.serve>` announces/withdraws
automatically only when ``PYSTELLA_FLEET_DIR`` is set. The drill seam
:meth:`ReplicaRegistry.kill` abandons the record *without* a
tombstone — a simulated crash, used by the two-replica fleet drill so
the aggregator's expiry path is exercised by tier-1 evidence.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import socket
import threading
import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import ledger as _ledger

__all__ = ["ReplicaRegistry", "read_records", "stack_fingerprint",
           "service_status_record"]

#: per-process announce counter: two in-process replicas share a pid,
#: so default replica ids need a process-local discriminator
_SEQ = itertools.count()


def stack_fingerprint(versions=None, flags=None):
    """One short digest over the compiler stack (version triple +
    scheduler-relevant flags) — the skew key: two replicas whose
    fingerprints differ are not interchangeable for warm-artifact
    reuse or apples-to-apples perf comparison."""
    if versions is None:
        versions = _ledger.runtime_versions()
    if flags is None:
        flags = _ledger.xla_flag_fingerprint()
    blob = json.dumps({"versions": versions, "flags": flags},
                      sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _device_kind():
    """Device kind from an already-imported jax only — announcing a
    replica must never trigger backend init."""
    import sys
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return str(jax.devices()[0].device_kind)
        except Exception:  # noqa: BLE001 — membership must not raise
            pass
    return None


def service_status_record(service):
    """The dynamic record fields read off a live
    :class:`~pystella_tpu.service.ScenarioService` — called at
    announce time and again on every heartbeat, so readers see queue
    depth and warm fingerprints at most one beat old."""
    status = service.live_status()
    warm = {}
    for sig in service.pool.signatures():
        entry = service.pool.get(sig)
        fp = getattr(entry, "fingerprint", None)
        if fp:
            warm[str(sig)] = fp
    return {
        "serving": bool(status.get("serving")),
        "queue_depth": status.get("queue_depth"),
        "leases_completed": status.get("leases_completed"),
        "completed": status.get("completed"),
        "warm_fingerprints": warm,
    }


class ReplicaRegistry:
    """One replica's registry membership (module docstring).

    :arg root: the shared registry directory (created if missing).
    :arg replica_id: record identity; default derives from ``label``,
        pid, and a process-local counter so two in-process replicas
        never collide.
    :arg heartbeat_s: beat cadence; ``None`` reads the registered
        ``PYSTELLA_FLEET_HEARTBEAT_S``; ``<= 0`` announces once and
        never beats (tests drive :meth:`heartbeat` by hand).
    :arg status_fn: optional zero-arg callable returning record fields
        to merge on every beat (the service passes a
        :func:`service_status_record` closure). A raising status_fn is
        swallowed — a heartbeat must never kill serving.
    :arg label: carried on the record and the default replica id.
    """

    def __init__(self, root, replica_id=None, heartbeat_s=None,
                 status_fn=None, label="replica"):
        self.root = os.path.abspath(str(root))
        os.makedirs(self.root, exist_ok=True)
        if heartbeat_s is None:
            heartbeat_s = _config.get_float("PYSTELLA_FLEET_HEARTBEAT_S")
        self.heartbeat_s = float(heartbeat_s)
        self.label = str(label)
        self.replica_id = (str(replica_id) if replica_id else
                           f"{self.label}-{os.getpid()}-{next(_SEQ)}")
        self.status_fn = status_fn
        self.record = {}
        self.heartbeats = 0
        self.killed = False
        self._stop = threading.Event()
        self._thread = None

    @property
    def path(self):
        return os.path.join(self.root, self.replica_id + ".json")

    # -- writer lifecycle ----------------------------------------------------

    def announce(self, **fields):
        """Publish the record (identity + stack fingerprint + any
        ``fields``, e.g. ``url=...``) and start the heartbeat thread.
        Returns ``self``."""
        versions = _ledger.runtime_versions()
        flags = _ledger.xla_flag_fingerprint()
        self.record = {
            "replica": self.replica_id,
            "label": self.label,
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "started_ts": time.time(),
            "device_kind": _device_kind(),
            "versions": versions,
            "flags": flags,
            "fingerprint": stack_fingerprint(versions, flags),
            "withdrawn": False,
        }
        self.record.update(fields)
        self.heartbeat()
        _events.emit("fleet_announce", replica=self.replica_id,
                     url=self.record.get("url"),
                     fingerprint=self.record["fingerprint"],
                     dir=self.root, label=self.label)
        if self.heartbeat_s > 0:
            self._thread = threading.Thread(
                target=self._beat, daemon=True,
                name=f"pystella-fleet:{self.replica_id}")
            self._thread.start()
        return self

    def heartbeat(self):
        """One beat: refresh the dynamic fields via ``status_fn`` and
        rewrite the record atomically."""
        if self.status_fn is not None:
            try:
                self.record.update(self.status_fn() or {})
            except Exception:  # noqa: BLE001 — never kill serving
                pass
        self.heartbeats += 1
        self._write()

    def _beat(self):
        while not self._stop.wait(self.heartbeat_s):
            self.heartbeat()

    def _write(self):
        rec = dict(self.record)
        rec["ts"] = time.time()
        rec["heartbeats"] = self.heartbeats
        # atomic replace; the tmp name embeds the replica id, so
        # concurrent writers (distinct replicas) never collide
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, sort_keys=True)
        os.replace(tmp, self.path)

    def withdraw(self):
        """Clean exit: stop the heartbeat and write the tombstone
        (``withdrawn: true``) so readers see a shutdown, not a crash.
        A no-op after :meth:`kill` — a crashed replica cannot clean
        up, and the drill relies on that. Idempotent."""
        self._stop_thread()
        if self.killed or not self.record:
            return
        self.record["withdrawn"] = True
        self.record["serving"] = False
        self._write()
        _events.emit("fleet_withdraw", replica=self.replica_id,
                     heartbeats=self.heartbeats, label=self.label)
        self.record = {}

    def kill(self):
        """Drill seam: simulate a crash — stop beating, leave the
        record as-is (no tombstone). Readers will watch it go stale
        and expire it."""
        self.killed = True
        self._stop_thread()

    def _stop_thread(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.withdraw()


# -- readers ----------------------------------------------------------------


def read_records(root, expire_s=None, now=None):
    """Every parseable record under ``root``, each annotated with
    ``age_s`` (since last heartbeat) and ``status``: ``"live"``
    (beating within ``expire_s``), ``"stale"`` (heartbeat expired —
    presumed crashed), or ``"withdrawn"`` (tombstoned clean exit).
    Unreadable or non-record files are skipped, not raised — a reader
    must tolerate a writer mid-crash. ``expire_s`` defaults to the
    registered ``PYSTELLA_FLEET_EXPIRE_S``."""
    if expire_s is None:
        expire_s = _config.get_float("PYSTELLA_FLEET_EXPIRE_S")
    expire_s = float(expire_s)
    now = time.time() if now is None else float(now)
    records = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(root, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict) or not rec.get("replica"):
            continue
        ts = rec.get("ts")
        age = (now - float(ts)) if isinstance(ts, (int, float)) else None
        rec["age_s"] = age
        if rec.get("withdrawn"):
            rec["status"] = "withdrawn"
        elif age is None or age > expire_s:
            rec["status"] = "stale"
        else:
            rec["status"] = "live"
        records.append(rec)
    return records
