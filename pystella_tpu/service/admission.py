"""Warm-pool admission control keyed on program fingerprints.

The serving tier's latency contract is *dispatch, never compile*: a
request is **warm** exactly when its (model, lattice, mesh) scenario
signature has an armed :class:`WarmPool` entry — a ready batched
stepper whose chunk program was already traced, compiled, and dispatched
once at arm time — whose fingerprint components (jax/jaxlib/libtpu
versions + scheduler flags, the same identity the PR-6 compile ledger
and :class:`~pystella_tpu.obs.warmstart.WarmstartStore` key on) still
match the live process. A warm lease therefore does **zero** tracing and
**zero** backend compiles; the service proves it per lease from the
compile ledger (``service_lease.backend_compiles``) and the perf gate
refuses a report claiming warm admissions over mismatched fingerprints.

A **cold** signature (no armed entry, or a stale one) takes the
registered ``PYSTELLA_SERVICE_COLD_POLICY``:

- ``"compile"`` — admitted, queued behind the build+compile of a fresh
  pool entry at dispatch time (its time-to-first-step pays the compile,
  visible in the report's warm-vs-cold TTFS split);
- ``"reject"`` — refused with a typed :class:`ColdSignature` verdict
  (``service_reject``, reason ``cold_signature``).

With a :class:`~pystella_tpu.obs.warmstart.WarmstartStore` attached, an
armed entry is additionally audited against the newest AOT artifact
exported under its signature label: a version/flag-stale artifact
demotes the admission to cold (``fingerprint_ok=False`` recorded) —
the store is the cross-process warm contract, and serving "warm" over
a stale export is exactly the lie the gate exists to catch.
"""

from __future__ import annotations

import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import memory as _memory

__all__ = ["AdmissionController", "AdmissionVerdict", "CapacityExceeded",
           "ColdSignature", "WarmPool", "WarmPoolEntry",
           "parse_signature", "request_signature"]


def request_signature(model, grid_shape, proc_shape=(1, 1, 1),
                      dtype="float32"):
    """The canonical scenario-signature string a request carries and
    the warm pool keys on: ``model/NxNxN/PxPxP/dtype``. Two requests
    share a signature exactly when one armed batched program can serve
    both."""
    return "/".join((
        str(model),
        "x".join(str(int(n)) for n in grid_shape),
        "x".join(str(int(p)) for p in proc_shape),
        str(dtype)))


def parse_signature(signature):
    """Inverse of :func:`request_signature`:
    ``(model, grid_shape, proc_shape, dtype)``."""
    parts = str(signature).split("/")
    if len(parts) != 4:
        raise ValueError(
            f"malformed scenario signature {signature!r} (want "
            "'model/NxNxN/PxPxP/dtype')")
    model, grid, proc, dtype = parts
    return (model,
            tuple(int(n) for n in grid.split("x")),
            tuple(int(p) for p in proc.split("x")),
            dtype)


class AdmissionVerdict:
    """One admission decision. Truthiness is ``admitted``."""

    kind = "admission"

    def __init__(self, request, admitted, warm, reason="",
                 fingerprint=None, fingerprint_ok=None):
        self.request = request
        self.admitted = bool(admitted)
        self.warm = bool(warm)
        self.reason = str(reason)
        self.fingerprint = fingerprint
        self.fingerprint_ok = fingerprint_ok

    def __bool__(self):
        return self.admitted

    def __repr__(self):
        return (f"{type(self).__name__}(admitted={self.admitted}, "
                f"warm={self.warm}, reason={self.reason!r})")


class ColdSignature(AdmissionVerdict):
    """The typed cold-signature verdict: the request's signature has no
    live warm-pool entry. ``admitted`` reflects the cold policy
    (``compile`` admits behind a build, ``reject`` refuses)."""

    kind = "cold_signature"


class CapacityExceeded(AdmissionVerdict):
    """The typed memory-aware rejection: resident warm-pool programs +
    the candidate's predicted HBM footprint exceed device capacity x
    ``PYSTELLA_CAPACITY_HEADROOM`` (and the ``evict`` policy, when
    armed, could not free enough). Never admitted."""

    kind = "capacity_exceeded"


class WarmPoolEntry:
    """One armed signature: the ready batched stepper and its identity.

    Built by :meth:`WarmPool.arm`; holds the single-member stepper, its
    sampler, the :class:`~pystella_tpu.ensemble.EnsembleStepper` sized
    for the service's lease slots, the per-member sentinel, and the
    program fingerprint (+ components) of the warmed chunk program.
    """

    def __init__(self, signature, stepper, sample, dt, ens, sentinel,
                 fingerprint, components, decomp=None, trace_s=0.0,
                 compile_s=0.0, param_names=(), template=None):
        self.signature = str(signature)
        self.stepper = stepper
        self.sample = sample
        self.dt = float(dt)
        self.ens = ens
        self.sentinel = sentinel
        self.fingerprint = fingerprint
        self.components = components
        self.decomp = decomp
        self.trace_s = float(trace_s)
        self.compile_s = float(compile_s)
        self.param_names = tuple(param_names)
        self.template = template
        self.armed_ts = time.time()

    @property
    def tick_dtype(self):
        """The dtype the per-member ``t``/``dt``/parameter columns are
        built in: the template state's result dtype. Feeding f64
        columns (numpy's default) into an f32 member body would
        PROMOTE the state inside the RK update when jax runs with x64
        enabled — the chunk output then re-traces the warm program at
        the next dispatch, silently breaking dispatch-never-compile.
        One dtype, derived once from the armed avals, keeps the chunk
        self-composing."""
        import numpy as np
        if not self.template:
            return np.float32
        import jax
        leaves = jax.tree_util.tree_leaves(self.template[0])
        return np.result_type(*[leaf.dtype for leaf in leaves])

    def stack(self, states):
        """Build one lease batch from member states with CANONICAL
        dtypes and placement — the warm contract depends on both: the
        armed chunk program was compiled against the template's leaf
        dtypes and committed input shardings, and a later lease whose
        batch arrives off-spec (e.g. members restored from host copies
        after a preemption, or an f64 checkpoint of an f32 state)
        would re-trace and recompile, silently breaking
        dispatch-never-compile. For an ensemble decomposition
        ``EnsembleStepper.stack`` already places members over the
        mesh; for the single-device tier the batch is committed to the
        entry's device explicitly."""
        import jax
        import jax.numpy as jnp
        template = self.template[0] if self.template else None
        if template is not None:
            def _cast(t, x):
                return x if getattr(x, "dtype", None) == t.dtype \
                    else jnp.asarray(x, dtype=t.dtype)
            states = [jax.tree_util.tree_map(_cast, template, s)
                      for s in states]
        batch = self.ens.stack(states)
        decomp = self.decomp
        if decomp is not None \
                and getattr(decomp, "ensemble_axis", None) is not None:
            return batch
        if decomp is not None:
            devices = list(decomp.mesh.devices.flat)
            if len(devices) > 1:
                # a spatially-sharded lease batch keeps whatever
                # placement the member states carried
                return batch
            dev = devices[0]
        else:
            dev = jax.devices()[0]
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, dev), batch)

    def fingerprint_ok(self):
        """Do the entry's version/flag fingerprint components still
        match the live process? In-process they drift only when the
        scheduler-flag environment changes under the service — the
        same staleness rule the AOT warm-start store enforces across
        processes."""
        live = _memory.fingerprint_components(self.signature)
        saved = self.components or {}
        return (saved.get("versions") == live.get("versions")
                and saved.get("flags") == live.get("flags"))


class WarmPool:
    """The armed-signature registry: signature -> :class:`WarmPoolEntry`.

    :meth:`arm` builds a signature's single-member stepper through the
    caller's builder, wraps it in a lease-sized
    :class:`~pystella_tpu.ensemble.EnsembleStepper`, and dispatches the
    chunk program ONCE on a template batch under a
    :class:`~pystella_tpu.obs.memory.compile_watch` — so every later
    lease against this entry is a pure dispatch (the in-process jit
    cache serves it; the compile cost is recorded here, in the
    ``service_arm`` event, and nowhere near a request's latency).
    """

    def __init__(self):
        self._entries = {}

    def get(self, signature):
        return self._entries.get(str(signature))

    def signatures(self):
        return sorted(self._entries)

    def evict(self, signature):
        """Drop an armed entry (the capacity 'evict' policy's lever);
        returns the removed entry or ``None``. A later lease on the
        signature re-arms cold — slower, never wrong."""
        return self._entries.pop(str(signature), None)

    def arm(self, signature, builder, slots, chunk, decomp=None,
            invariants=None):
        """Arm ``signature``: ``builder(grid_shape, decomp) ->
        (stepper, sample, dt)`` with ``sample(seed) -> (state, params)``
        one member's draw. Returns the entry (re-arming replaces)."""
        import numpy as np
        from pystella_tpu import obs
        from pystella_tpu.ensemble import EnsembleStepper

        signature = str(signature)
        _model, grid_shape, _proc, _dtype = parse_signature(signature)
        stepper, sample, dt = builder(grid_shape, decomp)
        ens = EnsembleStepper(stepper, int(slots), decomp=decomp,
                              via="vmap")
        template_state, template_params = sample(0)
        sentinel = obs.Sentinel.for_state(template_state,
                                          invariants=invariants)
        param_names = tuple(sorted(template_params or {}))
        size = int(slots)
        entry = WarmPoolEntry(
            signature, stepper, sample, dt, ens, sentinel,
            None, None, decomp=decomp, param_names=param_names,
            template=(template_state, dict(template_params or {})))
        batch = entry.stack([template_state] * size)
        td = entry.tick_dtype
        t_vec = np.zeros(size, dtype=td)
        dt_vec = np.full(size, float(dt), dtype=td)
        rhs = {n: np.full(size, float(template_params[n]), dtype=td)
               for n in param_names}
        with _memory.compile_watch(f"service.arm.{signature}") as w:
            import jax
            warmed, _matrix = ens.multi_step(
                batch, int(chunk), t=t_vec, dt=dt_vec, rhs_args=rhs,
                sentinel=sentinel)
            jax.block_until_ready(warmed)
        fingerprint, components = _memory.signature_fingerprint(
            label=f"service.{signature}",
            args=(batch, t_vec, dt_vec, rhs))
        entry.fingerprint = fingerprint
        entry.components = components
        entry.trace_s = float(w.trace_seconds)
        entry.compile_s = float(w.compile_seconds)
        self._entries[signature] = entry
        _events.emit("service_arm", signature=signature,
                     fingerprint=fingerprint, slots=size,
                     chunk=int(chunk), trace_s=round(w.trace_seconds, 4),
                     compile_s=round(w.compile_seconds, 4),
                     cache_hits=w.cache_hits,
                     cache_misses=w.cache_misses)
        return entry


class AdmissionController:
    """Admission decisions over a :class:`WarmPool` (+ optional
    :class:`~pystella_tpu.obs.warmstart.WarmstartStore` audit).

    :arg pool: the warm pool.
    :arg store: optional AOT artifact store; when set, a warm admission
        additionally requires the newest artifact labeled with the
        signature (when one exists) to match the live process — a stale
        export demotes the verdict to cold with
        ``fingerprint_ok=False``.
    :arg cold_policy: ``"compile"`` | ``"reject"`` (default: the
        registered ``PYSTELLA_SERVICE_COLD_POLICY``).
    :arg capacity: optional :class:`~pystella_tpu.obs.capacity.
        CapacityMonitor`; when set, every would-be-admitted verdict
        additionally passes the memory budget — resident warm-pool
        programs + the candidate's predicted footprint must fit
        capacity x headroom, else the verdict becomes a typed
        :class:`CapacityExceeded` rejection (after the ``evict``
        policy, when armed, failed to free enough).
    """

    def __init__(self, pool, store=None, cold_policy=None,
                 capacity=None):
        self.pool = pool
        self.store = store
        self.capacity = capacity
        if cold_policy is None:
            cold_policy = _config.getenv("PYSTELLA_SERVICE_COLD_POLICY")
        cold_policy = str(cold_policy).strip().lower()
        if cold_policy not in ("compile", "reject"):
            raise ValueError(
                f"unknown cold policy {cold_policy!r} (want 'compile' "
                "or 'reject')")
        self.cold_policy = cold_policy

    def _artifact_problems(self, signature):
        """Version/flag mismatches of the newest store artifact for
        ``signature`` (``None`` when no store or no artifact)."""
        if self.store is None:
            return None
        metas = self.store.entries(label=signature)
        if not metas:
            return None
        return self.store._mismatches(metas[0])

    def admit(self, request):
        """The admission decision for one request (no queue side
        effects — the service enqueues on a positive verdict). With a
        capacity monitor attached, an admitted verdict additionally
        passes the memory budget (:meth:`_capacity_verdict`)."""
        entry = self.pool.get(request.signature)
        verdict = self._base_verdict(request, entry)
        if verdict.admitted and self.capacity is not None:
            capacity_verdict = self._capacity_verdict(request, entry)
            if capacity_verdict is not None:
                return capacity_verdict
        return verdict

    def _base_verdict(self, request, entry):
        if entry is not None:
            problems = self._artifact_problems(request.signature)
            if not entry.fingerprint_ok():
                return ColdSignature(
                    request, self.cold_policy == "compile", False,
                    reason="stale warm-pool entry (compiler stack or "
                           "scheduler flags changed since arm)",
                    fingerprint=entry.fingerprint,
                    fingerprint_ok=False)
            if problems:
                return ColdSignature(
                    request, self.cold_policy == "compile", False,
                    reason="stale AOT artifact: " + "; ".join(problems),
                    fingerprint=entry.fingerprint,
                    fingerprint_ok=False)
            return AdmissionVerdict(
                request, True, True,
                reason="warm pool hit",
                fingerprint=entry.fingerprint, fingerprint_ok=True)
        admitted = self.cold_policy == "compile"
        return ColdSignature(
            request, admitted, False,
            reason=("cold signature: no warm-pool entry for "
                    f"{request.signature!r}"
                    + ("" if admitted
                       else " (policy rejects cold signatures)")))

    def _capacity_verdict(self, request, entry):
        """``None`` when the request fits the memory budget (or the
        budget is unknowable — the monitor admits honestly); a
        :class:`CapacityExceeded` rejection otherwise. The ``evict``
        policy drops other idle armed entries oldest-first and
        re-checks before giving up."""
        monitor = self.capacity
        predicted = monitor.candidate_bytes(request.signature, entry)
        decision = monitor.admission_check(request.signature, predicted)
        if not decision["admitted"] and monitor.policy == "evict":
            victims = sorted(
                (sig for sig in self.pool.signatures()
                 if sig != str(request.signature)),
                key=lambda sig: self.pool.get(sig).armed_ts)
            for sig in victims:
                evicted = self.pool.evict(sig)
                monitor.note_evicted(sig)
                _events.emit(
                    "capacity_evict", signature=sig,
                    for_signature=request.signature,
                    fingerprint=getattr(evicted, "fingerprint", None),
                    resident_bytes=monitor.resident_bytes())
                decision = monitor.admission_check(
                    request.signature, predicted)
                if decision["admitted"]:
                    break
        if decision["admitted"]:
            return None
        _events.emit(
            "capacity_reject", id=request.id, tenant=request.tenant,
            signature=request.signature,
            predicted_bytes=decision.get("predicted_bytes"),
            resident_bytes=decision.get("resident_bytes"),
            capacity_bytes=decision.get("capacity_bytes"),
            budget_bytes=decision.get("budget_bytes"),
            headroom=decision.get("headroom"),
            policy=decision.get("policy"),
            reason=decision.get("reason"))
        return CapacityExceeded(
            request, False, entry is not None,
            reason=decision.get("reason", "capacity exceeded"),
            fingerprint=getattr(entry, "fingerprint", None))
