"""Retire-time streamed analytics: results leave as events, never as
full field states.

A production service cannot ship multi-gigabyte final states back
through a request path — and almost no tenant wants them. What leaves
the service instead is one ``member_result`` event per retired member,
emitted incrementally at its retire point (the driver's one deliberate
sync), carrying:

- **per-field reductions** — mean / rms / max-abs per state leaf,
  computed on the retired host copy;
- **a spectrum summary** (optional) — the member's power spectrum
  through the configured :class:`~pystella_tpu.PowerSpectra` (on a
  multi-device service mesh that is the fused pencil path of PR 10:
  one dispatch, transform + |f(k)|² weighting + binning fused),
  summarized as bin count, total power, and the peak bin — never the
  raw field;
- **request provenance** — tenant, signature, status
  (``completed`` / ``diverged``), total steps, queue latency,
  time-to-first-step, and the warm/cold admission tag, so the ledger's
  ``service`` section can split its SLO metrics without re-joining
  event streams;
- **the deadline verdict** — retire time is where a deadline is won or
  lost, so the retire event is where it is counted: every deadlined
  request's record carries ``deadline_ts`` and ``margin_s``
  (``deadline_ts - retire_ts`` — positive on a hit, negative on a
  miss, recorded EITHER WAY so hit margins are as auditable as
  misses), and a miss additionally emits a ``deadline_missed`` event.
  The ledger's ``latency`` section derives the per-priority-class
  miss rates from these and the gate's deadline-miss SLO fails CI on
  a regression (``doc/service.md``).

Every ``member_result`` also closes its request's trace (obs schema
v2): the event carries the request's ``trace``/span fields, so the
:class:`~pystella_tpu.obs.spans.SpanAssembler` reads it as the root
span's end.
"""

from __future__ import annotations

import time

import numpy as np

from pystella_tpu.obs import events as _events

__all__ = ["ResultEmitter"]


def _reductions(state):
    out = {}
    for name, leaf in state.items():
        arr = np.asarray(leaf)
        out[str(name)] = {
            "mean": float(arr.mean()),
            "rms": float(np.sqrt(np.mean(np.square(arr)))),
            "max_abs": float(np.max(np.abs(arr))),
        }
    return out


class ResultEmitter:
    """Per-member result emission (module docstring).

    :arg spectra: optional ``spectra(field) -> bins`` callable (a
        :class:`~pystella_tpu.PowerSpectra` qualifies) applied to one
        field of the retired state.
    :arg spectra_field: the state key to transform (default: the
        first key, sorted).
    :arg label: tag carried on every event.
    """

    def __init__(self, spectra=None, spectra_field=None,
                 label="service"):
        self.spectra = spectra
        self.spectra_field = spectra_field
        self.label = str(label)
        #: every emitted record, newest last (host-side bookkeeping
        #: only — the events are the product)
        self.records = []

    def _spectrum_summary(self, state):
        if self.spectra is None:
            return None
        field = self.spectra_field
        if field is None:
            field = sorted(state)[0]
        try:
            bins = np.asarray(self.spectra(state[field]))
        except Exception as e:  # noqa: BLE001 — analytics are best-effort
            return {"error": f"{type(e).__name__}: {e}"}
        flat = bins.reshape(-1, bins.shape[-1]) if bins.ndim > 1 \
            else bins.reshape(1, -1)
        mean_bins = flat.mean(axis=0)
        return {
            "field": str(field),
            "nbins": int(bins.shape[-1]),
            "total_power": float(mean_bins.sum()),
            "peak_bin": int(np.argmax(mean_bins)),
        }

    def emit(self, request, state, status="completed", lease=None,
             diverged_fields=None):
        """Emit one ``member_result`` for ``request``'s retired host
        ``state`` (``state`` may be ``None`` for a diverged member
        whose trajectory is not worth reducing); returns the record.
        Retire time is also the deadline verdict: a deadlined request
        records its ``margin_s`` hit or miss, and a miss emits the
        ``deadline_missed`` event the miss-rate SLO counts."""
        retire_ts = time.time()
        request.retire_ts = retire_ts
        record = {
            "id": request.id,
            "tenant": request.tenant,
            "signature": request.signature,
            "label": self.label,
            "lease": lease,
            "status": str(status),
            "steps": int(request.nsteps),
            "seed": request.seed,
            "priority": request.priority,
            "warm": request.warm,
            "queue_latency_s": request.queue_latency_s,
            "ttfs_s": request.ttfs_s,
        }
        deadline_ts = getattr(request, "deadline_ts", None)
        if deadline_ts is not None:
            request.margin_s = float(deadline_ts) - retire_ts
            request.deadline_missed = request.margin_s < 0.0
            record["deadline_ts"] = float(deadline_ts)
            record["margin_s"] = round(request.margin_s, 6)
            record["deadline_missed"] = request.deadline_missed
        if diverged_fields:
            record["diverged_fields"] = sorted(diverged_fields)
        if state is not None:
            record["reductions"] = _reductions(state)
            spectrum = self._spectrum_summary(state)
            if spectrum is not None:
                record["spectrum"] = spectrum
        self.records.append(record)
        with _events.tracing(trace=getattr(request, "trace_id", None),
                             parent=getattr(request, "span_id", None)):
            _events.emit("member_result", **record)
            if record.get("deadline_missed"):
                _events.emit("deadline_missed", id=request.id,
                             tenant=request.tenant,
                             priority=request.priority,
                             deadline_ts=record["deadline_ts"],
                             margin_s=record["margin_s"],
                             status=str(status), label=self.label)
        return record
