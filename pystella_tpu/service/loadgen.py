"""Seeded synthetic load for the scenario service.

``run()`` stands up a :class:`~pystella_tpu.service.ScenarioService`
around a small scalar-preheating model and drives it with a
deterministic multi-tenant request mix that exercises every policy leg
in one pass — the tier-1 proof (``bench.py --smoke`` wires it in; the
TPU-window ``service`` leg scales it up):

- **mixed tenants and priorities**: three tenants with 2:1:1 fair-share
  weights submit priority-1 work against one WARM signature (armed
  before any submission — those requests' time-to-first-step is pure
  dispatch, proven by the lease's ``backend_compiles == 0``);
- **one forced cold signature**: a request for a lattice no pool entry
  serves, handled per the cold policy (default: admitted queued behind
  the build+compile, its TTFS visibly paying it);
- **one forced preemption**: a priority-3 request arrives (via
  ``schedule_arrival``) while the first priority-1 lease is mid-flight;
  the lease drains to a durable checkpoint, the high-priority request
  is served next, and the preempted members resume bit-consistently —
  ``run()`` re-verifies that against an uninterrupted replay through
  the same warm program and reports ``preempt_bitexact``;
- **one quota rejection**: the heaviest tenant submits one request past
  its admission quota;
- **one certain capacity rejection**: after arming, the capacity
  monitor's budget (:mod:`pystella_tpu.obs.capacity`) is pinned to a
  deterministic multiple of the resident predicted footprint, and a
  seeded "hog" signature whose recorded footprint is TWICE the whole
  budget is submitted — ``CapacityExceeded`` by construction, so every
  smoke record carries one memory-aware rejection (and, at retire,
  per-tenant chip-second accounts with healthy goodput for the
  tenants that ran);
- **one certain SLO burn alert**: a seeded
  :class:`~pystella_tpu.obs.slo.SLOMonitor` rides the run
  (:func:`seeded_slo_monitor`) with its ``deadline_miss`` leg windowed
  to the last sample — bravo's impossible 20 ms deadline fires
  ``slo_alert`` at its guaranteed miss, charlie's unmissable 60 s
  deadline resolves it at the next retire, so BOTH live-alert
  transitions land in every smoke record deterministically (the
  queue/TTFS legs run with deliberately generous objectives so only
  the seeded leg can fire). The monitor's ingest cost is measured and
  reported (``slo.ingest_s``) — the emit-path overhead pin.

Everything lands in the configured event log; the perf ledger's
``service``/``latency``/``alerts`` sections and the gate's SLO + alert
verdicts consume it from there.

:func:`run_fleet` is the fleet-plane counterpart: a deterministic
TWO-replica drill — two in-process services with their own ephemeral
live endpoints and registry records, a split tenant mix, a
:class:`~pystella_tpu.obs.fleet.FleetAggregator` federating both, and
one replica killed mid-run (no tombstone) so the aggregator's expiry
path, the ``fleet_replica_lost`` record, and the unresolved
``dead_replicas`` fleet alert are all produced by real machinery in a
seconds-long run. The ledger's ``fleet`` section and the gate's fleet
verdicts are pinned against exactly this record in tier-1.

:func:`run_perf` is the continuous-performance counterpart
(:mod:`pystella_tpu.obs.perf`): a seeded sleep-in-step drill with two
injected sustained slowdowns that must fire ``perf_anomaly`` (with
straggler attribution), write exactly one rate-limited flight-recorder
capture, recover (``perf_recovered``), and fire+resolve the
``perf_regression`` SLO leg — the tier-1 proof of the whole plane in
about a second.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from pystella_tpu.obs import events as _events
from pystella_tpu.obs import slo as _slo
from pystella_tpu.service.admission import request_signature
from pystella_tpu.service.queue import (
    FairShareScheduler, ScenarioRequest)
from pystella_tpu.service.results import ResultEmitter
from pystella_tpu.service.server import ScenarioService

__all__ = ["run", "run_fleet", "run_perf", "build_preheat_model",
           "seeded_slo_monitor", "seeded_fleet_legs",
           "seeded_perf_monitor"]


def seeded_slo_monitor(label="loadgen"):
    """The loadgen's deterministic SLO-monitor configuration: the
    ``deadline_miss`` leg is capped at the LAST deadline verdict
    (``window_samples=1``), so the mix's one guaranteed miss fires the
    alert and the next guaranteed hit resolves it — one certain
    fire+resolve pair per run, independent of wall-clock windows. The
    queue/TTFS legs keep running with objectives far above anything a
    smoke mix produces (they exist so the ingest path is exercised, not
    to fire), and the incident leg keeps its default (it fires only
    when a drill injects faults)."""
    return _slo.SLOMonitor(legs={
        "queue_p95": {"objective": 120.0},
        "warm_ttfs": {"objective": 120.0},
        "deadline_miss": {"window_samples": 1, "min_samples": 1},
        "incident_rate": {},
    }, label=label)


def seeded_fleet_legs():
    """The fleet drill's deterministic
    :class:`~pystella_tpu.obs.fleet.FleetAggregator` leg
    configuration, mirroring :func:`seeded_slo_monitor`: the
    ``deadline_miss`` leg is windowed to the last federated sample so
    replica-a's one guaranteed miss fires the FLEET alert and its one
    guaranteed hit resolves it within a single aggregation pass; the
    queue/TTFS legs run with objectives no smoke mix can breach (the
    federation ingest path is exercised, they never fire); and
    ``dead_replicas`` keeps its zero bar — the killed replica's expiry
    is the drill's one certain unresolved fleet alert."""
    return {
        "queue_p95": {"objective": 120.0},
        "warm_ttfs": {"objective": 120.0},
        "deadline_miss": {"window_samples": 1, "min_samples": 1},
        "incident_rate": {},
        "dead_replicas": {},
    }


def build_preheat_model(dtype=np.float32):
    """The loadgen's scenario model: a 2-field scalar-preheating
    system on the generic XLA path (the same physics as ``bench.py``'s
    smoke payload, self-contained so the package needs no driver
    import). Returns the ``builder(grid_shape, decomp)`` the service's
    model registry wants."""

    def builder(grid_shape, decomp=None):
        import jax
        import pystella_tpu as ps

        lattice = ps.Lattice(grid_shape, (5.0,) * 3, dtype=dtype)
        dt = dtype(0.1 * min(lattice.dx))
        if decomp is None:
            decomp = ps.DomainDecomposition(
                (1, 1, 1), devices=jax.devices()[:1])
        mphi, gsq = 1.20e-6, 2.5e-7

        def potential(f):
            phi, chi = f[0], f[1]
            return (mphi**2 / 2 * phi**2
                    + gsq / 2 * phi**2 * chi**2) / mphi**2

        sector = ps.ScalarSector(2, potential=potential)
        derivs = ps.FiniteDifferencer(decomp, 2, lattice.dx)
        sector_rhs = ps.compile_rhs_dict(sector.rhs_dict)

        def full_rhs(state, t, a, hubble):
            return sector_rhs(state, t, lap_f=derivs.lap(state["f"]),
                              a=a, hubble=hubble)

        stepper = ps.LowStorageRK54(full_rhs, dt=dt)

        def sample(seed):
            rng = np.random.default_rng(1000 + seed)
            state = {
                "f": decomp.shard(1e-3 * rng.standard_normal(
                    (2,) + tuple(grid_shape)).astype(dtype)),
                "dfdt": decomp.shard(1e-4 * rng.standard_normal(
                    (2,) + tuple(grid_shape)).astype(dtype)),
            }
            return state, {"a": 1.0, "hubble": 0.5}

        return stepper, sample, float(dt)

    return builder


class _CapturingEmitter(ResultEmitter):
    """Result emitter that additionally keeps the retired host states
    (the loadgen's bit-consistency re-verification needs them; a real
    deployment never holds them — events are the product)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.states = {}

    def emit(self, request, state, **kwargs):
        if state is not None:
            self.states[request.id] = state
        return super().emit(request, state, **kwargs)


def _uninterrupted_reference(entry, request, slots, chunk):
    """Replay ``request`` uninterrupted through the SAME warm chunk
    program (same chunk size, ballast co-members): the reference the
    preempted-and-resumed trajectory must match bit for bit."""
    import jax

    state, draw = entry.sample(request.seed)
    template_state, template_draw = entry.template
    states = [state] + [template_state] * (slots - 1)
    batch = entry.stack(states)
    td = entry.tick_dtype
    dt_vec = np.full(slots, entry.dt, dtype=td)
    params = {}
    for n in entry.param_names:
        col = np.full(slots, float((template_draw or {}).get(n, 0.0)),
                      dtype=td)
        col[0] = float((draw or {}).get(n, 0.0))
        params[n] = col
    n_chunks = -(-request.nsteps // chunk)
    start = np.zeros(slots, dtype=np.int64)
    for i in range(n_chunks):
        t_vec = ((start + i * chunk) * dt_vec).astype(td)
        batch, _m = entry.ens.multi_step(
            batch, chunk, t=t_vec, dt=dt_vec, rhs_args=params,
            sentinel=entry.sentinel)
    jax.block_until_ready(batch)
    return entry.ens.take_member(batch, 0)


def run(checkpoint_dir, seed=0, slots=None, chunk=None, grid=16,
        cold_grid=12, nsteps=8, quota=3, label="loadgen",
        spectra=True, faults=None, store=None, slo=None,
        capacity=None):
    """Drive one full synthetic service run (module docstring).
    Returns the stats dict (also emitted as a ``service_loadgen``
    event). ``grid``/``cold_grid`` are the warm/cold lattice edges;
    ``nsteps`` the per-request step budget (a multiple of the chunk
    keeps retire boundaries aligned); ``faults`` threads a
    FaultInjector into every lease's supervisor (drills); ``slo`` an
    :class:`~pystella_tpu.obs.slo.SLOMonitor` override (default: the
    :func:`seeded_slo_monitor`; ``False`` disables the live monitor
    entirely, restoring the pre-live event record byte for byte);
    ``capacity`` a :class:`~pystella_tpu.obs.capacity.CapacityMonitor`
    override (``False`` disables the capacity plane — no budget pin,
    no hog submission, no chip-second attribution)."""
    import pystella_tpu as ps

    rng = np.random.default_rng(seed)
    warm_sig = request_signature("preheat", (grid,) * 3)
    cold_sig = request_signature("preheat", (cold_grid,) * 3)

    if slo is None:
        slo = seeded_slo_monitor(label=label)
    elif slo is False:
        slo = None
    scheduler = FairShareScheduler(
        quota=quota, weights={"alpha": 2.0, "bravo": 1.0,
                              "charlie": 1.0})
    results = _CapturingEmitter(label=label)
    service = ScenarioService(checkpoint_dir, slots=slots, chunk=chunk,
                              scheduler=scheduler, results=results,
                              store=store, faults=faults, slo=slo,
                              capacity=capacity, label=label)
    service.register_model("preheat", build_preheat_model())

    # deploy-time arming: the warm signature's program is traced,
    # compiled, and dispatched once HERE — before any request exists,
    # so no request's latency ever contains it
    service.arm(warm_sig)
    # the capacity drill: pin a deterministic HBM budget AFTER the
    # warm program is armed — the resident footprint (plus the cold
    # build) fits with a wide margin, while a seeded "hog" signature
    # whose recorded footprint is twice the WHOLE budget cannot fit
    # under any headroom, so exactly one CapacityExceeded rejection
    # lands in every run regardless of lattice sizes or backend
    hog_sig = request_signature("preheat", (grid * 4,) * 3)
    cap_budget = None
    if service.capacity is not None:
        cap_budget = int(max(service.capacity.resident_bytes(), 1) * 64)
        service.capacity.capacity_bytes = cap_budget
        service.capacity.ledger.record(
            f"service.{hog_sig}", fingerprint="loadgen-hog",
            predicted_bytes=2 * cap_budget, source="aval_estimate",
            persist=False)
    if spectra:
        # retire-time per-member spectra through the planner-selected
        # transform tier (the fused pencil path whenever the service
        # mesh makes it feasible; the single-device smoke mesh serves
        # the same fused spectrum program through the DFT tier)
        entry = service.pool.get(warm_sig)
        sdec = entry.decomp or _default_decomp()
        lat = ps.Lattice((grid,) * 3, (5.0,) * 3, dtype=np.float32)
        fft = ps.make_dft(sdec, grid_shape=(grid,) * 3,
                          dtype=np.float32)
        results.spectra = ps.PowerSpectra(sdec, fft, lat.dk, lat.volume)
        results.spectra_field = "f"

    # the mix: priority-1 warm work across three tenants (alpha twice
    # the weight), one over-quota submission, one cold signature, and
    # a priority-3 arrival one chunk into the first lease. Two
    # requests carry deadlines, one of each verdict BY CONSTRUCTION:
    # bravo's 20 ms deadline cannot survive even a warm lease (the
    # seeded deadline MISS the latency section and the gate's
    # miss-rate SLO pin in tier-1), charlie's 60 s cannot be missed by
    # a smoke mix — so both margin polarities are exercised every run
    mix = [
        ScenarioRequest("alpha", warm_sig, nsteps, seed=1),
        ScenarioRequest("bravo", warm_sig, nsteps, seed=2,
                        deadline_s=0.02),
        ScenarioRequest("alpha", warm_sig, nsteps, seed=3),
        ScenarioRequest("charlie", warm_sig, nsteps, seed=4,
                        deadline_s=60.0),
        ScenarioRequest("alpha", warm_sig, nsteps, seed=5),
        ScenarioRequest("bravo", warm_sig, nsteps, seed=6),
        # over quota: alpha already holds `quota` queued requests
        ScenarioRequest("alpha", warm_sig, nsteps, seed=7),
        # the forced cold signature (no pool entry for cold_grid)
        ScenarioRequest("bravo", cold_sig, nsteps,
                        seed=int(rng.integers(100))),
    ]
    verdicts = [service.submit(r) for r in mix]
    hog_verdict = None
    if service.capacity is not None:
        # the certain CapacityExceeded: charlie is under quota, the
        # signature's recorded footprint is 2x the budget — the BASE
        # verdict admits, the capacity verdict must refuse
        hog = ScenarioRequest("charlie", hog_sig, nsteps, seed=99)
        hog_verdict = service.submit(hog)
    high = ScenarioRequest("charlie", warm_sig, nsteps,
                           seed=8, priority=3)
    service.schedule_arrival(1, high)

    t_serve0 = time.perf_counter()
    summary = service.serve()
    serve_wall_s = time.perf_counter() - t_serve0

    # bit-consistency re-verification: every preempted-and-resumed
    # request's final state must equal its uninterrupted replay
    # through the same warm chunk program
    entry = service.pool.get(warm_sig)
    preempted_ids = [r.id for r in mix + [high]
                     if r.resume_step > 0]
    bitexact = None
    for rid in preempted_ids:
        req = next(r for r in mix + [high] if r.id == rid)
        got = results.states.get(rid)
        if got is None:
            bitexact = False
            break
        ref = _uninterrupted_reference(entry, req, service.slots,
                                       service.chunk)
        ok = all(np.array_equal(np.asarray(got[k]),
                                np.asarray(ref[k])) for k in ref)
        bitexact = ok if bitexact is None else (bitexact and ok)

    deadlined = [r for r in mix + [high]
                 if r.deadline_missed is not None]
    stats = {
        **summary,
        "requests": len(mix) + 1 + (1 if hog_verdict is not None
                                    else 0),
        "warm_admissions": sum(1 for v in verdicts
                               if v.admitted and v.warm),
        "cold_admissions": sum(1 for v in verdicts
                               if v.admitted and not v.warm),
        "preempted_requests": len(preempted_ids),
        "preempt_bitexact": bitexact,
        "deadlined_requests": len(deadlined),
        "deadline_misses": sum(1 for r in deadlined
                               if r.deadline_missed),
        # one trace id per request, end to end: the preempted requests
        # prove trace survival across requeue (their several
        # service_dispatch events share the id)
        "traces": sorted(
            r.trace_id for r in mix + [high]
            + ([hog] if hog_verdict is not None else [])
            if r.trace_id is not None),
        "serve_wall_s": round(serve_wall_s, 4),
    }
    if service.capacity is not None:
        stats["capacity"] = {
            "budget_bytes": cap_budget,
            "hog_rejected": bool(
                hog_verdict is not None
                and getattr(hog_verdict, "kind", None)
                == "capacity_exceeded"),
            "resident_predicted_bytes":
                service.capacity.resident_bytes(),
            "watermark_samples": len(service.capacity.watermarks),
        }
        state = slo.state()
        stats["slo"] = {
            "alerts": state["alerts_total"],
            "resolved": state["resolved_total"],
            "flaps": state["flaps_total"],
            "alerting": state["alerting"],
            "ingested": state["ingested"],
            "ingest_s": state["ingest_s"],
            # the emit-path overhead pin: the monitor's whole ingest
            # cost as a share of the serve wall (< 2% by contract)
            "overhead_pct": round(100.0 * state["ingest_s"]
                                  / max(serve_wall_s, 1e-9), 4),
        }
    _events.emit("service_loadgen", seed=seed, **stats)
    return stats


def run_fleet(workdir, grid=12, nsteps=4, slots=1, chunk=2,
              heartbeat_s=0.1, expire_s=0.5, label="fleet-drill"):
    """The deterministic two-replica fleet drill (module docstring).

    Two in-process :class:`~pystella_tpu.service.ScenarioService`
    replicas (``replica-a``, ``replica-b``) serve a split tenant mix
    — a: ``alpha``/``bravo`` with both deadline polarities (the
    seeded SLO story of :func:`run`), b: ``delta``/``echo`` — each
    with its own ephemeral live endpoint (``live_port="auto"``) and
    registry record under ``<workdir>/registry``. The orchestration
    rides the event log's synchronous subscriber channel: a
    subscriber callback BLOCKS a replica's serve thread at a chosen
    event (b at its first retire, a at its ``service_done``, which is
    emitted while the live plane is still up), so the aggregation
    passes run against two replicas that are provably mid-serve —
    no sleep-and-hope scheduling.

    The drill then takes b down in the shape of a real wedge-then-
    crash: its endpoint closes first and one scrape records the
    live-but-unreachable failure against the still-beating record
    (the failed-scrape evidence), then the crash seam
    (:meth:`~pystella_tpu.service.registry.ReplicaRegistry.kill` — no
    tombstone) stops the heartbeats, and the drill scrapes past the
    expiry until the aggregator declares b LOST (reason
    ``"expired"``): ``fleet_replica_lost`` plus the unresolved
    ``dead_replicas`` fleet alert. Replica a withdraws
    cleanly (tombstone), so the final registry distinguishes the
    shutdown from the crash. Returns the stats dict (also emitted as
    ``fleet_loadgen``); every ``fleet_*`` event lands in the
    configured event log for the ledger's ``fleet`` section and the
    gate's fleet verdicts.

    ``heartbeat_s``/``expire_s`` default to drill-fast values (0.1 s
    beats, 0.5 s expiry) — the production defaults live in the
    registered ``PYSTELLA_FLEET_*`` knobs.
    """
    from pystella_tpu.obs import fleet as _fleet
    from pystella_tpu.service import registry as _registry

    t0 = time.perf_counter()
    workdir = os.path.abspath(str(workdir))
    registry_dir = os.path.join(workdir, "registry")
    env_names = ("PYSTELLA_FLEET_DIR", "PYSTELLA_FLEET_HEARTBEAT_S")
    # the services read both knobs through config.getenv at serve
    # time; two in-process replicas share the process env, so the
    # drill pins it for the duration and restores the caller's values
    # env-registry: PYSTELLA_FLEET_DIR, PYSTELLA_FLEET_HEARTBEAT_S
    prior = {n: os.environ.get(n) for n in env_names}
    os.environ["PYSTELLA_FLEET_DIR"] = registry_dir
    os.environ["PYSTELLA_FLEET_HEARTBEAT_S"] = str(float(heartbeat_s))

    warm_sig = request_signature("preheat", (grid,) * 3)
    svc_a = ScenarioService(
        os.path.join(workdir, "ckpt-a"), slots=slots, chunk=chunk,
        slo=seeded_slo_monitor(label="replica-a"),
        label="replica-a", live_port="auto", fleet_id="replica-a")
    # replica-b carries NO deadline leg: its monitor sees replica-a's
    # retire events through the shared process log, and a second copy
    # of the deadline samples on b's /slo would federate as a
    # fire/resolve/fire flap at fleet level
    svc_b = ScenarioService(
        os.path.join(workdir, "ckpt-b"), slots=slots, chunk=chunk,
        slo=_slo.SLOMonitor(legs={
            "queue_p95": {"objective": 120.0},
            "warm_ttfs": {"objective": 120.0},
            "incident_rate": {},
        }, label="replica-b"),
        label="replica-b", live_port="auto", fleet_id="replica-b")
    for svc in (svc_a, svc_b):
        svc.register_model("preheat", build_preheat_model())
        svc.arm(warm_sig)

    # the pause points: a subscriber callback runs synchronously on
    # the EMITTING thread, so waiting on a gate inside it holds that
    # replica's serve loop at the event — mid-lease for b, live-plane-
    # still-up for a — while the main thread aggregates
    b_seen, b_gate = threading.Event(), threading.Event()
    a_done, a_gate = threading.Event(), threading.Event()

    def orchestrate(rec):
        kind = rec.get("kind")
        data = rec.get("data") or {}
        if (kind == "member_result"
                and data.get("label") == "replica-b"
                and not b_seen.is_set()):
            b_seen.set()
            b_gate.wait(timeout=120.0)
        elif (kind == "service_done"
                and data.get("label") == "replica-a"
                and not a_done.is_set()):
            a_done.set()
            a_gate.wait(timeout=120.0)

    _events.get_log().subscribe(orchestrate)
    summaries, errors = {}, {}

    def serve_in_thread(name, svc):
        try:
            summaries[name] = svc.serve()
        except Exception as e:  # noqa: BLE001 — reported after join
            errors[name] = e

    thread_a = thread_b = None
    try:
        # -- replica-b up first: mid-serve by its first retire --------
        for req in (ScenarioRequest("delta", warm_sig, nsteps, seed=21),
                    ScenarioRequest("echo", warm_sig, nsteps, seed=22)):
            svc_b.submit(req)
        thread_b = threading.Thread(
            target=serve_in_thread, args=("b", svc_b),
            name="fleet-drill-b", daemon=True)
        thread_b.start()
        if not b_seen.wait(timeout=120.0):
            raise RuntimeError(
                "fleet drill: replica-b never retired a member")

        # -- replica-a: the seeded deadline mix (slots=1 leases the
        # requests one at a time; fair-share picks bravo's EDF-first
        # miss, then alpha, then bravo's hit — miss fires the alert,
        # hit resolves it, deterministically)
        for req in (ScenarioRequest("bravo", warm_sig, nsteps, seed=11,
                                    deadline_s=0.02),
                    ScenarioRequest("alpha", warm_sig, nsteps, seed=12),
                    ScenarioRequest("bravo", warm_sig, nsteps, seed=13,
                                    deadline_s=60.0)):
            svc_a.submit(req)
        thread_a = threading.Thread(
            target=serve_in_thread, args=("a", svc_a),
            name="fleet-drill-a", daemon=True)
        thread_a.start()
        if not a_done.wait(timeout=120.0):
            raise RuntimeError(
                "fleet drill: replica-a never finished its mix")

        # -- aggregation pass 1: both replicas provably live ----------
        agg = _fleet.FleetAggregator(
            registry_dir=registry_dir, expire_s=expire_s,
            legs=seeded_fleet_legs(), label=label)
        both_live = agg.scrape()
        queue_gauge_replicas = sorted(
            both_live["gauges"].get("pystella_service_queue_depth", {}))

        # -- the mid-run kill, staged like a real wedge-then-crash:
        # b's endpoint dies first (close blocks ~0.5 s on the serve
        # poll, so the record KEEPS beating past it), one scrape
        # records the live-but-unreachable failure, then the crash
        # seam stops the heartbeats; b's serve loop drains out
        svc_b.live_server.close()
        agg.scrape()
        svc_b.fleet_registry.kill()
        b_gate.set()
        thread_b.join(timeout=120.0)

        # -- expiry: b's record goes stale, the aggregator declares it
        # LOST and the dead_replicas fleet alert fires (unresolved)
        time.sleep(expire_s + 0.3)
        final = agg.scrape()
        for _ in range(50):
            if final["dead"]:
                break
            time.sleep(0.1)
            final = agg.scrape()

        # -- replica-a withdraws cleanly (tombstone) ------------------
        a_gate.set()
        thread_a.join(timeout=120.0)
        if errors:
            name, err = sorted(errors.items())[0]
            raise RuntimeError(
                f"fleet drill: replica-{name} serve failed: {err}") \
                from err
    finally:
        # release any still-held gate before unwinding so a failed
        # drill cannot leave a serve thread parked in the subscriber
        b_gate.set()
        a_gate.set()
        _events.get_log().unsubscribe(orchestrate)
        for name, value in prior.items():
            if value is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = value

    records = _registry.read_records(registry_dir, expire_s=expire_s)
    stats = {
        "label": label,
        "registry_dir": registry_dir,
        "replicas": ["replica-a", "replica-b"],
        "killed": "replica-b",
        "completed": {
            "replica-a": summaries.get("a", {}).get("completed"),
            "replica-b": summaries.get("b", {}).get("completed")},
        "live_both_pass": both_live["live"],
        "queue_gauge_replicas": queue_gauge_replicas,
        "scrapes": final["scrapes"],
        "endpoint_ok": final["endpoint_ok"],
        "endpoint_failed": final["endpoint_failed"],
        "scrape_success_rate": final["scrape_success_rate"],
        "lost": final["lost"],
        "dead": final["dead"],
        "alerts": final["alerts_total"],
        "resolved": final["resolved_total"],
        "flaps": final["flaps_total"],
        "alerting": final["alerting"],
        "legs": {name: {"value_fast": leg.get("value_fast"),
                        "bar": leg.get("bar"),
                        "n_slow": leg.get("n_slow"),
                        "alerting": leg.get("alerting")}
                 for name, leg in final["legs"].items()},
        "skewed": final["skew"]["skewed"],
        "divergent": sorted(final["divergence"]["divergent"]),
        "registry": {r["replica"]: r["status"] for r in records},
        "wall_s": round(time.perf_counter() - t0, 4),
    }
    _events.emit("fleet_loadgen", **stats)
    return stats


def seeded_perf_monitor(recorder, label="perf-drill"):
    """The perf drill's deterministic
    :class:`~pystella_tpu.obs.perf.PerfMonitor` configuration: a short
    baseline window (16 samples, armed after 8) so a seconds-long
    drill trains it, ``k=1``/``h=8`` with the standard 4-sigma
    increment clip — a 5x injected slowdown saturates the clip, so the
    detector fires on the THIRD consecutive slow step (ceil(8/4)=2
    full-clip increments plus one more crosses h=8) while a single
    container-scheduler stall (one clipped increment, then decay)
    cannot — and six consecutive in-band steps recover it."""
    from pystella_tpu.obs import perf as _perf
    return _perf.PerfMonitor(window=16, min_samples=8, k=1.0, h=8.0,
                             recover_n=6, recorder=recorder,
                             digest_every=32, label=label)


def run_perf(capture_dir, base_ms=5.0, slow_ms=25.0, healthy=30,
             slow=12, cooldown=20, capture_steps=4, cooldown_s=3600.0,
             seed=0, label="perf-drill", tracer=None):
    """The seeded continuous-performance drill: a sleep-in-step loop
    through a real :class:`~pystella_tpu.utils.profiling.StepTimer`
    with TWO injected sustained slowdowns, proving the whole plane in
    about a second of wall time:

    - ``healthy`` steps of ``base_ms`` sleeps train the detector's
      baseline, then ``slow`` steps of ``slow_ms`` (5x) MUST fire
      ``perf_anomaly`` — with straggler attribution in the payload —
      and start the flight recorder, which writes a real
      ``jax.profiler`` Perfetto artifact over the next
      ``capture_steps`` steps (``tracer`` overrides the backend for
      tests);
    - ``cooldown`` healthy steps recover it (``perf_recovered``);
    - a SECOND identical slowdown fires again, but the recorder's
      ``cooldown_s`` rate limit (default: far longer than the drill)
      suppresses its capture — exactly one artifact per drill, plus a
      recorded suppression count: the rate-limiting proof;
    - a seeded :class:`~pystella_tpu.obs.slo.SLOMonitor` rides the
      run with only the ``perf_regression`` leg, windowed to the last
      transition sample, so the anomaly fires ``slo_alert`` and the
      recovery resolves it deterministically.

    The StepTimer emits per-step ``step_time`` events, so the event
    log ingests into a complete :class:`~pystella_tpu.obs.ledger.
    PerfLedger` report whose ``perf`` section links the capture — the
    record the gate's ``check_perf`` audit consumes. Returns the stats
    dict (also emitted as ``perf_loadgen``), ``stats["ok"]`` rolling
    up the acceptance pins above."""
    from pystella_tpu.obs import perf as _perf
    from pystella_tpu.utils.profiling import StepTimer

    t0 = time.perf_counter()
    rng = np.random.default_rng(seed)
    recorder = _perf.FlightRecorder(
        capture_dir, steps=capture_steps, cooldown_s=cooldown_s,
        tracer=tracer, label=label)
    monitor = seeded_perf_monitor(recorder, label=label)
    slo = _slo.SLOMonitor(legs={
        "perf_regression": {"window_samples": 1, "min_samples": 1},
    }, label=label)
    _events.get_log().subscribe(slo.handle)
    timer = StepTimer(report_every=1e9, emit_steps=True,
                      signature="drill", perf=monitor)
    # the schedule: healthy/slow/healthy/slow/healthy, the jitter
    # seeded so the healthy phases are not a constant series (the
    # detector must stay quiet on realistic noise, not on zeros)
    plan = ([base_ms] * healthy + [slow_ms] * slow
            + [base_ms] * cooldown + [slow_ms] * slow
            + [base_ms] * cooldown)
    try:
        timer.tick()                      # arms the inter-step clock
        for ms in plan:
            time.sleep((ms + float(rng.uniform(0.0, 0.2))) * 1e-3)
            timer.tick()
        recorder.flush()                  # close a still-open capture
        slo.evaluate()
    finally:
        _events.get_log().unsubscribe(slo.handle)
    mstate = monitor.state()
    det = mstate["signatures"].get("drill") or {}
    sstate = slo.state()
    captures = recorder.captures
    artifact = captures[0].get("artifact") if captures else None
    straggler = None
    if det.get("fires"):
        # re-derive the attribution the anomaly payload carried
        straggler = monitor._attribution(  # noqa: SLF001 — drill introspection
            monitor._sigs["drill"]["recent"])
    stats = {
        "label": label,
        "steps": len(plan),
        "anomalies": int(det.get("fires") or 0),
        "recovered": int(det.get("recoveries") or 0),
        "anomalous_at_exit": bool(det.get("anomalous")),
        "captures": len(captures),
        "artifact": artifact,
        "suppressed": recorder.suppressed,
        "capture_errors": recorder.errors,
        "straggler": straggler,
        "digest": {k: det.get(k) for k in
                   ("count", "p50_ms", "p95_ms", "p99_ms")},
        "slo": {
            "alerts": sstate["alerts_total"],
            "resolved": sstate["resolved_total"],
            "alerting": sstate["alerting"],
        },
        "observe_s": mstate["observe_s"],
        "wall_s": round(time.perf_counter() - t0, 4),
    }
    stats["ok"] = bool(
        stats["anomalies"] >= 2
        and stats["recovered"] == stats["anomalies"]
        and not stats["anomalous_at_exit"]
        and stats["captures"] == 1
        and artifact is not None
        and stats["suppressed"] >= 1
        and stats["slo"]["alerts"] >= 1
        and not stats["slo"]["alerting"]
        and straggler is not None)
    _events.emit("perf_loadgen", **stats)
    return stats


def _default_decomp():
    import jax
    import pystella_tpu as ps
    return ps.DomainDecomposition((1, 1, 1), devices=jax.devices()[:1])
