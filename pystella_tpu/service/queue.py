"""Request ingestion and fair-share scheduling for the scenario service.

A :class:`ScenarioRequest` is one tenant's unit of work: a scenario
signature (model + lattice + mesh, :func:`pystella_tpu.service.
admission.request_signature`), a step budget, a seed, a priority class,
and an optional deadline. The :class:`FairShareScheduler` turns a
multi-tenant stream of them into lease-sized dispatch decisions:

- **priority classes dominate**: a dispatch always serves the highest
  priority class with queued work — and the service preempts a running
  lower-class lease when a higher class arrives
  (:mod:`pystella_tpu.service.server`).
- **weighted fair share across tenants** within a class: the scheduler
  keeps a per-tenant *deficit* (entitlement minus weighted work served;
  serving cost ``c`` to tenant ``t`` charges ``c / weight(t)``, and the
  counters are renormalized so the most-starved tenant sits at zero).
  Each slot goes to the largest-deficit tenant with a queued candidate,
  so a tenant with weight 2 gets twice the member-steps of a weight-1
  tenant under sustained load, and an idle tenant's first request is
  served promptly (its deficit never decayed).
- **deadline-aware ordering** within a tenant: earliest absolute
  deadline first (requests without one sort last), FIFO tiebreak.
- **per-tenant admission quotas**: a tenant may hold at most ``quota``
  queued requests; a submission beyond that raises
  :class:`QuotaExceeded` (the service turns it into a typed
  ``service_reject``) instead of letting one tenant starve the rest of
  the queue.
- **shape-compatible leases**: one lease is one batched program, so a
  dispatch only mixes requests sharing a signature — the first pick
  fixes it, later slots filter to it.

Preempted requests re-enter through :meth:`FairShareScheduler.requeue`
(no quota re-check — the work was already admitted; the original
``submit_ts`` is kept so queue-latency accounting reflects the true
wait).
"""

from __future__ import annotations

import itertools
import time

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events

__all__ = ["FairShareScheduler", "QuotaExceeded", "ScenarioRequest"]

_request_ids = itertools.count(1)


class QuotaExceeded(RuntimeError):
    """A tenant tried to queue more requests than its admission quota
    allows (``PYSTELLA_SERVICE_QUOTA`` / the scheduler's ``quota=``)."""


class ScenarioRequest:
    """One tenant's simulation request.

    :arg tenant: tenant name (fair-share and occupancy accounting key).
    :arg signature: the (model, lattice, mesh) scenario signature
        (:func:`~pystella_tpu.service.admission.request_signature`) —
        the warm-pool admission key.
    :arg nsteps: per-member step budget.
    :arg seed: IC sampler seed.
    :arg priority: priority class (larger = more urgent; classes
        strictly dominate each other in dispatch order, and a higher
        class preempts a running lower-class lease).
    :arg deadline_s: optional deadline in seconds FROM SUBMISSION;
        stored as an absolute ``deadline_ts`` at :meth:`submit
        <FairShareScheduler.submit>` time and used for EDF ordering
        within the tenant's queue.
    :arg label: free-form tag carried through events.

    The service fills the runtime fields (``id``, ``submit_ts``,
    ``dispatch_ts``, ``warm``, ``status``, ``resume_state``/
    ``resume_step`` for a preempted request, ...).
    """

    def __init__(self, tenant, signature, nsteps, seed=0, priority=1,
                 deadline_s=None, label=""):
        self.tenant = str(tenant)
        self.signature = str(signature)
        self.nsteps = int(nsteps)
        self.seed = int(seed)
        self.priority = int(priority)
        self.deadline_s = (None if deadline_s is None
                           else float(deadline_s))
        self.label = str(label)
        if self.nsteps < 1:
            raise ValueError("nsteps must be >= 1")
        # runtime bookkeeping (service-owned)
        self.id = next(_request_ids)
        self.status = "new"
        # the request-scoped trace context (obs schema v2): allocated
        # HERE, at the birth of the request, and carried through every
        # lease it rides — a preempted-and-requeued request keeps ONE
        # trace id, which is exactly what makes its cross-lease
        # latency attributable (obs.spans). PYSTELLA_TRACE_SERVICE=0
        # opts the whole layer out (events then stay v1-shaped).
        if _config.get_bool("PYSTELLA_TRACE_SERVICE"):
            self.trace_id = _events.new_trace_id()
            self.span_id = _events.new_span_id()
        else:
            self.trace_id = None
            self.span_id = None
        self.submit_ts = None
        self.deadline_ts = None
        self.retire_ts = None
        self.margin_s = None
        self.deadline_missed = None
        self.dispatch_ts = None
        self.queue_latency_s = None
        self.ttfs_s = None
        self.warm = None
        self.fingerprint = None
        self.fingerprint_ok = None
        self.params_draw = None
        self.resume_state = None
        self.resume_step = 0
        self.failures = 0

    @property
    def remaining_steps(self):
        """Steps still owed (shrinks when a preemption requeues the
        request with a restored trajectory)."""
        return max(0, self.nsteps - int(self.resume_step))

    def __repr__(self):
        return (f"ScenarioRequest(#{self.id} {self.tenant!r} "
                f"{self.signature!r} p{self.priority} "
                f"nsteps={self.nsteps} status={self.status!r})")


class FairShareScheduler:
    """Multi-tenant fair-share + priority + deadline scheduler (module
    docstring has the policy).

    :arg quota: per-tenant queued-request cap (default: the registered
        ``PYSTELLA_SERVICE_QUOTA``).
    :arg weights: ``{tenant: weight}`` fair-share weights (missing
        tenants weigh 1.0).
    """

    def __init__(self, quota=None, weights=None):
        if quota is None:
            quota = _config.get_int("PYSTELLA_SERVICE_QUOTA")
        self.quota = int(quota)
        self.weights = dict(weights or {})
        self._queue = []
        self._deficit = {}

    # -- introspection -------------------------------------------------------

    @property
    def pending(self):
        return len(self._queue)

    def queued_for(self, tenant):
        return sum(1 for r in self._queue if r.tenant == tenant)

    def weight(self, tenant):
        w = float(self.weights.get(tenant, 1.0))
        return w if w > 0 else 1.0

    def has_priority_above(self, priority):
        """A request of a STRICTLY higher class is waiting — the
        service's preemption trigger."""
        return any(r.priority > priority for r in self._queue)

    # -- ingestion -----------------------------------------------------------

    def submit(self, request, now=None):
        """Enqueue ``request`` (stamping ``submit_ts`` and the absolute
        deadline); raises :class:`QuotaExceeded` past the tenant's
        quota."""
        if self.queued_for(request.tenant) >= self.quota:
            raise QuotaExceeded(
                f"tenant {request.tenant!r} already holds "
                f"{self.queued_for(request.tenant)} queued request(s) "
                f"(quota {self.quota})")
        request.submit_ts = time.time() if now is None else float(now)
        if request.deadline_s is not None:
            request.deadline_ts = request.submit_ts + request.deadline_s
        request.status = "queued"
        self._queue.append(request)
        self._deficit.setdefault(request.tenant, 0.0)
        return request

    def requeue(self, request):
        """Re-enter a preempted request at its original ``submit_ts``
        (so the measured queue latency covers the full wait, preemption
        included). No quota re-check: the work was already admitted."""
        request.status = "queued"
        self._queue.append(request)
        self._deficit.setdefault(request.tenant, 0.0)
        return request

    # -- dispatch ------------------------------------------------------------

    def _charge(self, tenant, cost):
        """Weighted-deficit bookkeeping: serving ``cost`` member-steps
        to ``tenant`` consumes ``cost / weight`` of its entitlement;
        renormalize so the most-starved KNOWN tenant (every tenant that
        ever submitted holds an entry — ``submit`` seeds it) sits at
        deficit 0, keeping the counters bounded over an unbounded
        service lifetime."""
        self._deficit[tenant] = (self._deficit.get(tenant, 0.0)
                                 - cost / self.weight(tenant))
        top = max(self._deficit.values(), default=0.0)
        if top != 0.0:
            for t in self._deficit:
                self._deficit[t] -= top

    def dispatch(self, slots):
        """Pick up to ``slots`` requests for one lease: highest
        priority class, weighted fair share across tenants, EDF within
        a tenant, all sharing one signature (one batched program).
        Returns the picked requests (removed from the queue; possibly
        empty)."""
        if not self._queue or slots < 1:
            return []
        pclass = max(r.priority for r in self._queue)
        picked = []
        signature = None
        while len(picked) < slots:
            pool = [r for r in self._queue
                    if r.priority == pclass and r not in picked
                    and (signature is None
                         or r.signature == signature)]
            if not pool:
                break
            tenants = sorted({r.tenant for r in pool})
            tenant = max(tenants,
                         key=lambda t: (self._deficit.get(t, 0.0), t))
            mine = [r for r in pool if r.tenant == tenant]
            mine.sort(key=lambda r: (
                r.deadline_ts if r.deadline_ts is not None
                else float("inf"),
                r.submit_ts if r.submit_ts is not None else 0.0,
                r.id))
            req = mine[0]
            signature = signature if signature is not None \
                else req.signature
            picked.append(req)
            self._charge(tenant, max(1, req.remaining_steps))
        for r in picked:
            self._queue.remove(r)
        return picked
