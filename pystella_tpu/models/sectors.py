"""Physics sectors: symbolic equation systems for preheating simulations.

TPU-native counterpart of /root/reference/pystella/sectors.py:42-229. A
Sector bundles a symbolic ``rhs_dict`` (consumed by
:class:`~pystella_tpu.Stepper`), energy ``reducers`` (consumed by
:class:`~pystella_tpu.Reduction`), and a ``stress_tensor`` method (consumed
by :class:`TensorPerturbationSector`). Expressions evaluate against state
environments containing the field arrays plus auxiliary names (``lap_f``,
``dfdx``, ``a``, ``hubble``) supplied by the driver.
"""

from __future__ import annotations

import numpy as np

from pystella_tpu.field import DynamicField, Field, Var, diff

__all__ = ["Sector", "ScalarSector", "TensorPerturbationSector",
           "tensor_index", "get_rho_and_p"]


def tensor_index(i, j):
    """Pack 1-based symmetric rank-2 indices ``(i, j)`` into a 0-based
    length-6 storage index (``tensor_index(1, 1) == 0``; reference
    sectors.py:164-167 returns 1-based values, callers here key
    ``range(6)``)."""
    a, b = min(i, j), max(i, j)
    return (7 - a) * a // 2 - 4 + b


class Sector:
    """Base class (reference sectors.py:42-89)."""

    @property
    def rhs_dict(self):
        """Symbolic system of equations for time integration."""
        raise NotImplementedError

    @property
    def reducers(self):
        """Quantities to reduce over the lattice (energy components etc.)."""
        raise NotImplementedError

    def stress_tensor(self, mu, nu, drop_trace=True):
        """The component ``T_{mu nu}`` of this sector's stress-energy."""
        raise NotImplementedError


class ScalarSector(Sector):
    """Scalar fields with an arbitrary potential in conformal FLRW
    spacetime (reference sectors.py:92-161).

    :arg nscalars: number of scalar fields.
    :arg f: the :class:`~pystella_tpu.DynamicField`; defaults to
        ``DynamicField("f", shape=(nscalars,))``.
    :arg potential: callable mapping the field (symbolically) to the scalar
        potential; defaults to zero.

    The Klein-Gordon right-hand side in conformal time is
    ``f'' = lap f - 2 H f' - a² dV/df``.
    """

    def __init__(self, nscalars, **kwargs):
        self.nscalars = nscalars
        self.f = kwargs.pop("f", DynamicField("f", shape=(nscalars,)))
        self.potential = kwargs.pop("potential", lambda x: 0)

    @property
    def rhs_dict(self):
        f = self.f
        H = Var("hubble")
        a = Var("a")

        rhs_dict = {}
        V = self.potential(f)
        for fld in range(self.nscalars):
            rhs_dict[f[fld]] = f.dot[fld]
            rhs_dict[f.dot[fld]] = (f.lap[fld]
                                    - 2 * H * f.dot[fld]
                                    - a**2 * diff(V, f[fld]))
        return rhs_dict

    @property
    def reducers(self):
        f = self.f
        a = Var("a")

        return {
            "kinetic": [f.dot[fld]**2 / 2 / a**2
                        for fld in range(self.nscalars)],
            "potential": [self.potential(f)],
            "gradient": [-f[fld] * f.lap[fld] / 2 / a**2
                         for fld in range(self.nscalars)],
        }

    def energy_means(self, f, dfdt, a=1.0, lap_f=None):
        """Traceable mean energy densities of the scalar system —
        the model-level invariant inputs for the numerics sentinel
        (:mod:`pystella_tpu.obs.sentinel`): ``kinetic`` and
        ``potential`` (plus ``gradient`` when ``lap_f`` is supplied —
        the reducers' integration-by-parts form) and their ``total``,
        matching :attr:`reducers` up to the lattice average. Pure jnp,
        so it runs inside a jitted step on sharded arrays with no host
        sync; a drifting ``total`` in a conserved setting is the drift
        slope the ledger's ``numerics`` section and the gate track.

        :arg f, dfdt: field arrays ``(nscalars, ...)``.
        :arg a: scale factor (scalar, traced or static).
        :arg lap_f: optional Laplacian of ``f`` — omit it (driver loops
            that don't already have one) and the gradient energy is
            skipped rather than paid for with an extra stencil pass.
        """
        import jax.numpy as jnp

        from pystella_tpu.field import evaluate

        out = {"kinetic": jnp.mean(jnp.sum(dfdt * dfdt, axis=0))
               / 2 / a**2}
        if lap_f is not None:
            out["gradient"] = (jnp.mean(jnp.sum(-f * lap_f, axis=0))
                               / 2 / a**2)
        pot = jnp.asarray(evaluate(self.potential(self.f), {"f": f}))
        out["potential"] = jnp.mean(jnp.broadcast_to(pot, f.shape[1:]))
        out["total"] = sum(out.values())
        return out

    def stress_tensor(self, mu, nu, drop_trace=False):
        f = self.f
        a = Var("a")

        tmunu = sum(f.d(fld, mu) * f.d(fld, nu)
                    for fld in range(self.nscalars))
        if drop_trace:
            return tmunu

        metric_inv = np.diag((-1, 1, 1, 1))  # times 1/a^2 (contravariant)
        lag = (- sum(sum(metric_inv[alpha, beta] / a**2
                         * f.d(fld, alpha) * f.d(fld, beta)
                         for alpha in range(4) for beta in range(4))
                     for fld in range(self.nscalars)) / 2
               - self.potential(f))
        metric = np.diag((-1, 1, 1, 1))  # times a^2 (covariant)
        return tmunu + metric[mu, nu] * a**2 * lag


class TensorPerturbationSector(Sector):
    """Transverse-traceless metric perturbations ``h_ij`` sourced by the
    anisotropic stress of other sectors (reference sectors.py:170-208):
    ``h_ij'' = lap h_ij - 2 H h_ij' + 16 pi S_ij``.

    :arg sectors: list of Sectors whose ``stress_tensor`` sources ``hij``.
    :arg hij: defaults to ``DynamicField("hij", shape=(6,))``.
    """

    def __init__(self, sectors, **kwargs):
        self.hij = kwargs.pop("hij", DynamicField("hij", shape=(6,)))
        self.sectors = sectors

    @property
    def rhs_dict(self):
        hij = self.hij
        H = Var("hubble")

        rhs_dict = {}
        for i in range(1, 4):
            for j in range(i, 4):
                fld = tensor_index(i, j)
                sij = sum(sector.stress_tensor(i, j, drop_trace=True)
                          for sector in self.sectors)
                rhs_dict[hij[fld]] = hij.dot[fld]
                rhs_dict[hij.dot[fld]] = (hij.lap[fld]
                                          - 2 * H * hij.dot[fld]
                                          + 16 * np.pi * sij)
        return rhs_dict

    @property
    def reducers(self):
        return {}


def get_rho_and_p(energy):
    """Callback for energy reductions computing total density and pressure
    (reference sectors.py:211-229)."""
    energy["total"] = sum(np.sum(e) for e in energy.values())
    energy["pressure"] = 0
    if "kinetic" in energy:
        energy["pressure"] = energy["pressure"] + np.sum(energy["kinetic"])
    if "gradient" in energy:
        energy["pressure"] = energy["pressure"] - np.sum(energy["gradient"]) / 3
    if "potential" in energy:
        energy["pressure"] = energy["pressure"] - np.sum(energy["potential"])
    return energy
