"""FLRW scale-factor evolution in conformal time.

TPU-native counterpart of /root/reference/pystella/expansion.py:28-176. The
reference integrates the two-variable scale-factor ODE on the host CPU with
a loopy C-target kernel; here the same Stepper classes run the scalar system
directly on host floats (no device round-trips), and the Friedmann
right-hand sides are plain functions usable inside a fused jitted
simulation step as well.
"""

from __future__ import annotations

import numpy as np

__all__ = ["Expansion"]


class Expansion:
    """Scale-factor stepping for conformal FLRW spacetime.

    :arg energy: initial energy density (initializes ``adot`` via
        Friedmann 1).
    :arg Stepper: a :class:`~pystella_tpu.Stepper` subclass.
    :arg mpl: unreduced Planck mass; sets units (reference expansion.py:55-61).
    """

    def __init__(self, energy, Stepper, mpl=1.0, dtype=np.float64):
        self.mpl = mpl
        self.dtype = np.dtype(dtype)
        self.a = self.dtype.type(1.0)
        self.adot = self.adot_friedmann_1(self.a, energy)
        self.hubble = self.adot / self.a

        def rhs(state, t, energy=0.0, pressure=0.0):
            return {"a": state["adot"],
                    "adot": self.addot_friedmann_2(state["a"], energy,
                                                   pressure)}

        self.stepper = Stepper(rhs)
        self._carry = None

    def adot_friedmann_1(self, a, energy):
        """``da/dtau`` from Friedmann's first equation,
        ``H² = 8 pi a² rho / (3 mpl²)`` (reference expansion.py:101-117)."""
        return np.sqrt(8 * np.pi * a**2 / 3 / self.mpl**2 * energy) * a

    def addot_friedmann_2(self, a, energy, pressure):
        """``d²a/dtau²`` from Friedmann's second equation
        (reference expansion.py:119-138)."""
        return (4 * np.pi * a**2 / 3 / self.mpl**2
                * (energy - 3 * pressure) * a)

    def step(self, stage, energy, pressure, dt):
        """Execute one stage of the stepper (reference expansion.py:140-157);
        updates ``a``, ``adot``, ``hubble``."""
        state_or_carry = ({"a": self.a, "adot": self.adot}
                          if stage == 0 else self._carry)
        result = self.stepper(stage, state_or_carry, 0.0, dt,
                              energy=energy, pressure=pressure)
        if stage == self.stepper.num_stages - 1:
            self.a = self.dtype.type(result["a"])
            self.adot = self.dtype.type(result["adot"])
            self._carry = None
        else:
            self._carry = result
            current = self.stepper.current(result)
            self.a = self.dtype.type(current["a"])
            self.adot = self.dtype.type(current["adot"])
        self.hubble = self.adot / self.a

    def stage_sequence(self, nsteps, energy, pressure, dt):
        """Advance ``nsteps`` full steps with FROZEN ``(energy, pressure)``,
        recording the per-stage ``(a, hubble)`` a driver loop would have
        passed to each field stage (the value *entering* the stage).

        This is the host-side precompute for chunked hot loops
        (:meth:`FusedScalarStepper.multi_step` ``rhs_seq``): the exact
        driver re-evaluates the field energy every stage and feeds it
        back, while a chunk holds the stage-entry energy for ``nsteps``
        steps — a background-coupling lag of one chunk, acceptable when
        ``nsteps * dt`` is small against the expansion timescale (the
        drift is measured in ``tests/test_examples.py``). ``self`` IS
        advanced to the chunk end. Returns two ``(nsteps * num_stages,)``
        float arrays ``(a_seq, hubble_seq)``."""
        ns = self.stepper.num_stages
        a_seq = np.empty(nsteps * ns, self.dtype)
        hubble_seq = np.empty(nsteps * ns, self.dtype)
        i = 0
        for _ in range(nsteps):
            for s in range(ns):
                a_seq[i], hubble_seq[i] = self.a, self.hubble
                self.step(s, energy, pressure, dt)
                i += 1
        return a_seq, hubble_seq

    def constraint(self, energy):
        """Dimensionless violation of Friedmann 1 as an evolution constraint
        (reference expansion.py:159-176)."""
        return np.abs(self.adot_friedmann_1(self.a, energy) / self.adot - 1)

    def constraint_residual(self, a, adot, energy):
        """The same Friedmann-1 residual as :meth:`constraint`, but
        computed from explicit ``(a, adot, energy)`` using only
        power/abs arithmetic — traceable, so it runs *inside* a jitted
        step as a numerics-sentinel invariant
        (:mod:`pystella_tpu.obs.sentinel`), e.g. against the on-device
        background of an energy-coupled chunk
        (``FusedScalarStepper.coupled_multi_step`` passes ``a``/``adot``
        in the sentinel's ``aux``)."""
        adot_f1 = (8 * np.pi * a**2 / 3 / self.mpl**2 * energy) ** 0.5 * a
        return abs(adot_f1 / adot - 1)
