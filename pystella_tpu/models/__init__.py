from pystella_tpu.models.sectors import (
    Sector, ScalarSector, TensorPerturbationSector, tensor_index,
    get_rho_and_p,
)
from pystella_tpu.models.expansion import Expansion

__all__ = [
    "Sector", "ScalarSector", "TensorPerturbationSector", "tensor_index",
    "get_rho_and_p", "Expansion",
]
