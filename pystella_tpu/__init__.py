"""pystella_tpu: a TPU-native framework for PDE systems on 3-D periodic
lattices.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the reference
pystella (/root/reference): symbolic field expressions, finite-difference and
spectral operators, Runge-Kutta steppers, distributed 3-D lattices over
device meshes, Fourier analysis (spectra, projections, Gaussian random
fields), FLRW expansion, scalar-field / gravitational-wave sectors, and
multigrid solvers.

Where the reference generates OpenCL via loopy and communicates via MPI
(/root/reference/pystella/__init__.py:24-40), here XLA is the kernel
generator and compiler, lattices are ``jax.Array``s sharded over a
``jax.sharding.Mesh``, and communication is XLA collectives over ICI/DCN.
"""

from pystella_tpu import config
from pystella_tpu.field import (
    Field, DynamicField, Expr, Var, Shifted,
    diff, simplify, substitute, evaluate, field_names, shift_fields,
    exp, log, sin, cos, tan, sinh, cosh, tanh, sqrt, fabs, sign,
    t, x, y, z,
)
from pystella_tpu.grid import Lattice
from pystella_tpu.parallel import (
    DomainDecomposition, ensemble_mesh, make_mesh)
from pystella_tpu.ops import (
    ElementWiseMap,
    FirstCenteredDifference, SecondCenteredDifference, FiniteDifferencer,
    expand_stencil, centered_diff,
    Reduction, FieldStatistics,
    Histogrammer, FieldHistogrammer,
    FFTStencil, fft_laplacian, use_fft_stencil,
)
from pystella_tpu.ops.pallas_stencil import StreamingStencil
from pystella_tpu.ops.fused import FusedScalarStepper, FusedPreheatStepper
from pystella_tpu.fourier import (
    DFT, PencilFFT, make_dft, fftfreq, pfftfreq, make_hermitian,
    Projector, PowerSpectra, RayleighGenerator,
    SpectralCollocator, SpectralPoissonSolver,
)
from pystella_tpu.models import (
    Sector, ScalarSector, TensorPerturbationSector, tensor_index,
    get_rho_and_p, Expansion,
)
from pystella_tpu import obs
from pystella_tpu import ensemble
from pystella_tpu.ensemble import (
    EnsembleDriver, EnsembleMonitor, EnsembleStepper, Scenario)
from pystella_tpu import resilience
from pystella_tpu.resilience import (
    DeviceSubsetFault, FaultInjector, RecoveryFailed, RemeshPlanner,
    RetryPolicy, Supervisor)
from pystella_tpu import service
from pystella_tpu.service import ScenarioRequest, ScenarioService
from pystella_tpu.utils import (Checkpointer, HealthMonitor,
    SimulationDiverged, OutputFile, ShardedSnapshot, StepTimer, timer,
    trace, advise_shapes)
from pystella_tpu.step import (
    Stepper, RungeKuttaStepper, LowStorageRKStepper, compile_rhs_dict,
    RungeKutta4, RungeKutta3Heun, RungeKutta3Nystrom, RungeKutta3Ralston,
    RungeKutta3SSP, RungeKutta2Midpoint, RungeKutta2Heun, RungeKutta2Ralston,
    LowStorageRK54, LowStorageRK144, LowStorageRK134, LowStorageRK124,
    LowStorageRK3Williamson, LowStorageRK3Inhomogeneous,
    LowStorageRK3Symmetric, LowStorageRK3PredictorCorrector, LowStorageRK3SSP,
    all_steppers,
)

__version__ = "2026.1"


def choose_device_and_make_context(platform=None):
    """Parity shim for the reference device chooser
    (/root/reference/pystella/__init__.py:46-102). With JAX there is no
    context to create; returns ``(None, jax.devices()[0])``."""
    import jax
    devices = jax.devices(platform) if platform else jax.devices()
    return None, devices[0]


class DisableLogging:
    """Context manager silencing logging (reference
    /root/reference/pystella/__init__.py:105-114)."""

    def __enter__(self):
        import logging
        self.previous_level = logging.root.manager.disable
        logging.disable(logging.CRITICAL)

    def __exit__(self, exception_type, exception_value, traceback):
        import logging
        logging.disable(self.previous_level)


__all__ = [
    "Field", "DynamicField", "Expr", "Var", "Shifted", "diff", "simplify",
    "substitute", "evaluate", "field_names", "shift_fields",
    "expand_stencil", "centered_diff",
    "exp", "log", "sin", "cos", "tan", "sinh", "cosh", "tanh", "sqrt",
    "fabs", "sign", "t", "x", "y", "z",
    "Lattice", "DomainDecomposition", "ensemble_mesh", "make_mesh",
    "ensemble", "EnsembleStepper", "EnsembleDriver", "Scenario",
    "EnsembleMonitor",
    "resilience", "Supervisor", "FaultInjector", "RetryPolicy",
    "RecoveryFailed", "RemeshPlanner", "DeviceSubsetFault",
    "service", "ScenarioService", "ScenarioRequest",
    "ElementWiseMap",
    "FirstCenteredDifference", "SecondCenteredDifference",
    "FiniteDifferencer",
    "Reduction", "FieldStatistics", "Histogrammer", "FieldHistogrammer",
    "StreamingStencil", "FusedScalarStepper", "FusedPreheatStepper",
    "FFTStencil", "fft_laplacian", "use_fft_stencil",
    "DFT", "PencilFFT", "make_dft", "fftfreq", "pfftfreq",
    "make_hermitian",
    "Projector", "PowerSpectra", "RayleighGenerator",
    "SpectralCollocator", "SpectralPoissonSolver",
    "Sector", "ScalarSector", "TensorPerturbationSector", "tensor_index",
    "get_rho_and_p", "Expansion", "OutputFile", "ShardedSnapshot",
    "timer", "Checkpointer", "obs", "config",
    "HealthMonitor", "SimulationDiverged", "StepTimer", "trace",
    "Stepper", "RungeKuttaStepper", "LowStorageRKStepper", "compile_rhs_dict",
    "RungeKutta4", "RungeKutta3Heun", "RungeKutta3Nystrom",
    "RungeKutta3Ralston", "RungeKutta3SSP", "RungeKutta2Midpoint",
    "RungeKutta2Heun", "RungeKutta2Ralston",
    "LowStorageRK54", "LowStorageRK144", "LowStorageRK134", "LowStorageRK124",
    "LowStorageRK3Williamson", "LowStorageRK3Inhomogeneous",
    "LowStorageRK3Symmetric", "LowStorageRK3PredictorCorrector",
    "LowStorageRK3SSP", "all_steppers",
    "choose_device_and_make_context", "DisableLogging",
]
