"""Grid-transfer operators (restriction and interpolation) for multigrid.

TPU-native counterpart of /root/reference/pystella/multigrid/transfer.py:40-264.
The reference generates loopy stencil kernels indexed by ``(2i, 2j, 2k)``
(restriction) or by ``((i+a)//2, i%2)`` parity selection (interpolation).
Here both are tensor-product per-axis array ops on local blocks: restriction
is a strided slice of a halo-padded block, interpolation is an interleave
(``stack`` + ``reshape``) of even/odd parts — shapes are static, so XLA
fuses the three axes into one pass.

Each operator works on *local blocks*: inside a ``shard_map`` (halos arrive
via ``lax.ppermute`` through the supplied pad function) or on whole
replicated arrays (periodic wrap pad). The multigrid driver chooses per
level; the operators themselves are mesh-agnostic.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

__all__ = ["RestrictionBase", "FullWeighting", "Injection",
           "InterpolationBase", "LinearInterpolation", "CubicInterpolation",
           "periodic_pad"]


def periodic_pad(x, halo, lattice_axes=None):
    """Pad the lattice axes of ``x`` with periodic wraps of width
    ``halo[d]`` — the local (no-communication) analog of
    ``DomainDecomposition.pad_with_halos`` for replicated arrays."""
    if np.isscalar(halo):
        halo = (halo,) * 3
    if lattice_axes is None:
        lattice_axes = tuple(range(x.ndim - 3, x.ndim))
    for d, ax in enumerate(lattice_axes):
        h = halo[d]
        if h == 0:
            continue
        lo = lax.slice_in_dim(x, x.shape[ax] - h, x.shape[ax], axis=ax)
        hi = lax.slice_in_dim(x, 0, h, axis=ax)
        x = lax.concatenate([lo, x, hi], dimension=ax)
    return x


class RestrictionBase:
    """Tensor-product restriction: coarse point ``i`` receives
    ``sum_o c_o * fine[2 i + o]`` along each axis (reference
    transfer.py:40-102; coefficient convention matches
    ``pystella.derivs.centered_diff``).

    :arg coefs: dict mapping fine-grid offset ``o`` (relative to the
        coinciding fine point ``2 i``) to its weight.
    :arg halo_shape: accepted for API parity with the reference (padding is
        handled by the pad function, not baked into array shapes).
    :arg correct: if True, :meth:`__call__` computes ``f2 - R(f1)`` — the
        kernel the reference calls ``restrict_and_correct``.
    """

    coefs = {0: 1}

    def __init__(self, halo_shape=0, correct=False, **kwargs):
        self.halo_shape = halo_shape
        self.correct = correct
        self.pad = max(abs(int(o)) for o in self.coefs)

    def apply_local(self, x, pad_fn=periodic_pad):
        """Restrict the trailing 3 (lattice) axes of a local block ``x``
        (even extents) to half resolution."""
        hp = self.pad
        la = x.ndim - 3
        if hp:
            x = pad_fn(x, (hp,) * 3)
        for d in range(3):
            ax = la + d
            n = x.shape[ax] - 2 * hp
            m = n // 2
            acc = None
            for o, c in sorted(self.coefs.items()):
                start = hp + o
                sl = lax.slice_in_dim(x, start, start + 2 * (m - 1) + 1,
                                      stride=2, axis=ax)
                acc = c * sl if acc is None else acc + c * sl
            # the strided slice consumed this axis's halos; later axes keep
            # theirs until their own pass
            x = acc
        return x

    def __call__(self, f1, f2=None, decomp=None):
        """Restrict global array ``f1``; with ``correct=True`` returns
        ``f2 - R(f1)``. ``decomp`` (if given and sharded) runs the operator
        under ``shard_map`` with ppermute halos."""
        out = _run_local(self, f1, decomp)
        if self.correct:
            if f2 is None:
                raise ValueError("correct=True requires f2")
            return f2 - out
        return out


class FullWeighting(RestrictionBase):
    """1/4, 1/2, 1/4 full-weighting restriction per axis (reference
    transfer.py:105-125)."""

    coefs = {-1: 1 / 4, 0: 1 / 2, 1: 1 / 4}


class Injection(RestrictionBase):
    """Direct injection ``f2[i] = f1[2i]`` (reference transfer.py:128-143)."""

    coefs = {0: 1}


class InterpolationBase:
    """Tensor-product interpolation, coarse to fine (reference
    transfer.py:146-205). Per axis: ``fine[2i] = sum_e e_c * coarse[i+e]``
    and ``fine[2i+1] = sum_o o_c * coarse[i+o]``, with coefficients given in
    *coarse-grid* offsets; the two parts interleave via stack+reshape (the
    analog of the reference's 8-parity kernel).

    :arg correct: if True, :meth:`__call__` computes ``f1 + I(f2)`` — the
        reference's ``interpolate_and_correct``.
    """

    even_coefs = {0: 1}
    odd_coefs = {0: 1 / 2, 1: 1 / 2}

    def __init__(self, halo_shape=0, correct=False, **kwargs):
        self.halo_shape = halo_shape
        self.correct = correct
        offs = list(self.even_coefs) + list(self.odd_coefs)
        self.pad = max(abs(int(o)) for o in offs)

    def apply_local(self, x, pad_fn=periodic_pad):
        """Interpolate the trailing 3 (lattice) axes of a local coarse block
        to double resolution."""
        hp = self.pad
        la = x.ndim - 3
        if hp:
            x = pad_fn(x, (hp,) * 3)

        for d in range(3):
            ax = la + d
            m = x.shape[ax] - 2 * hp

            def part(coefs):
                acc = None
                for o, c in sorted(coefs.items()):
                    sl = lax.slice_in_dim(x, hp + o, hp + o + m, axis=ax)
                    acc = c * sl if acc is None else acc + c * sl
                return acc

            even, odd = part(self.even_coefs), part(self.odd_coefs)
            y = jnp.stack([even, odd], axis=ax + 1)
            shape = list(even.shape)
            shape[ax] *= 2
            x = y.reshape(shape)
        return x

    def __call__(self, f2, f1=None, decomp=None):
        """Interpolate global coarse array ``f2``; with ``correct=True``
        returns ``f1 + I(f2)``."""
        out = _run_local(self, f2, decomp)
        if self.correct:
            if f1 is None:
                raise ValueError("correct=True requires f1")
            return f1 + out
        return out


class LinearInterpolation(InterpolationBase):
    """Linear interpolation (reference transfer.py:208-231)."""

    even_coefs = {0: 1}
    odd_coefs = {0: 1 / 2, 1: 1 / 2}


class CubicInterpolation(InterpolationBase):
    """Cubic interpolation; odd fine points take a 4-point coarse stencil
    (reference transfer.py:234-264)."""

    even_coefs = {0: 1}
    odd_coefs = {-1: -1 / 16, 0: 9 / 16, 1: 9 / 16, 2: -1 / 16}


def _run_local(op, x, decomp):
    """Apply ``op.apply_local`` on a global array — under ``shard_map`` when
    a sharded decomp is supplied, else locally with periodic-wrap pads.
    Compiled wrappers are cached on ``op`` so repeated calls reuse the
    executable. The replicated branch is jitted too: eagerly it issues
    ~a dozen sliced ops per transfer, each a separate device dispatch
    (~15 ms uncached on a tunneled TPU — measured as the dominant
    V-cycle orchestration cost)."""
    import jax
    cache = getattr(op, "_jit_cache", None)
    if cache is None:
        cache = op._jit_cache = {}
    if decomp is not None and any(p > 1 for p in decomp.proc_shape):
        key = (decomp, x.ndim)
        fn = cache.get(key)
        if fn is None:
            spec = decomp.spec(x.ndim - 3)

            def body(blk):
                return op.apply_local(blk, pad_fn=decomp.pad_with_halos)

            from pystella_tpu.obs import memory as _obs_memory
            fn = cache[key] = _obs_memory.instrument_jit(
                jax.jit(decomp.shard_map(body, spec, spec)),
                label=f"mg.transfer.{type(op).__name__}.sharded")
        return fn(x)
    fn = cache.get("local")
    if fn is None:
        from pystella_tpu.obs import memory as _obs_memory
        fn = cache["local"] = _obs_memory.instrument_jit(
            jax.jit(lambda a: op.apply_local(a)),
            label=f"mg.transfer.{type(op).__name__}.local")
    return fn(x)
