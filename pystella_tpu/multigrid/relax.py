"""Relaxation (smoothing) solvers for boundary-value problems L(f) = rho.

TPU-native counterpart of /root/reference/pystella/multigrid/relax.py:36-373.
The reference builds four loopy kernels per solver (stepper, residual,
lhs-correction, residual statistics) and ping-pongs ``f``/``tmp_f`` arrays
with a halo exchange per iteration. Here each of those becomes a jitted
function; the whole ``nu``-iteration smooth runs as ONE compiled
computation — a ``lax.fori_loop`` whose body fuses the stencil evaluation
with the pointwise update, with ``lax.ppermute`` halo exchanges inside (via
``shard_map``) on sharded levels and periodic-wrap pads on replicated
(coarse) levels.

Equations are specified as in the reference (``lhs_dict`` mapping unknown
:class:`~pystella_tpu.Field`\\ s to ``(lhs, rho)`` pairs), with one
TPU-first change: the Laplacian appears *symbolically* as
``Field("lap_<name>")`` and is supplied by the solver from the
order-``2h`` centered stencil, so the smoother's effective operator is
exactly consistent with :class:`~pystella_tpu.FiniteDifferencer`. The
Jacobi/Newton diagonal is ``diff(lhs, f) + diff(lhs, lap_f) * lap_diag``
where ``lap_diag = sum_d c_0 / dx_d**2`` is the stencil's center weight
(the chain-rule term the reference gets from symbolic stencil
differentiation, relax.py:341-349).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pystella_tpu import field as _field
from pystella_tpu.field import Field, Var, diff, evaluate
from pystella_tpu.obs import memory as _obs_memory
from pystella_tpu.obs.scope import trace_scope
from pystella_tpu.ops.derivs import (
    SecondCenteredDifference, _apply_centered, _shifted)
from pystella_tpu.multigrid.transfer import periodic_pad

__all__ = ["LevelSpec", "RelaxationBase", "JacobiIterator", "NewtonIterator"]


@dataclasses.dataclass(frozen=True)
class LevelSpec:
    """Geometry of one multigrid level: global shape, spacing, and whether
    its arrays are sharded over the mesh (coarse levels whose local blocks
    would drop below the stencil halo are replicated instead — the
    level-dependent re-decomposition the reference gets by building a
    ``DomainDecomposition`` per level, multigrid/__init__.py:357-366)."""

    grid_shape: tuple
    dx: tuple
    sharded: bool


def _field_name(f):
    if isinstance(f, _field.Field):
        return f.name
    if isinstance(f, str):
        return f
    raise TypeError(f"lhs_dict keys must be Field or str, got {type(f)}")


#: jitted (Linf, L2) residual norms — one executable shared by every
#: solver instance; the four eager norm ops per unknown per smooth would
#: each be a separate device dispatch (~15 ms uncached on a tunneled TPU)
_residual_norms = jax.jit(lambda rn: (jnp.max(jnp.abs(rn)),
                                      jnp.sqrt(jnp.mean(rn * rn))))


class RelaxationBase:
    """Base class for relaxation solvers (reference relax.py:36-320).

    :arg decomp: a :class:`~pystella_tpu.DomainDecomposition` (used for
        sharded levels; replicated levels need no communication).
    :arg lhs_dict: dict ``{Field(f): (lhs, rho)}``; ``lhs`` is a symbolic
        expression in ``Field(f)``, ``Field("lap_" + f)`` and any auxiliary
        names; ``rho`` must be a :class:`~pystella_tpu.Field`.
    :arg halo_shape: stencil radius ``h`` of the order-``2h`` Laplacian.
    :arg omega: relaxation damping factor (the reference passes it via
        ``fixed_parameters=dict(omega=...)``, which is also accepted).
    """

    def __init__(self, decomp, lhs_dict, halo_shape=1, omega=1.0,
                 dtype=None, smoother="auto", overlap=None, **kwargs):
        self.decomp = decomp
        self.halo_shape = int(halo_shape)
        # halo-overlap policy for sharded levels (resolved per level
        # decomp at compile time — coarse replicated levels never
        # communicate); None defers to PYSTELLA_HALO_OVERLAP / auto
        self._overlap_override = overlap
        self.omega = float(kwargs.pop("fixed_parameters", {}).get(
            "omega", omega))
        self.dtype = dtype
        if smoother == "auto":
            # the Pallas sweep kernels are the measured-fast tier on TPU;
            # on CPU they would run in interpret mode (orders of magnitude
            # slower than XLA) — tests opt in explicitly
            smoother = "pallas" if jax.default_backend() == "tpu" else "xla"
        if smoother not in ("pallas", "xla"):
            raise ValueError(f"unknown smoother {smoother}")
        self.smoother = smoother
        self.stencil = SecondCenteredDifference(self.halo_shape)

        self.f_to_rho_dict = {}
        self.step_exprs = {}
        self.resid_exprs = {}
        self.lhs_exprs = {}
        for f, (lhs, rho) in lhs_dict.items():
            name = _field_name(f)
            if not isinstance(rho, _field.Field):
                raise TypeError("rho must be a Field naming the source array")
            self.f_to_rho_dict[name] = rho.name
            fsym = f if isinstance(f, _field.Field) else Field(name)
            self.step_exprs[name] = self.step_operator(fsym, lhs, rho)
            self.resid_exprs[name] = rho - lhs
            self.lhs_exprs[name] = lhs
        self._compiled = {}

    # -- subclass hook ------------------------------------------------------

    def step_operator(self, f, lhs, rho):
        """Symbolic relaxation update for unknown ``f`` (reference
        relax.py:140-150)."""
        raise NotImplementedError

    def _diagonal(self, f, lhs):
        """d lhs / d f including the Laplacian's center weight."""
        lap = Field("lap_" + f.name)
        return diff(lhs, f) + diff(lhs, lap) * Var("_lap_diag")

    # -- local stencil + environment ---------------------------------------

    def _lap_from_padded(self, padded, dx):
        h = self.halo_shape
        la = padded.ndim - 3
        acc = None
        for d in range(3):
            y = padded
            for other in range(3):
                if other != d:
                    y = _shifted(y, la + other, 0, h)
            term = _apply_centered(y, la + d, self.stencil.coefs, h, 2,
                                   1 / dx[d] ** 2)
            acc = term if acc is None else acc + term
        return acc

    def _local_lap(self, x, dx, pad_fn):
        h = self.halo_shape
        return self._lap_from_padded(pad_fn(x, (h,) * 3), dx)

    def _center(self, padded):
        """The unpadded block back out of a halo-padded one."""
        h = self.halo_shape
        la = padded.ndim - 3
        y = padded
        for d in range(3):
            y = _shifted(y, la + d, 0, h)
        return y

    def _lap_diag(self, dx):
        return float(sum(self.stencil.coefs[0] / d ** 2 for d in dx))

    def _env(self, fs, rhos, aux, dx, pad_fn):
        env = {**aux, **rhos, **fs}
        for n in fs:
            env["lap_" + n] = self._local_lap(fs[n], dx, pad_fn)
        env["omega"] = self.omega
        env["_lap_diag"] = self._lap_diag(dx)
        return env

    # -- compiled per-level operations --------------------------------------

    def _overlap_body(self, kind, level, decomp, nu=None):
        """The overlapped-halo variant of a sharded level's XLA body:
        per sweep, the unknowns' ``ppermute``s are issued first, the
        interior update is computed from local data while the
        collectives fly, and the boundary shells are stitched once
        halos land (``decomp.overlap_stencil``; bit-exact with the
        padded body — identical taps and per-element arithmetic)."""
        names = list(self.f_to_rho_dict)
        h = self.halo_shape
        halo = (h,) * 3
        dx = level.dx
        exprs = {"smooth": self.step_exprs, "residual": self.resid_exprs,
                 "tau": self.lhs_exprs}[kind]

        def apply(padded_fs, ex):
            env = {**ex.get("aux", {}), **ex.get("rhos", {})}
            env["omega"] = self.omega
            env["_lap_diag"] = self._lap_diag(dx)
            for n in names:
                p = padded_fs[n]
                env[n] = self._center(p)
                env["lap_" + n] = self._lap_from_padded(p, dx)
            if kind == "tau":
                return {self.f_to_rho_dict[n]:
                        ex["rr"][n] + evaluate(exprs[n], env)
                        for n in names}
            return {n: evaluate(exprs[n], env) for n in names}

        if kind == "smooth":
            def body(fs, rhos, aux):
                def it(_, fs):
                    return decomp.overlap_stencil(
                        fs, halo, apply,
                        extras={"rhos": rhos, "aux": aux})
                return lax.fori_loop(0, nu, it, fs)
        elif kind == "residual":
            def body(fs, rhos, aux):
                return decomp.overlap_stencil(
                    fs, halo, apply, extras={"rhos": rhos, "aux": aux})
        else:
            def body(fs, rr, aux):
                return decomp.overlap_stencil(
                    fs, halo, apply, extras={"rr": rr, "aux": aux})
        return body

    def _get_compiled(self, kind, level, nu=None, decomp=None):
        from pystella_tpu.parallel import overlap as _overlap
        decomp = decomp if decomp is not None else self.decomp
        use_overlap = (level.sharded
                       and _overlap.enabled(decomp,
                                            self._overlap_override))
        key = (kind, level, nu, decomp, use_overlap)
        cached = self._compiled.get(key)
        if cached is not None:
            return cached

        pad_fn = (decomp.pad_with_halos if level.sharded
                  else periodic_pad)
        dx = level.dx

        if use_overlap and kind in ("smooth", "residual", "tau"):
            body = self._overlap_body(kind, level, decomp, nu)
        elif kind == "smooth":
            def body(fs, rhos, aux):
                def it(_, fs):
                    env = self._env(fs, rhos, aux, dx, pad_fn)
                    return {n: evaluate(self.step_exprs[n], env)
                            for n in fs}
                return lax.fori_loop(0, nu, it, fs)
        elif kind == "residual":
            def body(fs, rhos, aux):
                env = self._env(fs, rhos, aux, dx, pad_fn)
                return {n: evaluate(self.resid_exprs[n], env) for n in fs}
        elif kind == "tau":
            # FAS coarse-grid right-hand side: restricted fine residual
            # plus the coarse operator applied to the restricted unknowns
            # (reference lhs_correction, relax.py:202-214)
            def body(fs, rr, aux):
                env = self._env(fs, {}, aux, dx, pad_fn)
                return {self.f_to_rho_dict[n]:
                        rr[n] + evaluate(self.lhs_exprs[n], env)
                        for n in fs}
        else:
            raise ValueError(kind)

        if level.sharded:
            spec = decomp.spec(0)
            fn = jax.jit(decomp.shard_map(body, (spec, spec, spec), spec))
        else:
            fn = jax.jit(body)
        fn = _obs_memory.instrument_jit(
            fn, label=f"mg.{kind}{tuple(level.grid_shape)}")
        self._compiled[key] = fn
        return fn

    def _cast(self, arrays):
        if self.dtype is None:
            return arrays
        return {k: jnp.asarray(v, self.dtype) for k, v in arrays.items()}

    # -- Pallas sweep tier ---------------------------------------------------

    def _aux_struct(self, aux):
        """Static routing of auxiliary arrays: lattice-shaped values ride
        the kernel's blockwise extras, scalars go to SMEM."""
        struct = []
        for k in sorted(aux):
            v = aux[k]
            ndim = getattr(v, "ndim", 0)
            struct.append((k, "lattice" if ndim >= 3 else "scalar"))
        return tuple(struct)

    def _pallas_level(self, kind, level, decomp, dtype, aux_struct):
        """A stencil-kernel pass for one level: ``smooth`` (runtime-``nu``
        ``fori_loop`` of whole-sweep kernels — one compile serves every
        sweep count) or ``residual``. Each sweep reads the unknowns once
        from HBM, computes the order-2h Laplacian from the VMEM window,
        evaluates the update pointwise, and writes once — the identical
        streaming pattern as the fused RK stages, replacing the XLA
        halo-pad sweeps measured ~10x below bandwidth (VERDICT r3 #5).
        Returns None when this level/mesh cannot take the kernel tier
        (z-sharded, sublane-infeasible sharded y, over-budget resident)
        — callers fall back to the XLA path."""
        from pystella_tpu.ops.pallas_stencil import (
            HY, ResidentStencil, StreamingStencil, lap_from_taps,
            sharded_halo)

        key = ("pallas", kind, level, decomp, str(dtype), aux_struct)
        if key in self._compiled:
            return self._compiled[key]

        names = list(self.f_to_rho_dict)
        nf = len(names)
        proc = decomp.proc_shape if level.sharded else (1, 1, 1)
        px, py, pz = proc
        local_shape = tuple(n // p for n, p in zip(level.grid_shape, proc))
        feasible = (pz == 1
                    and (py == 1 or (local_shape[1] >= HY
                                     and local_shape[1] % HY == 0)))
        coefs = self.stencil.coefs
        inv_dx2 = [1.0 / d**2 for d in level.dx]
        aux_lat = [k for k, kk in aux_struct if kk == "lattice"]
        aux_scal = [k for k, kk in aux_struct if kk == "scalar"]
        exprs = {"smooth": self.step_exprs,
                 "residual": self.resid_exprs,
                 "tau": self.lhs_exprs}[kind]

        def body(taps, extras, scalars):
            fs = taps()
            lap = lap_from_taps(taps, coefs, inv_dx2)
            env = {"omega": self.omega,
                   "_lap_diag": self._lap_diag(level.dx)}
            for i, n in enumerate(names):
                env[n] = fs[i]
                env["lap_" + n] = lap[i]
                if kind != "tau":
                    env[self.f_to_rho_dict[n]] = extras["rhos"][i]
            for k in aux_lat:
                env[k] = extras[k]
            for k in aux_scal:
                env[k] = scalars[k]
            vals = [jnp.broadcast_to(
                jnp.asarray(evaluate(exprs[n], env), fs.dtype),
                fs.shape[1:]) for n in names]
            if kind == "tau":
                # FAS coarse rho: restricted fine residual (riding the
                # "rhos" extras slot) + the coarse operator
                vals = [extras["rhos"][i] + v
                        for i, v in enumerate(vals)]
            return {"out": jnp.stack(vals)}

        st = None
        if feasible:
            extra_defs = {"rhos": (nf,), **{k: () for k in aux_lat}}
            try:
                st = StreamingStencil(
                    local_shape, {"f": nf}, self.halo_shape, body,
                    {"out": (nf,)}, extra_defs=extra_defs,
                    scalar_names=tuple(aux_scal), dtype=dtype,
                    x_halo=(px > 1), y_halo=(py > 1))
            except ValueError:
                if px == 1 and py == 1:
                    try:
                        st = ResidentStencil(
                            local_shape, {"f": nf}, self.halo_shape,
                            body, {"out": (nf,)}, extra_defs=extra_defs,
                            scalar_names=tuple(aux_scal), dtype=dtype)
                    except ValueError:
                        st = None
        if st is None:
            self._compiled[key] = None
            return None

        halo = sharded_halo(self.halo_shape, px, py)
        sharded = px > 1 or py > 1
        ov = None
        if sharded and px > 1 and py == 1:
            from pystella_tpu.ops.pallas_stencil import (
                OverlapStreamingStencil)
            from pystella_tpu.parallel import overlap as _overlap
            if _overlap.enabled(decomp, self._overlap_override):
                # x-sharded sweeps overlap the slab ppermutes with the
                # interior kernel (bit-exact; infeasible shapes keep
                # the padded single launch)
                try:
                    ov = OverlapStreamingStencil(st, self.halo_shape)
                except ValueError:
                    ov = None

        def run(fstack, rhostack, aux_args, nu):
            scalars = dict(zip(aux_scal, aux_args[len(aux_lat):]))
            extras = {"rhos": rhostack,
                      **dict(zip(aux_lat, aux_args[:len(aux_lat)]))}

            def one(fst):
                if ov is not None:
                    return ov(fst, decomp, scalars=scalars,
                              extras=extras)["out"]
                fin = (decomp.pad_with_halos(
                    fst, halo, exchange=(self.halo_shape,) * 3)
                    if sharded else fst)
                return st(fin, scalars=scalars, extras=extras)["out"]

            if kind != "smooth":
                return one(fstack)
            return lax.fori_loop(0, nu, lambda _, fst: one(fst), fstack)

        if sharded:
            spec = decomp.spec(1)
            from jax.sharding import PartitionSpec as P
            in_specs = (spec, spec,
                        (spec,) * len(aux_lat) + (P(),) * len(aux_scal),
                        P())
            core = decomp.shard_map(run, in_specs, spec, check_vma=False)
        else:
            core = run

        def entry(f_list, rho_list, aux_args, nu):
            # stack/unstack INSIDE the jit: eager jnp.stack copies the
            # full lattice per call (~40 copies per 512^3 V-cycle); here
            # XLA fuses or aliases them into the kernel's input layout
            fstack = jnp.stack(f_list)
            rhostack = jnp.stack([jnp.asarray(r, dtype) for r in rho_list])
            out = core(fstack, rhostack, aux_args, nu)
            return [out[i] for i in range(len(f_list))]

        fn = _obs_memory.instrument_jit(
            jax.jit(entry),
            label=f"mg.pallas_{kind}{tuple(level.grid_shape)}")
        self._compiled[key] = fn
        return fn

    def _try_pallas(self, kind, level, fs, rhos, aux, decomp, nu=0):
        if self.smoother != "pallas":
            return None
        names = list(self.f_to_rho_dict)
        dtype = jnp.result_type(fs[names[0]])
        aux_struct = self._aux_struct(aux)
        fn = self._pallas_level(kind, level, decomp, dtype, aux_struct)
        if fn is None:
            return None  # cheap: no stacking before the feasibility gate
        f_list = tuple(fs[n] for n in names)
        rho_list = tuple(rhos[self.f_to_rho_dict[n]] for n in names)
        aux_args = tuple(aux[k] for k, kk in aux_struct
                         if kk == "lattice")
        aux_args += tuple(aux[k] for k, kk in aux_struct
                          if kk == "scalar")
        out = fn(f_list, rho_list, aux_args, jnp.int32(nu))
        return {n: out[i] for i, n in enumerate(names)}

    def smooth(self, level, fs, rhos, aux, iterations, decomp=None):
        """Run ``iterations`` relaxation sweeps; returns updated unknowns."""
        decomp = decomp if decomp is not None else self.decomp
        iterations = int(iterations)
        fs, rhos, aux = self._cast(fs), self._cast(rhos), self._cast(aux)
        with trace_scope("mg_smooth"):
            res = self._try_pallas("smooth", level, fs, rhos, aux, decomp,
                                   nu=iterations)
            if res is not None:
                return res
            return self._get_compiled(
                "smooth", level, iterations, decomp)(fs, rhos, aux)

    def residual(self, level, fs, rhos, aux, decomp=None):
        """``rho - L(f)`` per unknown (reference relax.py:216-223)."""
        decomp = decomp if decomp is not None else self.decomp
        fs, rhos, aux = self._cast(fs), self._cast(rhos), self._cast(aux)
        with trace_scope("mg_residual"):
            res = self._try_pallas("residual", level, fs, rhos, aux, decomp)
            if res is not None:
                return res
            return self._get_compiled("residual", level, None, decomp)(
                fs, rhos, aux)

    def tau_rhs(self, level, fs, restricted_resid, aux, decomp=None):
        """Coarse-level rho with FAS tau-correction. Takes the Pallas
        stencil tier when the level admits it (the same kernel shape as
        ``residual``; VERDICT r4 #4), else the XLA halo-pad path."""
        decomp = decomp if decomp is not None else self.decomp
        fs = self._cast(fs)
        rr = self._cast(restricted_resid)
        aux = self._cast(aux)
        res = self._try_pallas(
            "tau", level, fs,
            {self.f_to_rho_dict[n]: rr[n] for n in fs}, aux, decomp)
        if res is not None:
            return {self.f_to_rho_dict[n]: res[n] for n in res}
        return self._get_compiled("tau", level, None, decomp)(fs, rr, aux)

    def error_arrays(self, level, fs, rhos, aux, decomp=None):
        """Residual norms as DEVICE scalars — no host sync, so cycle
        drivers can record errors without serializing the device queue
        (they convert once at the end; multigrid/__init__.py)."""
        r = self.residual(level, fs, rhos, aux, decomp)
        return {n: list(_residual_norms(rn)) for n, rn in r.items()}

    def get_error(self, level, fs, rhos, aux, decomp=None):
        """L-infinity and L2 norms of the residual per unknown (reference
        relax.py:242-266)."""
        return {n: [float(a), float(b)] for n, (a, b) in
                self.error_arrays(level, fs, rhos, aux, decomp).items()}

    # -- standalone relaxation (reference __call__, relax.py:164-200) -------

    def __call__(self, decomp, iterations=100, dx=None, **arrays):
        """Relax for ``iterations`` sweeps on global arrays. Unknowns, rho,
        and auxiliary arrays are passed by keyword; returns the dict of
        updated unknowns."""
        if dx is None:
            raise ValueError("dx is required")
        if np.isscalar(dx):
            dx = (float(dx),) * 3
        fs = {n: arrays.pop(n) for n in self.f_to_rho_dict}
        rhos = {r: arrays.pop(r) for r in self.f_to_rho_dict.values()}
        first = next(iter(fs.values()))
        sharded = (decomp is not None
                   and any(p > 1 for p in decomp.proc_shape))
        level = LevelSpec(tuple(first.shape[-3:]), tuple(dx), sharded)
        return self.smooth(level, fs, rhos, arrays, iterations, decomp)


class JacobiIterator(RelaxationBase):
    """Damped Jacobi iteration for linear systems (reference
    relax.py:323-349): ``f <- (1-omega) f + omega D^{-1} (rho - (L-D) f)``.
    """

    def step_operator(self, f, lhs, rho):
        omega = Var("omega")
        D = self._diagonal(f, lhs)
        R_y = lhs - D * f  # valid for linear equations, as in the reference
        return (1 - omega) * f + omega * (rho - R_y) / D


class NewtonIterator(RelaxationBase):
    """Newton iteration for arbitrary (nonlinear) systems (reference
    relax.py:352-373): ``f <- f - omega (L(f) - rho) / (dL/df)``."""

    def step_operator(self, f, lhs, rho):
        omega = Var("omega")
        D = self._diagonal(f, lhs)
        return f - omega * (lhs - rho) / D
