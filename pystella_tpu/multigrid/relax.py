"""Relaxation (smoothing) solvers for boundary-value problems L(f) = rho.

TPU-native counterpart of /root/reference/pystella/multigrid/relax.py:36-373.
The reference builds four loopy kernels per solver (stepper, residual,
lhs-correction, residual statistics) and ping-pongs ``f``/``tmp_f`` arrays
with a halo exchange per iteration. Here each of those becomes a jitted
function; the whole ``nu``-iteration smooth runs as ONE compiled
computation — a ``lax.fori_loop`` whose body fuses the stencil evaluation
with the pointwise update, with ``lax.ppermute`` halo exchanges inside (via
``shard_map``) on sharded levels and periodic-wrap pads on replicated
(coarse) levels.

Equations are specified as in the reference (``lhs_dict`` mapping unknown
:class:`~pystella_tpu.Field`\\ s to ``(lhs, rho)`` pairs), with one
TPU-first change: the Laplacian appears *symbolically* as
``Field("lap_<name>")`` and is supplied by the solver from the
order-``2h`` centered stencil, so the smoother's effective operator is
exactly consistent with :class:`~pystella_tpu.FiniteDifferencer`. The
Jacobi/Newton diagonal is ``diff(lhs, f) + diff(lhs, lap_f) * lap_diag``
where ``lap_diag = sum_d c_0 / dx_d**2`` is the stencil's center weight
(the chain-rule term the reference gets from symbolic stencil
differentiation, relax.py:341-349).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pystella_tpu import field as _field
from pystella_tpu.field import Field, Var, diff, evaluate
from pystella_tpu.ops.derivs import (
    SecondCenteredDifference, _apply_centered, _shifted)
from pystella_tpu.multigrid.transfer import periodic_pad

__all__ = ["LevelSpec", "RelaxationBase", "JacobiIterator", "NewtonIterator"]


@dataclasses.dataclass(frozen=True)
class LevelSpec:
    """Geometry of one multigrid level: global shape, spacing, and whether
    its arrays are sharded over the mesh (coarse levels whose local blocks
    would drop below the stencil halo are replicated instead — the
    level-dependent re-decomposition the reference gets by building a
    ``DomainDecomposition`` per level, multigrid/__init__.py:357-366)."""

    grid_shape: tuple
    dx: tuple
    sharded: bool


def _field_name(f):
    if isinstance(f, _field.Field):
        return f.name
    if isinstance(f, str):
        return f
    raise TypeError(f"lhs_dict keys must be Field or str, got {type(f)}")


class RelaxationBase:
    """Base class for relaxation solvers (reference relax.py:36-320).

    :arg decomp: a :class:`~pystella_tpu.DomainDecomposition` (used for
        sharded levels; replicated levels need no communication).
    :arg lhs_dict: dict ``{Field(f): (lhs, rho)}``; ``lhs`` is a symbolic
        expression in ``Field(f)``, ``Field("lap_" + f)`` and any auxiliary
        names; ``rho`` must be a :class:`~pystella_tpu.Field`.
    :arg halo_shape: stencil radius ``h`` of the order-``2h`` Laplacian.
    :arg omega: relaxation damping factor (the reference passes it via
        ``fixed_parameters=dict(omega=...)``, which is also accepted).
    """

    def __init__(self, decomp, lhs_dict, halo_shape=1, omega=1.0,
                 dtype=None, **kwargs):
        self.decomp = decomp
        self.halo_shape = int(halo_shape)
        self.omega = float(kwargs.pop("fixed_parameters", {}).get(
            "omega", omega))
        self.dtype = dtype
        self.stencil = SecondCenteredDifference(self.halo_shape)

        self.f_to_rho_dict = {}
        self.step_exprs = {}
        self.resid_exprs = {}
        self.lhs_exprs = {}
        for f, (lhs, rho) in lhs_dict.items():
            name = _field_name(f)
            if not isinstance(rho, _field.Field):
                raise TypeError("rho must be a Field naming the source array")
            self.f_to_rho_dict[name] = rho.name
            fsym = f if isinstance(f, _field.Field) else Field(name)
            self.step_exprs[name] = self.step_operator(fsym, lhs, rho)
            self.resid_exprs[name] = rho - lhs
            self.lhs_exprs[name] = lhs
        self._compiled = {}

    # -- subclass hook ------------------------------------------------------

    def step_operator(self, f, lhs, rho):
        """Symbolic relaxation update for unknown ``f`` (reference
        relax.py:140-150)."""
        raise NotImplementedError

    def _diagonal(self, f, lhs):
        """d lhs / d f including the Laplacian's center weight."""
        lap = Field("lap_" + f.name)
        return diff(lhs, f) + diff(lhs, lap) * Var("_lap_diag")

    # -- local stencil + environment ---------------------------------------

    def _local_lap(self, x, dx, pad_fn):
        h = self.halo_shape
        la = x.ndim - 3
        padded = pad_fn(x, (h,) * 3)
        acc = None
        for d in range(3):
            y = padded
            for other in range(3):
                if other != d:
                    y = _shifted(y, la + other, 0, h)
            term = _apply_centered(y, la + d, self.stencil.coefs, h, 2,
                                   1 / dx[d] ** 2)
            acc = term if acc is None else acc + term
        return acc

    def _lap_diag(self, dx):
        return float(sum(self.stencil.coefs[0] / d ** 2 for d in dx))

    def _env(self, fs, rhos, aux, dx, pad_fn):
        env = {**aux, **rhos, **fs}
        for n in fs:
            env["lap_" + n] = self._local_lap(fs[n], dx, pad_fn)
        env["omega"] = self.omega
        env["_lap_diag"] = self._lap_diag(dx)
        return env

    # -- compiled per-level operations --------------------------------------

    def _get_compiled(self, kind, level, nu=None, decomp=None):
        decomp = decomp if decomp is not None else self.decomp
        key = (kind, level, nu, decomp)
        cached = self._compiled.get(key)
        if cached is not None:
            return cached

        pad_fn = (decomp.pad_with_halos if level.sharded
                  else periodic_pad)
        dx = level.dx

        if kind == "smooth":
            def body(fs, rhos, aux):
                def it(_, fs):
                    env = self._env(fs, rhos, aux, dx, pad_fn)
                    return {n: evaluate(self.step_exprs[n], env)
                            for n in fs}
                return lax.fori_loop(0, nu, it, fs)
        elif kind == "residual":
            def body(fs, rhos, aux):
                env = self._env(fs, rhos, aux, dx, pad_fn)
                return {n: evaluate(self.resid_exprs[n], env) for n in fs}
        elif kind == "tau":
            # FAS coarse-grid right-hand side: restricted fine residual
            # plus the coarse operator applied to the restricted unknowns
            # (reference lhs_correction, relax.py:202-214)
            def body(fs, rr, aux):
                env = self._env(fs, {}, aux, dx, pad_fn)
                return {self.f_to_rho_dict[n]:
                        rr[n] + evaluate(self.lhs_exprs[n], env)
                        for n in fs}
        else:
            raise ValueError(kind)

        if level.sharded:
            spec = decomp.spec(0)
            fn = jax.jit(decomp.shard_map(body, (spec, spec, spec), spec))
        else:
            fn = jax.jit(body)
        self._compiled[key] = fn
        return fn

    def _cast(self, arrays):
        if self.dtype is None:
            return arrays
        return {k: jnp.asarray(v, self.dtype) for k, v in arrays.items()}

    def smooth(self, level, fs, rhos, aux, iterations, decomp=None):
        """Run ``iterations`` relaxation sweeps; returns updated unknowns."""
        return self._get_compiled("smooth", level, int(iterations), decomp)(
            self._cast(fs), self._cast(rhos), self._cast(aux))

    def residual(self, level, fs, rhos, aux, decomp=None):
        """``rho - L(f)`` per unknown (reference relax.py:216-223)."""
        return self._get_compiled("residual", level, None, decomp)(
            self._cast(fs), self._cast(rhos), self._cast(aux))

    def tau_rhs(self, level, fs, restricted_resid, aux, decomp=None):
        """Coarse-level rho with FAS tau-correction."""
        return self._get_compiled("tau", level, None, decomp)(
            self._cast(fs), self._cast(restricted_resid), self._cast(aux))

    def get_error(self, level, fs, rhos, aux, decomp=None):
        """L-infinity and L2 norms of the residual per unknown (reference
        relax.py:242-266)."""
        r = self.residual(level, fs, rhos, aux, decomp)
        return {n: [float(jnp.max(jnp.abs(rn))),
                    float(jnp.sqrt(jnp.mean(rn * rn)))]
                for n, rn in r.items()}

    # -- standalone relaxation (reference __call__, relax.py:164-200) -------

    def __call__(self, decomp, iterations=100, dx=None, **arrays):
        """Relax for ``iterations`` sweeps on global arrays. Unknowns, rho,
        and auxiliary arrays are passed by keyword; returns the dict of
        updated unknowns."""
        if dx is None:
            raise ValueError("dx is required")
        if np.isscalar(dx):
            dx = (float(dx),) * 3
        fs = {n: arrays.pop(n) for n in self.f_to_rho_dict}
        rhos = {r: arrays.pop(r) for r in self.f_to_rho_dict.values()}
        first = next(iter(fs.values()))
        sharded = (decomp is not None
                   and any(p > 1 for p in decomp.proc_shape))
        level = LevelSpec(tuple(first.shape[-3:]), tuple(dx), sharded)
        return self.smooth(level, fs, rhos, arrays, iterations, decomp)


class JacobiIterator(RelaxationBase):
    """Damped Jacobi iteration for linear systems (reference
    relax.py:323-349): ``f <- (1-omega) f + omega D^{-1} (rho - (L-D) f)``.
    """

    def step_operator(self, f, lhs, rho):
        omega = Var("omega")
        D = self._diagonal(f, lhs)
        R_y = lhs - D * f  # valid for linear equations, as in the reference
        return (1 - omega) * f + omega * (rho - R_y) / D


class NewtonIterator(RelaxationBase):
    """Newton iteration for arbitrary (nonlinear) systems (reference
    relax.py:352-373): ``f <- f - omega (L(f) - rho) / (dL/df)``."""

    def step_operator(self, f, lhs, rho):
        omega = Var("omega")
        D = self._diagonal(f, lhs)
        return f - omega * (lhs - rho) / D
