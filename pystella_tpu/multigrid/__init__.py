"""Geometric multigrid solvers on sharded 3-D lattices.

TPU-native counterpart of /root/reference/pystella/multigrid/__init__.py.
Cycles are the same ``(level, iterations)`` walks; the Full Approximation
Scheme and linear multigrid keep the reference's transfer semantics
(restrict unknowns + tau-corrected right-hand side going down,
correction-interpolation going up, multigrid/__init__.py:244-283) but are
*functional*: a cycle maps input arrays to output arrays, and every
per-level operation is a jitted XLA computation.

Level placement: fine levels run sharded over the device mesh (halo
exchange by ``lax.ppermute`` inside ``shard_map``); once a level's local
block would fall below the stencil/transfer halo, that level and all
coarser ones are computed replicated (every device redundantly owns the
whole coarse grid — cheaper than communicating 8**3 points). This replaces
the reference's per-level ``DomainDecomposition`` rebuild
(multigrid/__init__.py:357-366).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from pystella_tpu.obs import events as _events
from pystella_tpu.obs import memory as _obs_memory
from pystella_tpu.obs import metrics as _metrics
from pystella_tpu.obs.scope import trace_scope
from pystella_tpu.multigrid.relax import (
    LevelSpec, RelaxationBase, JacobiIterator, NewtonIterator)
from pystella_tpu.multigrid.transfer import (
    RestrictionBase, FullWeighting, Injection,
    InterpolationBase, LinearInterpolation, CubicInterpolation,
    periodic_pad, _run_local)

__all__ = [
    "mu_cycle", "v_cycle", "w_cycle", "f_cycle",
    "FullApproximationScheme", "MultiGridSolver",
    "RelaxationBase", "JacobiIterator", "NewtonIterator",
    "RestrictionBase", "FullWeighting", "Injection",
    "InterpolationBase", "LinearInterpolation", "CubicInterpolation",
    "LevelSpec", "periodic_pad",
]


def mu_cycle(mu, i, nu1, nu2, max_depth):
    """Generic recursive mu-cycle as a list of ``(level, iterations)``
    (reference multigrid/__init__.py:55-80). Level ``i`` has ``2**i`` fewer
    points per axis than the finest grid."""
    if i == max_depth:
        return [(i, nu2)]
    x = mu_cycle(mu, i + 1, nu1, nu2, max_depth)
    return [(i, nu1)] + x + x[1:] * (mu - 1) + [(i, nu2)]


def v_cycle(nu1, nu2, max_depth):
    """V-cycle (reference multigrid/__init__.py:83-105)."""
    return mu_cycle(1, 0, nu1, nu2, max_depth)


def w_cycle(nu1, nu2, max_depth):
    """W-cycle (reference multigrid/__init__.py:108-131)."""
    return mu_cycle(2, 0, nu1, nu2, max_depth)


def _updown(i, j, k, nu1, nu2):
    down = [(a, nu1) for a in range(i, j)]
    up = [(a, nu2) for a in range(j, k - 1, -1)]
    return down + up


def f_cycle(nu1, nu2, max_depth):
    """F-cycle (reference multigrid/__init__.py:140-166)."""
    cycle = _updown(0, max_depth, max_depth - 1, nu1, nu2)
    for top in range(max_depth - 1, 0, -1):
        cycle += _updown(top + 1, max_depth, top - 1, nu1, nu2)
    return cycle


class FullApproximationScheme:
    """Nonlinear multigrid via the Full Approximation Scheme (reference
    multigrid/__init__.py:169-439).

    :arg solver: a :class:`RelaxationBase` subclass instance
        (:class:`JacobiIterator` or :class:`NewtonIterator`).
    :arg halo_shape: stencil/transfer halo width; defaults to the solver's.
    :arg Restrictor: defaults to :class:`FullWeighting`.
    :arg Interpolator: defaults to :class:`LinearInterpolation`.
    :arg defer_errors: error-norm materialization. ``True`` keeps the
        per-smooth residual norms as device scalars until the cycle end
        (one batched fetch — eager per-smooth ``float()`` syncs
        serialized the whole V-cycle on the tunneled TPU); ``False``
        materializes eagerly. Default ``None`` auto-selects: deferred on
        accelerator backends, eager on CPU (where deferring across a
        3-axis virtual mesh was measured to abort XLA's CPU runtime).

    Unknown keyword arguments raise ``TypeError`` (a misspelled
    ``defer_errors`` silently changing sync behavior is exactly the kind
    of contamination the event log exists to catch).

    Call with the fine decomposition, the fine grid spacing, an optional
    cycle, and all arrays by keyword; returns ``(errors, unknowns)`` where
    ``errors`` is the reference's list of ``(level, {name: [Linf, L2]})``
    entries and ``unknowns`` the updated solution arrays (functional — the
    inputs are not mutated).
    """

    def __init__(self, solver, halo_shape=None, **kwargs):
        self.solver = solver
        self.halo_shape = (int(halo_shape) if halo_shape is not None
                           else solver.halo_shape)
        Restrictor = kwargs.pop("Restrictor", FullWeighting)
        self.restrictor = Restrictor(halo_shape=self.halo_shape)
        Interpolator = kwargs.pop("Interpolator", LinearInterpolation)
        self.interpolator = Interpolator(halo_shape=self.halo_shape)
        #: error-norm materialization: deferred (device scalars converted
        #: once at cycle end) keeps the device queue full — per-smooth
        #: ``float()`` syncs serialized the whole cycle on the remote
        #: (tunneled) TPU: 24 syncs x round-trip made a 512^3 V-cycle
        #: ~5.2 s whichever smoother tier ran. Eager stays the default on
        #: CPU, where deferring device scalars across a 3-axis virtual
        #: mesh was measured to abort XLA's CPU runtime.
        defer = kwargs.pop("defer_errors", None)
        self._defer_errors = defer
        if kwargs:
            raise TypeError(
                f"{type(self).__name__}() got unexpected keyword "
                f"argument(s): {', '.join(sorted(kwargs))}")
        self._transfer_cache = {}

    # -- level geometry -----------------------------------------------------

    def _make_levels(self, decomp, grid_shape, dx0, depth):
        if np.isscalar(dx0):
            dx0 = (float(dx0),) * 3
        dx0 = tuple(float(d) for d in dx0)
        # minimum local block so every halo pad (Laplacian h, restriction
        # pad, interpolation pad) fits, and restriction's fine block is even
        min_block = max(self.halo_shape, self.restrictor.pad,
                        self.interpolator.pad, 2)
        levels = []
        for i in range(depth + 1):
            shape_i = tuple(n >> i for n in grid_shape)
            if any(n << i != g for n, g in zip(shape_i, grid_shape)):
                raise ValueError(
                    f"grid {grid_shape} not divisible by 2**{i} for "
                    f"multigrid depth {depth}")
            sharded = any(p > 1 for p in decomp.proc_shape) and all(
                n % p == 0 and n // p >= min_block and (n // p) % 2 == 0
                for n, p in zip(shape_i, decomp.proc_shape))
            # once a level is replicated all coarser ones are too
            if levels and not levels[-1].sharded:
                sharded = False
            levels.append(LevelSpec(
                shape_i, tuple(d * 2 ** i for d in dx0), sharded))
        return levels

    # -- transfers ----------------------------------------------------------

    def _replicate(self, decomp, x):
        return jax.device_put(
            x, NamedSharding(decomp.mesh, P(*(None,) * x.ndim)))

    def _transfer_fn(self, op, decomp, key):
        key = key + (decomp,)
        cached = self._transfer_cache.get(key)
        if cached is None:
            spec = decomp.spec(0)

            def body(blk):
                return op.apply_local(blk, pad_fn=decomp.pad_with_halos)

            cached = _obs_memory.instrument_jit(
                jax.jit(decomp.shard_map(body, spec, spec)),
                label=f"mg.transfer.{type(op).__name__}")
            self._transfer_cache[key] = cached
        return cached

    def _restrict(self, decomp, lf, lc, x):
        """Restrict ``x`` from (fine) level ``lf`` to (coarse) ``lc``.
        Replicated levels go through ``_run_local``'s jitted path (one
        executable instead of ~a dozen eager dispatches per transfer —
        measured as the dominant V-cycle orchestration cost)."""
        if lc.sharded:
            return self._transfer_fn(
                self.restrictor, decomp, ("r", lf.grid_shape))(x)
        if lf.sharded:
            x = self._replicate(decomp, x)
        return _run_local(self.restrictor, x, None)

    def _interpolate(self, decomp, lc, lf, x):
        """Interpolate ``x`` from (coarse) level ``lc`` to (fine) ``lf``."""
        if lc.sharded and lf.sharded:
            return self._transfer_fn(
                self.interpolator, decomp, ("i", lc.grid_shape))(x)
        out = _run_local(self.interpolator, x, None)
        if lf.sharded:
            out = jax.device_put(out, decomp.sharding(out.ndim - 3))
        return out

    # -- cycle steps (reference transfer_down/transfer_up/smooth) -----------

    def transfer_down(self, decomp, levels, i, unknowns, rhos, aux):
        """Restrict unknowns and build the tau-corrected coarse rho
        (reference multigrid/__init__.py:244-267)."""
        solver = self.solver
        unknowns[i] = {n: self._restrict(decomp, levels[i - 1], levels[i], f)
                       for n, f in unknowns[i - 1].items()}
        r_fine = solver.residual(levels[i - 1], unknowns[i - 1],
                                 rhos[i - 1], aux[i - 1], decomp)
        rr = {n: self._restrict(decomp, levels[i - 1], levels[i], r)
              for n, r in r_fine.items()}
        rhos[i] = solver.tau_rhs(levels[i], unknowns[i], rr, aux[i], decomp)

    def transfer_up(self, decomp, levels, i, unknowns, rhos, aux):
        """Correct the finer level ``i`` by the coarse-grid change
        (reference multigrid/__init__.py:269-283): the correction is the
        smoothed coarse solution minus the restricted fine one, and is
        interpolated up and added."""
        for n, f_fine in unknowns[i].items():
            corr = (unknowns[i + 1][n]
                    - self._restrict(decomp, levels[i], levels[i + 1],
                                     f_fine))
            unknowns[i][n] = f_fine + self._interpolate(
                decomp, levels[i + 1], levels[i], corr)

    def smooth(self, levels, i, nu, unknowns, rhos, aux, decomp=None):
        """Relax level ``i`` for ``nu`` sweeps, recording errors before and
        after (reference multigrid/__init__.py:285-302). On accelerator
        backends the norms stay device scalars until the cycle end
        (``__call__`` materializes them once) — eager per-smooth
        ``float()`` syncs serialize the device queue, which costs a
        round trip per norm on the tunneled TPU. On CPU they materialize
        eagerly (deferring across a 3-axis virtual mesh was measured to
        abort XLA's CPU runtime)."""
        solver = self.solver
        defer = (self._defer_errors if self._defer_errors is not None
                 else jax.default_backend() != "cpu")
        err_fn = solver.error_arrays if defer else solver.get_error
        errs1 = err_fn(levels[i], unknowns[i], rhos[i], aux[i], decomp)
        unknowns[i] = solver.smooth(levels[i], unknowns[i], rhos[i],
                                    aux[i], nu, decomp)
        errs2 = err_fn(levels[i], unknowns[i], rhos[i], aux[i], decomp)
        return [(i, errs1), (i, errs2)]

    @staticmethod
    def _materialize_errors(errors):
        """Convert any deferred device-scalar norms to floats via ONE
        batched ``device_get`` of the whole record — per-scalar
        ``float()`` fetches would still pay a device round trip each
        (tens of them on the tunneled TPU), defeating the deferral."""
        fetched = jax.device_get(errors)
        return [(i, {n: [float(a), float(b)]
                     for n, (a, b) in errs.items()})
                for i, errs in fetched]

    # -- entry point --------------------------------------------------------

    def __call__(self, decomp, dx0=None, cycle=None, **kwargs):
        solver = self.solver
        unknowns0 = {n: kwargs.pop(n) for n in solver.f_to_rho_dict}
        rhos0 = {r: kwargs.pop(r)
                 for r in solver.f_to_rho_dict.values()}
        aux0 = kwargs
        grid_shape = tuple(next(iter(unknowns0.values())).shape[-3:])
        if dx0 is None:
            raise ValueError("dx0 is required")

        if cycle is None:
            depth = max(1, int(np.log2(min(grid_shape) / 8)))
            cycle = v_cycle(25, 50, depth)
        depth = max(i for i, _ in cycle)

        levels = self._make_levels(decomp, grid_shape, dx0, depth)

        aux = {0: aux0}
        for i in range(1, depth + 1):
            aux[i] = {k: self._restrict(decomp, levels[i - 1], levels[i], v)
                      for k, v in aux[i - 1].items()}
        unknowns = {0: dict(unknowns0)}
        rhos = {0: dict(rhos0)}

        with _metrics.timer("mg_cycle_s"), trace_scope("mg_cycle"):
            errors = self.smooth(levels, 0, cycle[0][1], unknowns, rhos,
                                 aux, decomp)
            previous = 0
            for i, nu in cycle[1:]:
                if i == previous + 1:
                    self.transfer_down(decomp, levels, i, unknowns, rhos,
                                       aux)
                elif i == previous - 1:
                    self.transfer_up(decomp, levels, i, unknowns, rhos,
                                     aux)
                else:
                    raise ValueError(
                        "consecutive levels must be spaced by one")
                errors += self.smooth(levels, i, nu, unknowns, rhos, aux,
                                      decomp)
                previous = i
            materialized = self._materialize_errors(errors)
        _metrics.counter("mg_cycles").inc()
        _metrics.counter("mg_smooths").inc(len(cycle))
        final = materialized[-1][1] if materialized else {}
        _events.emit("mg_cycle", depth=depth, grid_shape=grid_shape,
                     nsmooths=len(cycle), final_errors=final)
        return materialized, unknowns[0]


class MultiGridSolver(FullApproximationScheme):
    """Linear (correction-scheme) multigrid (reference
    multigrid/__init__.py:442-478). The coarse equation is ``L e = R r``
    with a zero initial guess for the correction ``e`` (the reference omits
    the zeroing — its noted slow convergence, __init__.py:463 — so this
    implementation adds it); going up, the correction is interpolated and
    added to the finer solution."""

    def transfer_down(self, decomp, levels, i, unknowns, rhos, aux):
        solver = self.solver
        r_fine = solver.residual(levels[i - 1], unknowns[i - 1],
                                 rhos[i - 1], aux[i - 1], decomp)
        rhos[i] = {}
        unknowns[i] = {}
        for n, r in r_fine.items():
            rr = self._restrict(decomp, levels[i - 1], levels[i], r)
            rhos[i][solver.f_to_rho_dict[n]] = rr
            unknowns[i][n] = jnp.zeros_like(rr)

    def transfer_up(self, decomp, levels, i, unknowns, rhos, aux):
        for n, f_fine in unknowns[i].items():
            unknowns[i][n] = f_fine + self._interpolate(
                decomp, levels[i + 1], levels[i], unknowns[i + 1][n])
