"""Batched member stepping: vmapped/mapped wrappers over the steppers.

The single-run steppers (:class:`~pystella_tpu.Stepper`,
:class:`~pystella_tpu.FusedScalarStepper`) advance ONE lattice per
call. :class:`EnsembleStepper` turns any of them into a population
engine: a batch of ``size`` members lives as ONE pytree whose leaves
carry a leading member axis, per-member parameters (couplings, dt,
time, IC draws) enter as batched pytree leaves, and the whole batch
advances as one jitted computation — one trace, one compile, no
re-trace per member.

Two batching tiers, chosen by ``via``:

``"vmap"``
    ``jax.vmap`` of the stepper's step body — the XLA tier. The
    partitioner sees the whole batched program, so on an
    ``(ensemble, x, y, z)`` mesh (:func:`~pystella_tpu.ensemble_mesh`)
    the member axis shards over the ensemble devices and each member's
    stencils/reductions stay shard-local. Member results agree with
    sequential single-member runs to a few ulp (vmap changes XLA fusion
    boundaries, not the math).
``"map"``
    ``jax.lax.map`` over the member axis — the fused-Pallas tier. The
    member body is traced ONCE at single-member shapes, so the Mosaic
    kernels run exactly as built (``pallas_call`` needs no batching
    rule) and member results are BIT-EXACT with sequential runs. The
    loop is sequential per device; use it for packed (spatially
    unsharded) members where throughput comes from the kernels, not
    from cross-member parallelism inside one device.

``via="auto"`` picks ``"map"`` for fused steppers (anything carrying a
Pallas chunk body — detected via the ``_multi_step_impl`` marker) and
``"vmap"`` otherwise.

Per-member arguments: ``t`` and ``dt`` may be scalars (shared) or
``(size,)`` arrays; ``rhs_args`` leaves may be scalars or arrays with a
leading ``size`` axis. :meth:`EnsembleStepper.batch_args` normalizes
everything to batched leaves before the dispatch.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from pystella_tpu.obs import memory as _obs_memory
from pystella_tpu.obs.scope import trace_scope

__all__ = ["EnsembleStepper", "repack_members"]


def repack_members(batch, decomp):
    """Re-place a batched ``(members, ...)`` state pytree onto a
    DIFFERENT ensemble decomposition — the member-axis repack of a
    re-mesh (:mod:`pystella_tpu.resilience.remesh`): the member count
    is unchanged, but the ensemble device extent shrank, so ``E``
    members over ``D'`` surviving devices land as ``E / D'`` per mesh
    slice. The new extent must divide the member count
    (``shard_members`` raises otherwise — the planner's member-axis
    shrink rule guarantees it picks such an extent). Checkpointed
    batches take the equivalent zero-copy path through
    ``Checkpointer.restore(mesh=new_decomp)`` instead; this is the
    in-memory repack for a batch that survived in host or device
    buffers."""
    import jax as _jax
    return _jax.tree_util.tree_map(decomp.shard_members, batch)


class EnsembleStepper:
    """Drive ``size`` members of a base stepper as one batched program.

    :arg stepper: any :class:`~pystella_tpu.Stepper` (including the
        fused Pallas steppers).
    :arg size: member count of every batch this wrapper dispatches.
    :arg decomp: optional ensemble-aware
        :class:`~pystella_tpu.DomainDecomposition` (built over an
        :func:`~pystella_tpu.ensemble_mesh`); when given,
        :meth:`stack` places batches with the member axis over the
        ensemble devices.
    :arg via: ``"vmap"`` | ``"map"`` | ``"auto"`` (see module
        docstring).
    :arg donate: donate the input batch buffers to each dispatch
        (the batch is rebound ``batch = step(batch)`` in driver loops;
        off by default because the eviction path re-reads slots).
    """

    def __init__(self, stepper, size, decomp=None, via="auto",
                 donate=False):
        self.stepper = stepper
        self.size = int(size)
        if self.size < 1:
            raise ValueError(f"ensemble size must be >= 1, got {size}")
        self.decomp = decomp
        if via == "auto":
            # fused steppers carry Pallas bodies (their chunked
            # _multi_step_impl); lax.map keeps those single-member
            via = "map" if hasattr(stepper, "_multi_step_impl") \
                else "vmap"
        if via not in ("vmap", "map"):
            raise ValueError(f"unknown batching tier {via!r}")
        self.via = via
        self._donate = bool(donate)
        self._jits = {}        # (kind, nsteps, sentinel-id) -> jitted
        self._write_jit = None

    # -- batch construction -------------------------------------------------

    def batch_args(self, tree):
        """Normalize an argument pytree to batched leaves: leaves whose
        leading axis is already ``size`` pass through, everything else
        is broadcast to a leading member axis. (A per-member SCALAR
        parameter is therefore a ``(size,)`` array, never a bare list.)
        """
        def go(x):
            x = jnp.asarray(x)
            if x.ndim >= 1 and x.shape[0] == self.size:
                return x
            return jnp.broadcast_to(x, (self.size,) + x.shape)
        return jax.tree_util.tree_map(go, tree)

    def stack(self, states):
        """One batched state pytree from ``size`` member states
        (stacked along a new leading axis and, with an ensemble
        ``decomp``, placed member-axis-over-ensemble-devices)."""
        states = list(states)
        if len(states) != self.size:
            raise ValueError(f"need {self.size} member states, "
                             f"got {len(states)}")
        if self.decomp is not None and self.decomp.ensemble_axis is not None:
            # stack on HOST and let shard_members device_put straight
            # to the batched sharding: jnp.stack would commit the whole
            # population to the default device first, which OOMs for
            # exactly the spatially-sharded large-lattice case the
            # ensemble mesh exists for (the sharded batch fits the
            # mesh; one device's copy of all of it does not)
            batched = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *states)
            return self.place(batched)
        batched = jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *states)
        return batched

    def place(self, batched):
        """Apply the ensemble mesh placement to an already-batched
        state (no-op without a ``decomp``)."""
        if self.decomp is None or self.decomp.ensemble_axis is None:
            return batched
        return jax.tree_util.tree_map(self.decomp.shard_members, batched)

    def take_member(self, batched, index):
        """Host copy of member ``index``'s state (forces a sync — use
        at retire/checkpoint points, not in the hot loop)."""
        return jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a[index])), batched)

    # -- the batched bodies -------------------------------------------------

    def _member_fn(self, nsteps):
        if nsteps == 1:
            return lambda st, t, dt, ra: self.stepper._step_impl(
                st, t, dt, ra)
        return self.stepper.multi_step_fn(nsteps)

    def _spmd_axis_name(self):
        """The ensemble mesh-axis name for ``jax.vmap``'s
        ``spmd_axis_name``: member bodies containing ``shard_map``s
        (halo-mode stencils) then treat the batched member axis as
        SHARDED over the ensemble devices instead of replicating it —
        without this, vmap-of-shard_map would all-gather every member
        onto every ensemble slice."""
        if (self.decomp is not None
                and self.decomp.ensemble_axis is not None
                and self.decomp.ensemble_devices > 1):
            return self.decomp.ensemble_axis
        return None

    def _batched_impl(self, nsteps):
        """The batched chunk body ``(batch, t_vec, dt_vec, rhs_args) ->
        batch`` under the selected tier."""
        member = self._member_fn(int(nsteps))
        if self.via == "vmap":
            spmd = self._spmd_axis_name()

            def run(batch, t, dt, rhs_args):
                with trace_scope("ensemble_step"):
                    return jax.vmap(member, spmd_axis_name=spmd)(
                        batch, t, dt, rhs_args)
        else:
            def run(batch, t, dt, rhs_args):
                with trace_scope("ensemble_step"):
                    return jax.lax.map(lambda a: member(*a),
                                       (batch, t, dt, rhs_args))
        return run

    def _get_jit(self, nsteps, sentinel=None, aux_arg=False):
        key = (int(nsteps), None if sentinel is None else id(sentinel),
               bool(aux_arg))
        fn = self._jits.get(key)
        if fn is not None:
            return fn
        run = self._batched_impl(nsteps)
        if sentinel is None:
            impl = run
        elif aux_arg:
            def impl(batch, t, dt, rhs_args, aux):
                new = run(batch, t, dt, rhs_args)
                with trace_scope("sentinel"):
                    hm = sentinel.compute_members(new, aux)
                return new, hm
        else:
            def impl(batch, t, dt, rhs_args):
                new = run(batch, t, dt, rhs_args)
                with trace_scope("sentinel"):
                    hm = sentinel.compute_members(new)
                return new, hm
        label = (f"ensemble.{self.via}[{self.size}x{int(nsteps)}]"
                 + (".health" if sentinel is not None else ""))
        fn = _obs_memory.instrument_jit(
            jax.jit(impl, donate_argnums=(0,) if self._donate else ()),
            label=label, donated=self._donate)
        self._jits[key] = fn
        return fn

    def health_jit(self, sentinel):
        """The cached jitted step+health executable for ``sentinel`` —
        also the IR-audit entry point (``pystella_tpu.lint`` lowers it
        to prove the member-axis health reductions fuse into the
        batched step module). Signature: ``(batch, t_vec, dt_vec,
        rhs_args, aux) -> (batch, health_matrix)``."""
        return self._get_jit(1, sentinel, aux_arg=True)

    # -- dispatch ------------------------------------------------------------

    def _norm(self, t, dt, rhs_args):
        dt = dt if dt is not None else self.stepper.dt
        if dt is None:
            raise ValueError("no dt: pass dt= or construct the base "
                             "stepper with one")
        return (self.batch_args(t), self.batch_args(dt),
                self.batch_args(rhs_args or {}))

    def step(self, batch, t=0.0, dt=None, rhs_args=None):
        """Advance every member one full RK step; one jitted batched
        dispatch. ``t``/``dt`` scalars or ``(size,)`` arrays;
        ``rhs_args`` leaves scalar or member-batched."""
        t, dt, rhs_args = self._norm(t, dt, rhs_args)
        return self._get_jit(1)(batch, t, dt, rhs_args)

    def multi_step(self, batch, nsteps, t=0.0, dt=None, rhs_args=None,
                   sentinel=None):
        """Advance every member ``nsteps`` steps as one jitted chunk
        (the fused tier pairs stages across step boundaries inside
        each member, exactly as its single-run ``multi_step`` does).
        With ``sentinel`` (a :class:`~pystella_tpu.obs.sentinel.
        Sentinel` built for ONE member's state), additionally returns
        the ``(size, len(vector))`` health MATRIX of the new batch,
        computed inside the same computation — per-member numerics
        observability with no extra dispatch and no host sync."""
        t, dt, rhs_args = self._norm(t, dt, rhs_args)
        return self._get_jit(int(nsteps), sentinel)(batch, t, dt,
                                                    rhs_args)

    def step_with_health(self, batch, sentinel, t=0.0, dt=None,
                         rhs_args=None, aux=None):
        """One step + the member-axis health matrix, in one jitted
        computation (``aux`` leaves scalar or member-batched)."""
        t, dt, rhs_args = self._norm(t, dt, rhs_args)
        aux = self.batch_args(aux or {})
        return self.health_jit(sentinel)(batch, t, dt, rhs_args, aux)

    # -- eviction / slot management -----------------------------------------

    def write_member(self, batch, index, member_state):
        """Overwrite slot ``index`` of the batch with ``member_state``
        (the evict-and-resample write, traced once: the slot index is a
        device scalar, so refilling ANY slot reuses one compiled
        program — no recompile, no shape change, the rest of the batch
        untouched)."""
        if self._write_jit is None:
            def impl(b, idx, m):
                return jax.tree_util.tree_map(
                    lambda ba, ma: jax.lax.dynamic_update_index_in_dim(
                        ba, ma.astype(ba.dtype), idx, 0), b, m)
            self._write_jit = _obs_memory.instrument_jit(
                jax.jit(impl), label="ensemble.write_member",
                donated=False)
        member_state = jax.tree_util.tree_map(jnp.asarray, member_state)
        with trace_scope("ensemble_evict"):
            return self._write_jit(batch, jnp.asarray(index, jnp.int32),
                                   member_state)
