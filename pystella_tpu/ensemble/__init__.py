"""Ensemble engine: batched scenario populations instead of single runs.

Everything else in the package drives ONE lattice per process; the
ROADMAP's production story (parameter scans, Monte-Carlo over IC seeds,
coupling sweeps) needs a batch axis. This subsystem adds it end to end:

- :mod:`pystella_tpu.ensemble.batch` —
  :class:`EnsembleStepper`: ``vmap``/``lax.map`` wrappers over the
  existing steppers that advance a population as ONE jitted program,
  threading per-member parameters (couplings, dt, IC draws) as batched
  pytree leaves with no re-trace per member. The device mesh side is
  :func:`pystella_tpu.parallel.ensemble_mesh` — ``(ensemble, x, y, z)``
  so small lattices pack the chip set along the member axis and large
  ones keep their spatial sharding.
- :mod:`pystella_tpu.ensemble.driver` — :class:`EnsembleDriver` +
  :class:`Scenario`: a scenario-queue scheduler that groups
  heterogeneous work into shape-compatible batches, advances each batch
  chunk-wise with the numerics sentinel piggybacked, and refills slots
  as members finish.
- :mod:`pystella_tpu.ensemble.health` — :class:`EnsembleMonitor`:
  per-member health matrices (the single-run sentinel reductions gain a
  member axis) with **evict-and-resample** — a diverged member is
  recorded (``member_evicted`` event + member-scoped forensic bundle)
  and its slot resampled in-place, without killing or recompiling the
  batch.

Observability rides along: the :class:`~pystella_tpu.obs.ledger.
PerfLedger` gains an ``ensemble`` report section (member-steps/s,
evictions, occupancy), ``obs.gate`` a member-throughput verdict, and
``pystella_tpu.lint`` lowers the vmapped batched step so the
donation/collective/dtype audits cover the batched program too. See
``doc/ensemble.md``.
"""

from pystella_tpu.ensemble.batch import EnsembleStepper, repack_members
from pystella_tpu.ensemble.driver import EnsembleDriver, Scenario
from pystella_tpu.ensemble.health import EnsembleMonitor, Eviction

__all__ = ["EnsembleStepper", "EnsembleDriver", "Scenario",
           "EnsembleMonitor", "Eviction", "repack_members"]
