"""Scenario-queue scheduler: heterogeneous populations over one batch.

A production parameter scan is not one batch of identical members — it
is a QUEUE of scenarios (preheating configs, wave tests, GW runs) whose
members differ in parameter draws, IC seeds, and step budgets. The
:class:`EnsembleDriver` turns that queue into batched device work:

- **grouping**: jobs are grouped into shape-compatible batches — same
  base stepper, same state pytree structure/shapes/dtypes, same
  per-member parameter names — because one batched executable can only
  carry members that share a trace. Scenarios in different groups run
  as separate batches, sequentially.
- **chunked stepping**: each batch advances ``chunk`` steps per
  dispatch through :meth:`~pystella_tpu.ensemble.EnsembleStepper.
  multi_step` with the sentinel piggybacked, so per-member health
  matrices come out of the SAME computation (no extra dispatch, no
  host sync on the step path).
- **slot refill**: a member that reaches its scenario's step budget
  retires; its slot is refilled from the queue (one compiled program —
  refills are ``dynamic_update_index_in_dim`` writes, never a
  recompile). With the queue drained, idle slots keep stepping as
  masked ballast so the batch shape never changes.
- **evict-and-resample**: an unhealthy member (per the
  :class:`~pystella_tpu.ensemble.EnsembleMonitor`) is evicted — named
  in a ``member_evicted`` event and a member-scoped forensic bundle —
  and its slot resampled from the same scenario under a fresh seed
  (``PYSTELLA_ENSEMBLE_RESAMPLE=0`` masks the slot instead). The batch
  itself never dies unless the eviction budget is exhausted.
- **throughput accounting**: ``ensemble_chunk`` events per dispatch
  window and one ``ensemble_done`` event with the batch totals
  (member-steps, wall seconds, member-steps/s, mean occupancy,
  evictions) — the :class:`~pystella_tpu.obs.ledger.PerfLedger`'s
  ``ensemble`` report section and the gate's member-throughput verdict
  ingest exactly these.

A :class:`Scenario` is a named member family::

    def sample(seed):
        rng = np.random.default_rng(seed)
        state = {...}                  # ONE member's state pytree
        params = {"g2": rng.uniform(...)}   # scalar rhs_args draw
        return state, params

    sc = Scenario("preheat-g2-scan", stepper, sample, nsteps=200,
                  dt=1e-3)
    driver = EnsembleDriver(size=8, chunk=10, decomp=edecomp)
    driver.submit(sc, seeds=range(64))
    out = driver.run()

``out["results"]`` holds one record per completed member (scenario,
seed, params, final t); pass ``on_finish`` to retrieve final states
(the only host sync, at retire time by design).
"""

from __future__ import annotations

import time

import numpy as np

import jax

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import metrics as _metrics
from pystella_tpu.ensemble.batch import EnsembleStepper
from pystella_tpu.ensemble.health import EnsembleMonitor

__all__ = ["EnsembleDriver", "Scenario"]


class Scenario:
    """One member family in the queue.

    :arg name: scenario name (events, eviction records, and forensic
        bundles carry it).
    :arg stepper: the single-member stepper every member of this
        scenario advances under (any :class:`~pystella_tpu.Stepper`,
        fused included).
    :arg sample: ``sample(seed) -> (state, params)`` — one member's
        initial state pytree and its SCALAR parameter draw (a dict
        merged into the batched ``rhs_args``; may be empty). Called
        again with a fresh seed when an evicted slot is resampled.
    :arg nsteps: per-member step budget; a member retires after it.
    :arg dt: member time step — a scalar, or ``dt(seed)`` for
        per-member draws.
    :arg t0: member start time.
    :arg invariants: optional ``{name: fn}`` sentinel invariants for
        this scenario's states (the first scenario of a batch group
        defines the group's sentinel).
    """

    def __init__(self, name, stepper, sample, nsteps, dt=None, t0=0.0,
                 invariants=None):
        self.name = str(name)
        self.stepper = stepper
        self.sample = sample
        self.nsteps = int(nsteps)
        self.dt = dt
        self.t0 = float(t0)
        self.invariants = dict(invariants or {})
        if self.nsteps < 1:
            raise ValueError(f"scenario {name!r}: nsteps must be >= 1")

    def member_dt(self, seed):
        dt = self.dt if not callable(self.dt) else self.dt(seed)
        if dt is None:
            dt = self.stepper.dt
        if dt is None:
            raise ValueError(
                f"scenario {self.name!r}: no dt (pass dt= or construct "
                "the stepper with one)")
        return float(dt)

    def __repr__(self):
        return f"Scenario({self.name!r}, nsteps={self.nsteps})"


class _Job:
    __slots__ = ("scenario", "seed", "resume", "trace")

    def __init__(self, scenario, seed, resume=None, trace=None):
        self.scenario = scenario
        self.seed = int(seed)
        #: ``(state, step, t, params)`` for a job re-entering with a
        #: restored trajectory (a preempted member) instead of a fresh
        #: sampler draw — see :meth:`EnsembleDriver.requeue`
        self.resume = resume
        #: optional request-scoped trace id (obs schema v2): the
        #: member lifecycle events carry it, so a caller that owns
        #: traces (the scenario service, a traced sweep harness) can
        #: attribute driver work per request — and a requeued member
        #: keeps its trace across the drain
        self.trace = trace


class _Slot:
    """One batch slot's host-side bookkeeping."""

    __slots__ = ("index", "job", "steps_done", "t", "dt", "active")

    def __init__(self, index):
        self.index = int(index)
        self.job = None
        self.steps_done = 0
        self.t = 0.0
        self.dt = 0.0
        self.active = False


def _state_signature(state):
    """The shape-compatibility key of one member state: leaf paths with
    shapes and dtypes (two scenarios batch together iff these match)."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    sig = []
    for path, leaf in leaves:
        arr = np.asarray(leaf) if not hasattr(leaf, "shape") else leaf
        sig.append((jax.tree_util.keystr(path), tuple(arr.shape),
                    str(arr.dtype)))
    return tuple(sig)


class EnsembleDriver:
    """Run a queue of scenario jobs through batched member stepping.

    :arg size: batch member count (default: the registered
        ``PYSTELLA_ENSEMBLE_SIZE``).
    :arg chunk: steps per batched dispatch (health matrices and
        eviction decisions happen at chunk granularity).
    :arg decomp: optional ensemble-aware
        :class:`~pystella_tpu.DomainDecomposition` (an
        :func:`~pystella_tpu.ensemble_mesh` mesh) for member placement.
    :arg via / donate: forwarded to
        :class:`~pystella_tpu.ensemble.EnsembleStepper`.
    :arg every: health-matrix maturity lag in CHUNKS before a poll
        converts it (the async-consumption contract of
        :class:`~pystella_tpu.obs.sentinel.SentinelMonitor`, at chunk
        granularity).
    :arg forensics: optional :class:`~pystella_tpu.obs.forensics.
        ForensicSink` — evictions then write member-scoped bundles.
    :arg resample: eviction policy override (default: the registered
        ``PYSTELLA_ENSEMBLE_RESAMPLE``): resample the slot from its
        scenario under a fresh seed, vs. mask it out for the run.
    :arg max_evictions / max_abs / invariant_bounds / history:
        forwarded to :class:`~pystella_tpu.ensemble.EnsembleMonitor`.
    :arg emit_steps: per-chunk ``ensemble_health`` events (summary
        counts only).
    :arg preempt: optional ``preempt(chunk_index) -> bool`` polled
        after every batched dispatch; returning true DRAINS the run at
        that chunk boundary — pending health matrices are converted,
        the batch is synced, and every still-active member leaves as a
        requeue record (scenario, seed, host state, steps done, t,
        parameter draw) in the run output's ``preempted`` list, with
        unstarted jobs in ``pending``. :meth:`requeue` is the matching
        re-entry: a drained member resumes its OWN trajectory (bit-
        consistent with an uninterrupted run) instead of resampling.
        This is the scenario service's preemption hook
        (:mod:`pystella_tpu.service`).
    """

    def __init__(self, size=None, chunk=4, decomp=None, via="auto",
                 donate=False, every=1, forensics=None, resample=None,
                 max_evictions=None, max_abs=None, invariant_bounds=None,
                 history=64, emit_steps=False, label="ensemble",
                 preempt=None):
        if size is None:
            size = _config.get_int("PYSTELLA_ENSEMBLE_SIZE")
        self.size = int(size)
        self.chunk = int(chunk)
        if self.size < 1 or self.chunk < 1:
            raise ValueError("size and chunk must be >= 1")
        self.decomp = decomp
        self.via = via
        self.donate = donate
        self.every = int(every)
        self.forensics = forensics
        if resample is None:
            resample = _config.get_bool("PYSTELLA_ENSEMBLE_RESAMPLE")
        self.resample = bool(resample)
        self.max_evictions = max_evictions
        self.max_abs = max_abs
        self.invariant_bounds = dict(invariant_bounds or {})
        self.history = int(history)
        self.emit_steps = bool(emit_steps)
        self.label = str(label)
        self.preempt = preempt
        self._queue = []          # FIFO of _Job, submit order preserved
        self._next_seed = {}      # scenario name -> next resample seed
        self._predrawn = {}       # (id(scenario), seed) -> (state, params)

    # -- queue --------------------------------------------------------------

    def submit(self, scenario, seeds, trace=None):
        """Enqueue one job per seed for ``scenario`` (FIFO; grouping
        into shape-compatible batches happens at :meth:`run`).
        ``trace`` optionally tags every job's member lifecycle events
        with a request-scoped trace id (obs schema v2)."""
        seeds = [int(s) for s in seeds]
        for s in seeds:
            self._queue.append(_Job(scenario, s, trace=trace))
        nxt = self._next_seed.get(scenario.name, 0)
        self._next_seed[scenario.name] = max([nxt] + [s + 1 for s in seeds])
        return self

    def _fresh_seed(self, scenario):
        s = self._next_seed.get(scenario.name, 0)
        self._next_seed[scenario.name] = s + 1
        return s

    def requeue(self, scenario, state, step, seed=0, params=None,
                t=None, trace=None):
        """Re-enter a preempted member: the job re-joins the queue
        carrying its RESTORED state and completed step count, so its
        slot resumes the same trajectory instead of resampling from
        scratch (the only re-entry path before this was a fresh draw).
        ``state`` is one member's state pytree (host or device arrays);
        ``step`` is the number of steps already taken (the member
        retires after ``scenario.nsteps - step`` more); ``params`` is
        the member's original parameter draw; ``t`` overrides the
        resume time (default ``scenario.t0 + step * member_dt``). A
        requeued member's trajectory is bit-consistent with its
        uninterrupted run — the batched per-member bodies are
        lane-independent, so neither the preemption boundary nor the
        co-members of the resumed batch change its arithmetic.
        ``trace`` carries the member's request-scoped trace id across
        the drain — the requeued member's events keep ONE trace."""
        job = _Job(scenario, seed,
                   resume={"state": state, "step": int(step),
                           "t": t, "params": dict(params or {})},
                   trace=trace)
        self._queue.append(job)
        nxt = self._next_seed.get(scenario.name, 0)
        self._next_seed[scenario.name] = max(nxt, int(seed) + 1)
        return self

    # -- grouping -----------------------------------------------------------

    def _group_jobs(self):
        """Partition the queue into shape-compatible groups (submit
        order preserved within and across groups). The group key is
        (stepper identity, state signature of a sample draw, sorted
        parameter names): exactly the things one batched trace can't
        vary. The signature draw happens once per SCENARIO, not per
        job — a sampler producing production-size fields must not run
        twice per member just to read shapes."""
        groups = []       # list of (key, [jobs], template_state, params)
        by_key = {}
        by_scenario = {}  # id(scenario) -> (signature, param_names, template)
        self._predrawn = {}  # (id(scenario), seed) -> (state, params)
        for job in self._queue:
            sc = job.scenario
            if job.resume is not None:
                # a requeued member carries its own restored state: its
                # signature comes from THAT, not a sampler draw (and it
                # groups with fresh jobs of the same shape — one
                # batched program serves both)
                ent = (_state_signature(job.resume["state"]),
                       tuple(sorted(job.resume["params"])),
                       (job.resume["state"],
                        dict(job.resume["params"])))
            else:
                ent = by_scenario.get(id(sc))
                if ent is None:
                    state, params = sc.sample(job.seed)
                    ent = (_state_signature(state),
                           tuple(sorted(params or {})),
                           (state, dict(params or {})))
                    by_scenario[id(sc)] = ent
                    # the fill/refill path reuses this draw for the same
                    # job instead of sampling it a second time
                    self._predrawn[(id(sc), job.seed)] = ent[2]
            sig, param_names, template = ent
            key = (id(sc.stepper), sig, param_names)
            if key not in by_key:
                by_key[key] = len(groups)
                groups.append({"key": key, "jobs": [],
                               "template": template})
            groups[by_key[key]]["jobs"].append(job)
        self._queue = []
        return groups

    def _sample(self, job):
        """One member's fill: a requeued job re-enters with its
        restored state; a fresh job draws from the sampler (reusing the
        grouping pass's signature draw when it was for this very
        (scenario, seed) job)."""
        if job.resume is not None:
            return job.resume["state"], dict(job.resume["params"])
        pre = self._predrawn.pop((id(job.scenario), job.seed), None)
        if pre is not None:
            return pre[0], dict(pre[1])
        return job.scenario.sample(job.seed)

    # -- the batch loop -----------------------------------------------------

    def run(self, on_finish=None):
        """Drain the queue. Returns ``{"results": [...], "evictions":
        [...], "preempted": [...], "pending": [...], "stats": {...}}``;
        ``on_finish(record, state)`` (if given) receives each retired
        member's host state — the one deliberate host sync, at retire
        time. With a ``preempt`` hook that fired, ``preempted`` holds
        one requeue record per still-active member (pass each to
        :meth:`requeue` to resume it later) and ``pending`` one record
        per job that never started: ``{"scenario", "seed"}``, plus the
        preserved resume payload (``state``/``step``/``t``/``params``)
        when the job was itself a requeued member — pass those back
        through :meth:`requeue`, the rest through :meth:`submit`.

        Raises :class:`~pystella_tpu.obs.sentinel.SimulationDiverged`
        only when a batch exhausts its eviction budget (the
        configuration itself is broken)."""
        groups = self._group_jobs()
        _events.emit("ensemble_run", label=self.label, size=self.size,
                     chunk=self.chunk,
                     groups=[{"scenarios": sorted({j.scenario.name
                                                   for j in g["jobs"]}),
                              "jobs": len(g["jobs"])} for g in groups])
        results, evictions, preempted, pending = [], [], [], []
        totals = {"member_steps": 0, "wall_s": 0.0, "chunks": 0,
                  "occupancy_sum": 0.0, "batches": len(groups)}
        for gi, g in enumerate(groups):
            drained = self._run_group(g, results, evictions, totals,
                                      on_finish, preempted, pending)
            if drained:
                # the preempt hook fired: later groups never start —
                # their jobs leave as pending, resubmittable as-is
                # (the drained group's own unstarted jobs were already
                # recorded by the drain)
                pending += [self._pending_record(j)
                            for rest in groups[gi + 1:]
                            for j in rest["jobs"]]
                break
        rate = (totals["member_steps"] / totals["wall_s"]
                if totals["wall_s"] > 0 else None)
        occupancy = (totals["occupancy_sum"] / totals["chunks"]
                     if totals["chunks"] else None)
        stats = {
            "size": self.size,
            "batches": totals["batches"],
            "chunks": totals["chunks"],
            "member_steps": totals["member_steps"],
            "wall_s": totals["wall_s"],
            "member_steps_per_s": rate,
            "occupancy_mean": occupancy,
            "members_completed": len(results),
            "evictions": len(evictions),
            "preempted": len(preempted),
        }
        _events.emit("ensemble_done", label=self.label, **stats)
        return {"results": results, "evictions": evictions,
                "preempted": preempted, "pending": pending,
                "stats": stats}

    def _make_monitor(self, sentinel):
        return EnsembleMonitor(
            sentinel, self.size, every=self.every, history=self.history,
            max_abs=self.max_abs, invariant_bounds=self.invariant_bounds,
            emit_steps=self.emit_steps, label=self.label,
            forensics=self.forensics, max_evictions=self.max_evictions)

    def _run_group(self, group, results, evictions, totals, on_finish,
                   preempted=None, pending=None):
        from pystella_tpu import obs

        jobs = list(group["jobs"])
        template_state, template_params = group["template"]
        stepper = jobs[0].scenario.stepper
        ens = EnsembleStepper(stepper, self.size, decomp=self.decomp,
                              via=self.via, donate=self.donate)
        sentinel = obs.Sentinel.for_state(
            template_state, invariants=jobs[0].scenario.invariants)
        monitor = self._make_monitor(sentinel)

        # initial fill: one sampled member per slot; spare slots carry
        # the template state as masked ballast (the batch shape is
        # fixed for the group's lifetime)
        slots = [_Slot(i) for i in range(self.size)]
        param_names = tuple(sorted(template_params))
        params = {n: np.zeros(self.size, dtype=np.float64)
                  for n in param_names}
        member_states = []
        t_vec = np.zeros(self.size)
        dt_vec = np.zeros(self.size)
        for slot in slots:
            if jobs:
                job = jobs.pop(0)
                state, draw = self._sample(job)
                self._arm(slot, job, draw, params, monitor)
                member_states.append(state)
                t_vec[slot.index] = slot.t
                dt_vec[slot.index] = slot.dt
            else:
                member_states.append(template_state)
                monitor.mask_member(slot.index)
                dt_vec[slot.index] = 1.0  # ballast: any finite dt
        batch = ens.stack(member_states)

        chunk_index = 0
        group_t0 = time.perf_counter()
        while any(s.active for s in slots):
            active = sum(s.active for s in slots)
            t_wall = time.perf_counter()
            batch, matrix = ens.multi_step(
                batch, self.chunk, t=t_vec, dt=dt_vec,
                rhs_args={n: params[n] for n in param_names},
                sentinel=sentinel)
            chunk_index += 1
            monitor.push(chunk_index, matrix)
            new_ev = monitor.poll()
            # dispatch-window time: jax dispatch is asynchronous, so
            # this measures host time until the poll's matrix converts
            # (>= `every` chunks behind), NOT this chunk's compute —
            # per-chunk events carry it as a dispatch-interval
            # distribution; throughput comes from the group wall clock
            # below, which the end-of-group sync closes honestly
            ms = (time.perf_counter() - t_wall) * 1e3
            t_vec += self.chunk * dt_vec
            for s in slots:
                if s.active:
                    s.steps_done += self.chunk
            totals["member_steps"] += self.chunk * active
            totals["chunks"] += 1
            totals["occupancy_sum"] += active / self.size
            _metrics.counter("ensemble_member_steps").inc(
                self.chunk * active)
            _events.emit("ensemble_chunk", step=chunk_index,
                         label=self.label, ms=ms, active=active,
                         size=self.size,
                         member_steps=self.chunk * active)
            batch = self._handle_evictions(
                new_ev, slots, batch, ens, params, t_vec, dt_vec,
                monitor, chunk_index, evictions)
            batch = self._retire_and_refill(
                slots, jobs, batch, ens, params, t_vec, dt_vec, monitor,
                chunk_index, results, on_finish, evictions)
            if (self.preempt is not None
                    and any(s.active for s in slots)
                    and self.preempt(chunk_index)):
                self._drain(slots, jobs, batch, ens, params, t_vec,
                            monitor, chunk_index, evictions,
                            preempted if preempted is not None else [],
                            pending if pending is not None else [])
                drained = True
                break
        else:
            drained = False
        # end of group: convert matrices still inside the maturity lag;
        # late trips are honest evictions (recorded, slot already done)
        late = monitor.flush()
        batch = self._handle_evictions(
            late, slots, batch, ens, params, t_vec, dt_vec, monitor,
            chunk_index, evictions)
        # block on the final state before closing the clock: the last
        # chunk's compute may still be in flight (the driver provably
        # runs ahead of the async health path), and member-steps/s
        # must not exclude it — this is the group's one deliberate
        # full sync, at its natural end
        jax.block_until_ready(batch)
        totals["wall_s"] += time.perf_counter() - group_t0
        return drained

    def _drain(self, slots, jobs, batch, ens, params, t_vec, monitor,
               chunk_index, evictions, preempted, pending):
        """Preemption drain at a chunk boundary: convert the health
        matrices still inside the maturity lag (a trip found here is an
        honest eviction — a diverged trajectory must not be requeued as
        good work), sync the batch, and capture every still-active
        member as a requeue record. No work is lost: the captured state
        is exactly the trajectory at ``steps_done`` steps, and
        :meth:`requeue` re-enters it bit-consistently."""
        late = monitor.flush()
        for ev in late:
            evictions.append(ev)
            s = slots[ev.member]
            if s.active:
                # evicted at the drain: its trajectory is poisoned —
                # record the eviction (done by the monitor) and do NOT
                # requeue it; the drain never resamples (the batch is
                # stopping, a fresh draw would be immediately drained
                # at step 0)
                s.active = False
                monitor.mask_member(s.index)
        jax.block_until_ready(batch)
        for s in slots:
            if not s.active:
                continue
            rec = {
                "scenario": s.job.scenario,
                "seed": s.job.seed,
                "state": ens.take_member(batch, s.index),
                "step": s.steps_done,
                "t": float(t_vec[s.index]),
                "params": {n: float(params[n][s.index])
                           for n in params},
            }
            preempted.append(rec)
            rec["trace"] = s.job.trace
            with _events.tracing(trace=s.job.trace):
                _events.emit("member_preempted", label=self.label,
                             member=s.index,
                             scenario=s.job.scenario.name,
                             seed=s.job.seed, step=s.steps_done)
            s.active = False
            monitor.mask_member(s.index)
        pending += [self._pending_record(j) for j in jobs]
        del jobs[:]

    @staticmethod
    def _pending_record(job):
        """An unstarted job as a resubmittable record. A job that was
        itself REQUEUED (it carries a restored trajectory) keeps its
        resume payload — dropping it would silently restart the member
        from step 0, losing the work the earlier drain preserved;
        resubmit such a record with :meth:`requeue`, plain ones with
        :meth:`submit`. The job's trace id rides along (pass it back
        as ``trace=``) so an unstarted traced job keeps one trace
        across the drain, like the started members do."""
        rec = {"scenario": job.scenario, "seed": job.seed,
               "trace": job.trace}
        if job.resume is not None:
            rec.update(state=job.resume["state"],
                       step=job.resume["step"], t=job.resume["t"],
                       params=dict(job.resume["params"]))
        return rec

    def _arm(self, slot, job, draw, params, monitor):
        sc = job.scenario
        slot.job = job
        slot.steps_done = 0
        slot.t = sc.t0
        slot.dt = sc.member_dt(job.seed)
        if job.resume is not None:
            # a requeued member picks its trajectory back up where the
            # drain left it: step budget and clock both resume
            slot.steps_done = int(job.resume["step"])
            slot.t = (float(job.resume["t"])
                      if job.resume["t"] is not None
                      else sc.t0 + slot.steps_done * slot.dt)
        slot.active = True
        for n in params:
            params[n][slot.index] = float(draw.get(n, 0.0))
        monitor.set_member(slot.index,
                           params={**draw, "seed": job.seed,
                                   "dt": slot.dt},
                           scenario=sc.name)
        with _events.tracing(trace=job.trace):
            _events.emit("member_started", label=self.label,
                         member=slot.index, scenario=sc.name,
                         seed=job.seed,
                         resumed_from=(slot.steps_done
                                       if job.resume is not None
                                       else None))

    def _handle_evictions(self, new_ev, slots, batch, ens, params,
                          t_vec, dt_vec, monitor, chunk_index,
                          evictions):
        """Resample (or mask) every slot the monitor just evicted. The
        slot write is one cached compiled program regardless of which
        member tripped — no recompile, the rest of the batch
        untouched."""
        for ev in new_ev:
            evictions.append(ev)
            slot = slots[ev.member]
            if not slot.active:
                # tripped after retiring/masking (a matured matrix from
                # its final chunks) — recorded, nothing to refill
                continue
            job = slot.job
            if not self.resample:
                slot.active = False
                monitor.mask_member(slot.index)
                continue
            seed = self._fresh_seed(job.scenario)
            state, draw = job.scenario.sample(seed)
            batch = ens.write_member(batch, slot.index, state)
            self._arm(slot, _Job(job.scenario, seed), draw, params,
                      monitor)
            t_vec[slot.index] = slot.t
            dt_vec[slot.index] = slot.dt
            monitor.reset_member(slot.index, at_step=chunk_index,
                                 params={**draw, "seed": seed,
                                         "dt": slot.dt},
                                 scenario=job.scenario.name)
        return batch

    def _retire_and_refill(self, slots, jobs, batch, ens, params, t_vec,
                           dt_vec, monitor, chunk_index, results,
                           on_finish, evictions):
        for slot in slots:
            if not slot.active or slot.steps_done < slot.job.scenario.nsteps:
                continue
            # retire-time health check: the member's final chunks may
            # still be inside the maturity lag — a member that diverged
            # there must be evicted, not reported finished (retire is
            # the driver's one deliberate sync point, so forcing those
            # matrices to host here is within contract)
            ev = monitor.check_member_now(slot.index, chunk_index)
            if ev is not None:
                batch = self._handle_evictions(
                    [ev], slots, batch, ens, params, t_vec, dt_vec,
                    monitor, chunk_index, evictions)
                continue
            job = slot.job
            record = {
                "scenario": job.scenario.name,
                "seed": job.seed,
                "member": slot.index,
                "steps": slot.steps_done,
                "t_final": float(t_vec[slot.index]),
                "params": {n: float(params[n][slot.index])
                           for n in params},
            }
            results.append(record)
            _metrics.counter("ensemble_members_completed").inc()
            with _events.tracing(trace=job.trace):
                _events.emit("member_finished", label=self.label,
                             **record)
            if on_finish is not None:
                on_finish(record, ens.take_member(batch, slot.index))
            if jobs:
                nxt = jobs.pop(0)
                state, draw = self._sample(nxt)
                batch = ens.write_member(batch, slot.index, state)
                self._arm(slot, nxt, draw, params, monitor)
                t_vec[slot.index] = slot.t
                dt_vec[slot.index] = slot.dt
                monitor.reset_member(slot.index, at_step=chunk_index,
                                     params={**draw, "seed": nxt.seed,
                                             "dt": slot.dt},
                                     scenario=nxt.scenario.name)
            else:
                slot.active = False
                monitor.mask_member(slot.index)
        return batch
