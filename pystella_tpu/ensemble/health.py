"""Per-member health with evict-and-resample.

The single-run :class:`~pystella_tpu.obs.sentinel.SentinelMonitor`
treats ANY unhealthy vector as fatal: it writes forensics and raises
``SimulationDiverged``, killing the run. In an ensemble that policy is
wrong — one bad parameter draw must not kill the other ``size - 1``
members (nor force a recompile of the batch). The
:class:`EnsembleMonitor` is the member-axis consumer:

- the batched step produces a ``(members, size)`` health MATRIX
  (:meth:`~pystella_tpu.obs.sentinel.Sentinel.compute_members`) per
  chunk; the monitor polls it with the same maturity lag as the
  single-run monitor (no host sync on the step path);
- an unhealthy ROW marks that member **evicted**: a ``member_evicted``
  run event names the member, its parameter draw, and the offending
  fields; a per-member forensic bundle
  (:func:`~pystella_tpu.obs.forensics.write_bundle` with ``member=``)
  records its own blowup curve — not the whole batch's; the member is
  then ignored until the driver resamples the slot and calls
  :meth:`EnsembleMonitor.reset_member`;
- the batch itself never raises — UNLESS the eviction budget
  (``PYSTELLA_ENSEMBLE_MAX_EVICTIONS``) is exhausted, at which point
  the configuration itself is declared broken the single-run way
  (``diverged`` event + :class:`~pystella_tpu.obs.sentinel.
  SimulationDiverged`).

The driver side (slot resampling, occupancy/throughput accounting)
lives in :mod:`pystella_tpu.ensemble.driver`.
"""

from __future__ import annotations

import collections

import numpy as np

from pystella_tpu import config as _config
from pystella_tpu.obs import events as _events
from pystella_tpu.obs import metrics as _metrics
from pystella_tpu.obs.sentinel import SimulationDiverged

__all__ = ["EnsembleMonitor", "Eviction"]


class Eviction:
    """One member eviction: ``member`` (slot index), ``step`` (the
    offending step), ``fields`` (bad field/invariant names),
    ``problems`` (human reasons), ``params`` (the member's parameter
    draw at trip time), ``scenario`` (its scenario name, when the
    driver registered one), ``bundle`` (forensic-bundle path or
    ``None``)."""

    __slots__ = ("member", "step", "fields", "problems", "params",
                 "scenario", "bundle")

    def __init__(self, member, step, fields, problems, params=None,
                 scenario=None, bundle=None):
        self.member = int(member)
        self.step = int(step)
        self.fields = tuple(fields)
        self.problems = tuple(problems)
        self.params = dict(params or {})
        self.scenario = scenario
        self.bundle = bundle

    def __repr__(self):
        return (f"Eviction(member={self.member}, step={self.step}, "
                f"fields={list(self.fields)})")


class EnsembleMonitor:
    """Asynchronous consumer of per-chunk ensemble health matrices.

    :arg sentinel: the (single-member) :class:`~pystella_tpu.obs.
        sentinel.Sentinel` whose :meth:`compute_members` produced the
        matrices.
    :arg size: member count (matrix row count).
    :arg every: minimum step lag before a matrix is host-converted
        (same pipelining contract as ``SentinelMonitor``).
    :arg history: ring-buffer capacity of decoded matrices (per-member
        forensic history is sliced from it).
    :arg max_abs / invariant_bounds: the health checks, per member.
    :arg forensics: optional :class:`~pystella_tpu.obs.forensics.
        ForensicSink`; each eviction writes a member-scoped bundle.
    :arg max_evictions: eviction budget (default: the registered
        ``PYSTELLA_ENSEMBLE_MAX_EVICTIONS``); exceeding it raises
        :class:`~pystella_tpu.obs.sentinel.SimulationDiverged`.
    :arg emit_steps: emit one ``ensemble_health`` event per checked
        matrix (summary counts only — per-member payloads would bloat
        the log at production sizes).
    """

    def __init__(self, sentinel, size, every=1, history=64,
                 max_abs=None, invariant_bounds=None, emit_steps=False,
                 label="", forensics=None, max_evictions=None):
        self.sentinel = sentinel
        self.size = int(size)
        self.every = int(every)
        self.max_abs = max_abs
        self.invariant_bounds = dict(invariant_bounds or {})
        self.emit_steps = bool(emit_steps)
        self.label = label
        self.forensics = forensics
        if max_evictions is None:
            max_evictions = _config.get_int(
                "PYSTELLA_ENSEMBLE_MAX_EVICTIONS")
        self.max_evictions = int(max_evictions)
        self._pending = collections.deque()   # (step, device matrix)
        self.history = collections.deque(maxlen=int(history))
        self.newest_step = None
        self.checked_through = None
        #: every Eviction so far, oldest first
        self.evictions = []
        self._member_params = {}   # member -> params dict
        self._member_scenario = {}  # member -> scenario name
        # members currently excluded from checks: evicted-awaiting-
        # resample and permanently masked (idle slots); plus the step
        # up to which a freshly resampled slot's STALE pending matrices
        # must be skipped
        self._suspended = set()
        self._masked = set()
        self._ignore_until = {}

    # -- driver bookkeeping -------------------------------------------------

    def set_member(self, member, params=None, scenario=None):
        """Record slot ``member``'s parameter draw / scenario name
        (what the eviction record and forensic bundle will name)."""
        member = int(member)
        if params is not None:
            self._member_params[member] = dict(params)
        if scenario is not None:
            self._member_scenario[member] = str(scenario)

    def mask_member(self, member):
        """Exclude slot ``member`` from all further checks (an idle
        slot after the scenario queue drained — its state keeps
        stepping as ballast and must not produce evictions)."""
        self._masked.add(int(member))

    def reset_member(self, member, at_step, params=None, scenario=None):
        """Re-arm checks for slot ``member`` after a resample/refill:
        matrices for steps ``<= at_step`` (produced by the OLD,
        possibly diverged occupant) are skipped for this member."""
        member = int(member)
        self._suspended.discard(member)
        self._masked.discard(member)
        self._ignore_until[member] = int(at_step)
        self.set_member(member, params=params, scenario=scenario)

    # -- queue --------------------------------------------------------------

    @property
    def pending_steps(self):
        return [s for s, _ in self._pending]

    def push(self, step, matrix):
        """Enqueue a ``(members, size)`` health matrix the in-graph
        batched step already produced (NO host sync)."""
        step = int(step)
        self._pending.append((step, matrix))
        self.newest_step = step

    def poll(self):
        """Check every pending matrix at least ``every`` steps behind
        the newest push. Returns the list of NEW :class:`Eviction`\\ s
        found (empty when all members are healthy); raises
        :class:`~pystella_tpu.obs.sentinel.SimulationDiverged` only
        when the eviction budget is exhausted."""
        new = []
        while (self._pending and self.newest_step is not None
                and self._pending[0][0] <= self.newest_step
                - self.every):
            new += self._check_one(*self._pending.popleft())
        return new

    def flush(self):
        """Drain the queue unconditionally (end of run); returns the
        remaining new evictions."""
        new = []
        while self._pending:
            new += self._check_one(*self._pending.popleft())
        return new

    def check_member_now(self, member, through_step):
        """Synchronously check ``member``'s rows of the still-pending
        matrices for steps ``<= through_step`` — the RETIRE-time
        check: a member about to be reported finished must not have
        diverged inside its final chunks, whose matrices are still
        inside the maturity lag (retire is the driver's one deliberate
        sync point, so forcing these matrices to host here is within
        contract). Matrices stay queued for the normal asynchronous
        path (a healthy row re-checked later is still healthy; a
        tripped member is suspended, so it cannot evict twice).
        Returns the :class:`Eviction`, or ``None`` when the member's
        tail is healthy."""
        member = int(member)
        if member in self._masked or member in self._suspended:
            return None
        tail = []
        for step, matrix in self._pending:
            if step > int(through_step):
                break
            if step <= self._ignore_until.get(member, -1):
                continue
            with _metrics.timer("ensemble_sentinel"):
                # decode ONE row — a drain wave retires every slot at
                # once, and decoding the whole matrix per retiring
                # member would be O(size^2) host work
                dec = self.sentinel.decode(np.asarray(matrix)[member])
                bad, why = self.sentinel.problems(
                    dec, max_abs=self.max_abs,
                    invariant_bounds=self.invariant_bounds)
            tail.append({"step": step, "members": {member: dec}})
            if bad:
                # commit the member's final-chunk rows to the history
                # ring before the evict, so the forensic bundle carries
                # exactly the series that diverged — healthy retires
                # commit nothing (size single-member appends per drain
                # wave would flush the ring other members' bundles
                # need). No double entry later: after the trip the
                # member is suspended, so _check_one skips it when
                # these matrices mature.
                self.history.extend(tail)
                ev = self._evict(step, member, bad, why)
                self._enforce_budget(step)
                return ev
        return None

    # -- the check ----------------------------------------------------------

    def _member_history(self, member):
        """This member's own health series from the ring buffer, in
        single-run record shape (so the forensic bundle's per-field
        blowup pivot applies unchanged)."""
        out = []
        for rec in self.history:
            row = rec["members"].get(member)
            if row is not None:
                out.append({"step": rec["step"], **row})
        return out

    def _check_one(self, step, matrix):
        # own metric names: the single-run `sentinel` timer and
        # `health_checks` counter feed the ledger's numerics section
        # (sentinel overhead % vs step time), which must keep
        # describing the single-run monitor when both run in one
        # process (bench.py --smoke does)
        with _metrics.timer("ensemble_sentinel"):
            decoded = self.sentinel.decode_members(matrix)
        self.checked_through = (step if self.checked_through is None
                                else max(self.checked_through, step))
        _metrics.counter("ensemble_health_checks").inc()
        checked = {}
        tripped = []
        for member, dec in enumerate(decoded):
            if member in self._masked or member in self._suspended:
                continue
            if step <= self._ignore_until.get(member, -1):
                continue
            checked[member] = dec
            with _metrics.timer("ensemble_sentinel"):
                bad, why = self.sentinel.problems(
                    dec, max_abs=self.max_abs,
                    invariant_bounds=self.invariant_bounds)
            if bad:
                tripped.append((member, bad, why))
        self.history.append({"step": step, "members": checked})
        if self.emit_steps:
            _events.emit("ensemble_health", step=step, label=self.label,
                         members=self.size, checked=len(checked),
                         tripped=[m for m, _, _ in tripped])
        new = []
        for member, bad, why in tripped:
            new.append(self._evict(step, member, bad, why))
        self._enforce_budget(step)
        return new

    def _enforce_budget(self, step):
        """Escalate to the single-run ``diverged`` path once the
        eviction budget is exhausted — a configuration producing that
        many bad draws is itself broken."""
        if len(self.evictions) > self.max_evictions:
            _events.emit(
                "diverged", step=step, label=self.label,
                fields=sorted({f for e in self.evictions
                               for f in e.fields}),
                problems=[f"eviction budget exhausted: "
                          f"{len(self.evictions)} member evictions "
                          f"(limit {self.max_evictions})"])
            raise SimulationDiverged(
                step, [f"member{e.member}" for e in self.evictions],
                [f"ensemble eviction budget exhausted "
                 f"({len(self.evictions)} > {self.max_evictions})"])

    def _evict(self, step, member, bad, why):
        """Record one member eviction: event + member-scoped forensic
        bundle; the member is suspended until the driver resamples the
        slot. Never raises (the batch survives by contract)."""
        self._suspended.add(member)
        params = self._member_params.get(member)
        scenario = self._member_scenario.get(member)
        _metrics.counter("ensemble_evictions").inc()
        _events.emit("member_evicted", step=step, label=self.label,
                     member=member, scenario=scenario, fields=bad,
                     problems=why, params=params)
        bundle = None
        if self.forensics is not None:
            offending = next(
                (n for n in bad if n in self.sentinel.invariants), None)
            bundle = self.forensics.write(
                step=step, reason="; ".join(why), bad_fields=bad,
                offending_invariant=offending,
                history=self._member_history(member),
                member=member,
                member_params={"scenario": scenario,
                               **(params or {})})
        ev = Eviction(member, step, bad, why, params=params,
                      scenario=scenario, bundle=bundle)
        self.evictions.append(ev)
        return ev
