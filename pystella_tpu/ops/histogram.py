"""Weighted histograms over the lattice.

TPU-native counterpart of /root/reference/pystella/histogram.py:33-350. The
reference uses a two-level atomic scatter kernel (workgroup-local atomics,
barrier, global atomic flush) followed by an MPI allreduce of the host copy.
XLA has no atomics; instead each device computes a local ``jnp.bincount``
over its shard inside ``shard_map`` and the per-device histograms are summed
with ``lax.psum`` over the mesh — deterministic by construction (no
write-race silencing needed, cf. histogram.py:111-112).
"""

from __future__ import annotations

from itertools import product

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

import weakref

from pystella_tpu import field as _field
from pystella_tpu.ops.reduction import Reduction

__all__ = ["Histogrammer", "FieldHistogrammer", "weighted_bincount"]

# cache keyed weakly on the decomp so discarded decompositions (and their
# compiled executables) remain collectable
_bincount_cache = weakref.WeakKeyDictionary()


def _bincount_fn(decomp, outer_shape, num_bins):
    """Build (and cache) the jitted distributed weighted-bincount for a
    given decomposition / outer shape / bin count."""
    per_decomp = _bincount_cache.setdefault(decomp, {})
    cached = per_decomp.get((outer_shape, num_bins))
    if cached is not None:
        return cached
    from jax.sharding import PartitionSpec as P
    nouter = int(np.prod(outer_shape, dtype=np.int64)) if outer_shape else 1
    spec = decomp.spec(len(outer_shape))
    out_spec = P(*(None,) * (len(outer_shape) + 1))

    def local(b, w):
        if nouter > 1:
            # offset bins per outer slice: one bincount covers all slices
            offsets = jnp.arange(nouter, dtype=jnp.int32).reshape(
                outer_shape + (1, 1, 1))
            b = b + offsets * num_bins
        h = jnp.bincount(b.reshape(-1), weights=w.reshape(-1),
                         length=num_bins * nouter)
        return decomp.psum(h).reshape(outer_shape + (num_bins,))

    fn = jax.jit(decomp.shard_map(local, (spec, spec), out_spec))
    per_decomp[(outer_shape, num_bins)] = fn
    return fn


def weighted_bincount(decomp, bins, weights, num_bins):
    """Distributed weighted histogram: per-device ``jnp.bincount`` over the
    local shard + ``psum`` over the mesh. ``bins`` (int32) and ``weights``
    share shape ``outer + lattice``; returns ``outer + (num_bins,)``,
    replicated. The shared primitive behind :class:`Histogrammer` and
    :class:`~pystella_tpu.PowerSpectra`."""
    outer_shape = tuple(bins.shape[:-3])
    return _bincount_fn(decomp, outer_shape, int(num_bins))(bins, weights)


class Histogrammer:
    """Computes weighted histograms of expressions.

    :arg decomp: a :class:`~pystella_tpu.DomainDecomposition`.
    :arg histograms: dict mapping names to ``(bin_expr, weight_expr)``; the
        bin index is ``floor(bin_expr)`` clipped to ``[0, num_bins)``
        (reference histogram.py:62-70).
    :arg num_bins: number of bins.
    :arg dtype: dtype of the output histogram.
    """

    def __init__(self, decomp, histograms, num_bins, dtype=np.float64,
                 **kwargs):
        self.decomp = decomp
        self.histograms = dict(histograms)
        self.num_bins = int(num_bins)
        self.dtype = dtype

        num_bins_ = self.num_bins

        def prepare(env):
            out = {}
            for name, (bin_expr, weight_expr) in self.histograms.items():
                b = _field.evaluate(bin_expr, env)
                w = _field.evaluate(weight_expr, env)
                # accumulate in the requested dtype (canonicalized: f64 only
                # when x64 is enabled) so large counts don't saturate in f32
                acc_dtype = jnp.zeros((), self.dtype).dtype
                b = jnp.clip(jnp.floor(b), 0, num_bins_ - 1).astype(jnp.int32)
                w = jnp.broadcast_to(w, b.shape).astype(acc_dtype)
                out[name] = (b, w)
            return out

        self._prepare = jax.jit(prepare)

    def __call__(self, allocator=None, **env):
        prepared = self._prepare(env)
        return {name: np.asarray(
                    weighted_bincount(self.decomp, b, w, self.num_bins)
                ).astype(self.dtype)
                for name, (b, w) in prepared.items()}


class FieldHistogrammer(Histogrammer):
    """Linear- and log-binned histograms of a field, with automatic bin
    bounds (reference histogram.py:210-350).

    Returns ``{"linear", "linear_bins", "log", "log_bins"}``, each with shape
    ``f.shape[:-3] + (num_bins[+1],)``.
    """

    def __init__(self, decomp, num_bins, dtype=np.float64, **kwargs):
        f = _field.Field("f")
        max_f, min_f = _field.Var("max_f"), _field.Var("min_f")
        max_log_f = _field.Var("max_log_f")
        min_log_f = _field.Var("min_log_f")

        linear_bin = (f - min_f) / (max_f - min_f)
        log_bin = ((_field.log(_field.fabs(f)) - min_log_f)
                   / (max_log_f - min_log_f))
        histograms = {
            "linear": (linear_bin * num_bins, 1),
            "log": (log_bin * num_bins, 1),
        }
        super().__init__(decomp, histograms, num_bins, dtype, **kwargs)

        self.get_min_max = Reduction(decomp, {
            "max_f": [(f, "max")],
            "min_f": [(f, "min")],
            "max_log_f": [(_field.log(_field.fabs(f)), "max")],
            "min_log_f": [(_field.log(_field.fabs(f)), "min")],
        })

    def __call__(self, f, allocator=None, **kwargs):
        outer_shape = f.shape[:-3]
        slices = list(product(*[range(n) for n in outer_shape]))

        min_max_keys = set(self.get_min_max.reducers.keys())
        bounds_passed = min_max_keys.issubset(set(kwargs.keys()))

        out = {}
        for key in ("linear", "log"):
            out[key] = np.zeros(outer_shape + (self.num_bins,), self.dtype)
            out[key + "_bins"] = np.zeros(outer_shape + (self.num_bins + 1,),
                                          self.dtype)

        for s in slices:
            if not bounds_passed:
                bounds = self.get_min_max(f=f[s])
                bounds = {key: np.asarray(val) for key, val in bounds.items()}
            else:
                bounds = {key: kwargs[key][s] for key in min_max_keys}

            hists = super().__call__(f=f[s], **bounds)
            for key, val in hists.items():
                out[key][s] = val

            out["linear_bins"][s] = np.linspace(
                bounds["min_f"], bounds["max_f"], self.num_bins + 1)
            out["log_bins"][s] = np.exp(np.linspace(
                bounds["min_log_f"], bounds["max_log_f"], self.num_bins + 1))

        return out
