"""Weighted histograms over the lattice.

TPU-native counterpart of /root/reference/pystella/histogram.py:33-350. The
reference uses a two-level atomic scatter kernel (workgroup-local atomics,
barrier, global atomic flush) followed by an MPI allreduce of the host copy.
XLA has no atomics; instead each device computes local ``jnp.bincount``s
over its shard inside ``shard_map`` — deterministic by construction (no
write-race silencing needed, cf. histogram.py:111-112).

Accumulation precision (production lattices exceed f32's 2**24 integer
range — a 512**3 grid has 1.3e8 sites, so a single bin can overflow exact
f32 counting even though TPUs have no native f64): each device's flat shard
is split into chunks of at most 2**22 elements, each chunk is bincounted
separately (int32 for pure counts, f32 for weighted sums — every per-chunk
partial stays exactly representable), the per-device per-chunk partials are
returned without any device-side reduction, and the final sum over chunks
and devices happens on the host in int64/float64. Counts are therefore
exact at any scale regardless of ``jax_enable_x64`` (matching the
reference's f64 device accumulation, histogram.py:199-206); weighted sums
carry at most one f32 rounding per 2**22-element chunk.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

import weakref

from pystella_tpu import field as _field
from pystella_tpu.ops.reduction import Reduction

__all__ = ["Histogrammer", "FieldHistogrammer", "weighted_bincount",
           "bincount_core", "fetch_partials"]

# cache keyed weakly on the decomp so discarded decompositions (and their
# compiled executables) remain collectable
_bincount_cache = weakref.WeakKeyDictionary()


#: largest per-chunk element count; keeps every per-chunk partial (int32
#: count or f32 weighted sum of same-order values) exactly representable
_CHUNK = 1 << 22


def _flat_names(lattice_names):
    """Per-axis layout entries flattened to the plain mesh-axis names
    actually sharded over: an entry may be ``None``, one name, or a
    TUPLE of names (the pencil-FFT k layout shards its y axis over the
    combined ``(x, z, y)`` mesh axes)."""
    out = []
    for n in lattice_names:
        if n is None:
            continue
        if isinstance(n, (tuple, list)):
            out.extend(m for m in n if m is not None)
        else:
            out.append(n)
    return tuple(out)


def bincount_core(decomp, outer_shape, num_bins, weighted,
                  lattice_names=None):
    """The UNJITTED shard_map-wrapped local bincount (cached): callers
    that fuse binning into a larger jitted program (the pencil-tier
    spectra path) compose this; :func:`_bincount_fn` wraps it in its
    own jit for standalone dispatch. Returns per-device, per-chunk
    partial histograms stacked along axis 0 (the host finalizes in
    wide precision). ``lattice_names`` are the per-lattice-axis mesh
    axis names of the input layout (default: the decomposition's
    position-space layout; k-space callers pass their own — entries
    may be combined-axis tuples)."""
    from jax.sharding import PartitionSpec as P
    if lattice_names is None:
        lattice_names = tuple(decomp.spec(0))
    lattice_names = tuple(lattice_names)
    per_decomp = _bincount_cache.setdefault(decomp, {})
    key = ("core", outer_shape, num_bins, weighted, lattice_names)
    cached = per_decomp.get(key)
    if cached is not None:
        return cached
    nouter = int(np.prod(outer_shape, dtype=np.int64)) if outer_shape else 1
    length = num_bins * nouter
    spec = P(*((None,) * len(outer_shape) + lattice_names))
    # partials stay sharded along the stacked chunk axis — no device-side
    # reduction, so no precision-losing f32/int32 cross-device sums;
    # stacking covers only the axes the input is actually sharded over
    # (mesh axes the input is replicated across would double count)
    stack = _flat_names(lattice_names)
    out_spec = P(stack or None, None)

    def flat_chunked_bins(b):
        if nouter > 1:
            # offset bins per outer slice: one bincount covers all slices
            offsets = jnp.arange(nouter, dtype=jnp.int32).reshape(
                outer_shape + (1, 1, 1))
            b = b + offsets * num_bins
        flat = b.reshape(-1)
        n = flat.size
        nchunks = -(-n // _CHUNK)
        chunk = -(-n // nchunks)
        pad = nchunks * chunk - n
        if pad:
            # padded elements go to a sentinel bin that is dropped below
            flat = jnp.concatenate(
                [flat, jnp.full((pad,), length, flat.dtype)])
        return flat.reshape(nchunks, chunk), nchunks, chunk, pad

    if weighted:
        def local(b, w):
            bb, nchunks, chunk, pad = flat_chunked_bins(b)
            flat_w = w.reshape(-1)
            if pad:
                flat_w = jnp.concatenate(
                    [flat_w, jnp.zeros((pad,), flat_w.dtype)])
            ww = flat_w.reshape(nchunks, chunk)
            return jax.vmap(
                lambda bi, wi: jnp.bincount(
                    bi, weights=wi, length=length + 1)[:length])(bb, ww)
        in_specs = (spec, spec)
    else:
        def local(b):
            bb, *_ = flat_chunked_bins(b)
            return jax.vmap(
                lambda bi: jnp.bincount(bi, length=length + 1)[:length])(bb)
        in_specs = (spec,)

    fn = decomp.shard_map(local, in_specs, out_spec)
    per_decomp[key] = fn
    return fn


def _bincount_fn(decomp, outer_shape, num_bins, weighted,
                 lattice_names=None):
    """Jitted wrapper of :func:`bincount_core` (cached)."""
    per_decomp = _bincount_cache.setdefault(decomp, {})
    key = ("jit", outer_shape, num_bins, weighted,
           None if lattice_names is None else tuple(lattice_names))
    cached = per_decomp.get(key)
    if cached is None:
        cached = jax.jit(bincount_core(decomp, outer_shape, num_bins,
                                       weighted, lattice_names))
        per_decomp[key] = cached
    return cached


def fetch_partials(partials):
    """Per-device bincount partials as a host array: a plain device_get
    on one controller; under multi-controller ``jax.distributed`` the
    device axis spans non-addressable shards, so every process
    allgathers the global value instead (the multihost analog of the
    reference's host-side MPI allreduce, histogram.py:199-206)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(partials, tiled=True)
    return np.asarray(partials)


def weighted_bincount(decomp, bins, weights, num_bins, lattice_names=None):
    """Distributed histogram: chunked per-device ``jnp.bincount``s with
    host-side wide-precision finalization (see module docstring). ``bins``
    (int32) has shape ``outer + lattice``; ``weights`` shares it, or is
    ``None`` for an exact integer count histogram. ``lattice_names``
    optionally overrides the assumed input layout (see
    :func:`_bincount_fn`). Returns a **host** ``np.ndarray`` of shape
    ``outer + (num_bins,)`` (float64, or int64 for counts). The shared
    primitive behind :class:`Histogrammer` and
    :class:`~pystella_tpu.PowerSpectra`."""
    outer_shape = tuple(bins.shape[:-3])
    num_bins = int(num_bins)
    if weights is None:
        partials = _bincount_fn(decomp, outer_shape, num_bins, False,
                                lattice_names)(bins)
        h = fetch_partials(partials).astype(np.int64).sum(axis=0)
    else:
        partials = _bincount_fn(decomp, outer_shape, num_bins, True,
                                lattice_names)(bins, weights)
        h = fetch_partials(partials).astype(np.float64).sum(axis=0)
    return h.reshape(outer_shape + (num_bins,))


class Histogrammer:
    """Computes weighted histograms of expressions.

    :arg decomp: a :class:`~pystella_tpu.DomainDecomposition`.
    :arg histograms: dict mapping names to ``(bin_expr, weight_expr)``; the
        bin index is ``floor(bin_expr)`` clipped to ``[0, num_bins)``
        (reference histogram.py:62-70).
    :arg num_bins: number of bins.
    :arg dtype: dtype of the output histogram.
    """

    def __init__(self, decomp, histograms, num_bins, dtype=np.float64,
                 **kwargs):
        self.decomp = decomp
        self.histograms = dict(histograms)
        self.num_bins = int(num_bins)
        self.dtype = dtype

        num_bins_ = self.num_bins

        def is_unit(expr):
            if isinstance(expr, _field.Constant):
                expr = expr.value
            return isinstance(expr, (int, float)) and expr == 1

        #: histograms with a constant unit weight take the exact integer
        #: count path (no f32 rounding at any lattice size)
        self._count_names = {name for name, (_, w)
                             in self.histograms.items() if is_unit(w)}

        def prepare(env):
            out = {}
            for name, (bin_expr, weight_expr) in self.histograms.items():
                b = _field.evaluate(bin_expr, env)
                b = jnp.clip(jnp.floor(b), 0, num_bins_ - 1).astype(jnp.int32)
                if name in self._count_names:
                    out[name] = (b, None)
                    continue
                w = _field.evaluate(weight_expr, env)
                acc = jnp.zeros((), self.dtype).dtype  # canonicalized
                out[name] = (b, jnp.broadcast_to(w, b.shape).astype(acc))
            return out

        self._prepare = jax.jit(prepare)

    def __call__(self, allocator=None, **env):
        prepared = self._prepare(env)
        return {name: weighted_bincount(
                    self.decomp, b, w, self.num_bins).astype(self.dtype)
                for name, (b, w) in prepared.items()}


class FieldHistogrammer(Histogrammer):
    """Linear- and log-binned histograms of a field, with automatic bin
    bounds (reference histogram.py:210-350).

    Returns ``{"linear", "linear_bins", "log", "log_bins"}``, each with shape
    ``f.shape[:-3] + (num_bins[+1],)``.
    """

    def __init__(self, decomp, num_bins, dtype=np.float64, **kwargs):
        f = _field.Field("f")
        max_f, min_f = _field.Var("max_f"), _field.Var("min_f")
        max_log_f = _field.Var("max_log_f")
        min_log_f = _field.Var("min_log_f")

        linear_bin = (f - min_f) / (max_f - min_f)
        log_bin = ((_field.log(_field.fabs(f)) - min_log_f)
                   / (max_log_f - min_log_f))
        histograms = {
            "linear": (linear_bin * num_bins, 1),
            "log": (log_bin * num_bins, 1),
        }
        super().__init__(decomp, histograms, num_bins, dtype, **kwargs)
        self._jit_bounds = {}  # outer ndim -> jitted bounds reductions

        self.get_min_max = Reduction(decomp, {
            "max_f": [(f, "max")],
            "min_f": [(f, "min")],
            "max_log_f": [(_field.log(_field.fabs(f)), "max")],
            "min_log_f": [(_field.log(_field.fabs(f)), "min")],
        })

    def _auto_bounds(self, f):
        """Per-outer-slice min/max of ``f`` and ``log|f|`` as ONE jitted
        dispatch + one host transfer (XLA fuses the log/abs into the
        reductions — no materialized full-field temporary)."""
        fn = self._jit_bounds.get(f.ndim)
        if fn is None:
            def impl(fa):
                lat = (-3, -2, -1)
                log_absf = jnp.log(jnp.abs(fa))
                return (jnp.max(fa, axis=lat), jnp.min(fa, axis=lat),
                        jnp.max(log_absf, axis=lat),
                        jnp.min(log_absf, axis=lat))
            fn = jax.jit(impl)
            self._jit_bounds[f.ndim] = fn
        mx, mn, mxl, mnl = jax.device_get(fn(f))
        return {"max_f": mx, "min_f": mn,
                "max_log_f": mxl, "min_log_f": mnl}

    @staticmethod
    def _widen(lo, hi):
        """``hi`` strictly above ``lo`` by at least a representable step
        at ``lo``'s scale (a +1.0 widening rounds away for |lo| above
        the dtype's integer range)."""
        bump = np.maximum(np.asarray(1.0, lo.dtype),
                          4 * np.spacing(np.abs(lo)))
        return np.where(lo == hi, lo + bump, hi)

    def _sanitize_bounds(self, bounds, dtype=None):
        """Keep bin bounds finite and non-degenerate (elementwise over
        any outer shape), IN THE DTYPE THE BIN EXPRESSIONS RUN IN — a
        field with zeros gives ``log|f| = -inf`` and an
        identically-zero field degenerate bounds, which would turn the
        bin expressions into nan; sanitizing before the cast could be
        undone by rounding (bounds closer than one target-dtype ulp)."""
        dt = np.dtype(dtype if dtype is not None else self.dtype)
        out = {k: np.asarray(v, dt) for k, v in bounds.items()}
        tiny_log = dt.type(np.log(np.finfo(dt).tiny))
        lo, hi = out["min_log_f"], out["max_log_f"]
        hi = np.where(np.isfinite(hi), hi, tiny_log)
        lo = np.where(np.isfinite(lo), lo, np.minimum(tiny_log, hi))
        out["min_log_f"], out["max_log_f"] = lo, self._widen(lo, hi)
        out["max_f"] = self._widen(out["min_f"], out["max_f"])
        return out

    def __call__(self, f, allocator=None, **kwargs):
        """Histogram every outer slice of ``f`` in ONE pass: per-slice
        bounds broadcast into the bin expressions and the offset
        bincount batches all slices through a single device dispatch
        (the reference loops components host-side, histogram.py:313-350;
        so did rounds 1-3 here)."""
        min_max_keys = set(self.get_min_max.reducers.keys())
        bounds_passed = min_max_keys.issubset(set(kwargs.keys()))

        if not bounds_passed:
            bounds = self._auto_bounds(f)
        else:
            bounds = {key: np.asarray(kwargs[key]) for key in min_max_keys}
        # sanitize in the dtype the bin expressions evaluate in, so the
        # degeneracy-widening survives
        bounds = self._sanitize_bounds(bounds, np.dtype(f.dtype))
        # broadcast per-slice bounds against the lattice axes
        env_bounds = {k: jnp.asarray(np.reshape(v, v.shape + (1, 1, 1)))
                      for k, v in bounds.items()}

        out = dict(super().__call__(f=f, **env_bounds))
        out["linear_bins"] = np.linspace(
            bounds["min_f"], bounds["max_f"], self.num_bins + 1,
            axis=-1).astype(self.dtype)
        out["log_bins"] = np.exp(np.linspace(
            bounds["min_log_f"].astype(np.float64),
            bounds["max_log_f"].astype(np.float64), self.num_bins + 1,
            axis=-1)).astype(self.dtype)
        return out
