"""Persistent per-device-kind kernel autotuner for the fused Pallas tier.

The ``choose_blocks`` heuristic picks a *safe* blocking from a VMEM
model; the measured optimum per (device kind, lattice shape, system)
can differ, and the temporal-blocking chunk depth
(:class:`~pystella_tpu.ops.fused.FusedScalarStepper` ``chunk_stages``)
is a genuine tradeoff — redundant halo recompute vs eliminated HBM
round trips — that only a measurement settles. This module makes that
measurement once per device kind and PERSISTS it:

- :func:`sweep` enumerates ``(bx, by, chunk depth, layout)`` candidates
  from the same VMEM model the heuristic uses
  (:func:`~pystella_tpu.ops.pallas_stencil.feasible_blocks` — the
  autotuner can never propose a config the builder would reject),
  measures each with the min-over-rounds **paired** estimator (the
  tests' sentinel-overhead idiom, adapted: candidates interleave
  inside each round so shared-host frequency/scheduler drift hits all
  of them equally, and each candidate's estimate is the minimum over
  rounds of that round's per-step time — noise only ever ADDS time),
  and records the winner;
- :class:`AutotuneStore` persists winners to
  ``bench_results/autotune_<device-kind>.json``, keyed on the PR-6
  program-fingerprint components (kernel shape / dtype / halo / mesh)
  with the compiler-stack versions and scheduler-flag fingerprint
  stored alongside; :meth:`AutotuneStore.lookup` re-derives those from
  the live process and REFUSES a stale entry (``autotune_mismatch``
  event + ``None`` return) exactly as ``WarmstartStore.load`` refuses a
  stale AOT artifact — a jax/libtpu bump can never silently apply last
  quarter's blocking;
- kernel builds consult the table before the heuristic
  (``FusedScalarStepper`` at construction; ``utils.advisor`` renders
  the same lookups so its advice matches what the kernel will really
  pick), emitting a ``block_choice`` event that records the blocking
  actually chosen and its source (``autotune`` | ``heuristic`` |
  ``override``).

Because the table is keyed on the same fingerprint components the
warm-start store uses, a TUNED kernel is AOT-servable through the
PR-12 scenario service's warm pool: sweep on a window, export the tuned
programs, and a later lease dispatches them with zero backend compiles.

CLI::

    python -m pystella_tpu.ops.autotune sweep --grid 256 [--dry-run]
    python -m pystella_tpu.ops.autotune show
    python -m pystella_tpu.ops.autotune gc [--dry-run]

``sweep --dry-run`` shrinks the grid and rounds so the whole path
rehearses on CPU (interpret-mode kernels; the numbers are then
meaningless but the table round trip is real).
"""

from __future__ import annotations

import hashlib
import json
import os
import time

import numpy as np

from pystella_tpu import config as _config

__all__ = ["AutotuneStore", "stepper_key", "default_store", "consult",
           "sweep", "candidate_configs", "measure_candidates"]

SCHEMA_VERSION = 1

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _repo_anchored(path):
    """Relative table dirs anchor at the repository root, not the cwd
    (the ``ensure_compilation_cache`` rule — a tool run from anywhere
    must find the same table)."""
    if not os.path.isabs(path):
        return os.path.join(_REPO_ROOT, path)
    return path


def _device_kind():
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "") or \
        jax.default_backend()
    return str(kind)


def _kind_slug(kind):
    return "".join(c if c.isalnum() else "_" for c in str(kind).lower())


def _live_components():
    """The process components a table entry must match to be served:
    compiler-stack versions and the scheduler-relevant flag fingerprint
    — the exact staleness rule ``WarmstartStore.load`` refuses on."""
    from pystella_tpu.obs.memory import runtime_versions
    from pystella_tpu.parallel.overlap import flags_fingerprint
    return {"versions": runtime_versions(), "flags": flags_fingerprint()}


def stepper_key(kind, local_shape, h, dtype, nscalars,
                gravitational_waves=False, proc_shape=(1, 1, 1),
                carry_dtype=None, tableau="LowStorageRK54"):
    """The structural identity a tuned-stepper entry is keyed on —
    everything that changes the kernels the builder would construct
    (local lattice shape, stencil radius, dtypes, system widths, mesh)
    and nothing that merely labels the run. Returns
    ``(digest, components)``; the version/flag components are checked
    at lookup time, not hashed into the key, so a stale entry is
    REFUSED loudly instead of silently missed."""
    comp = {
        "kind": str(kind),
        "local_shape": [int(s) for s in local_shape],
        "h": int(h),
        "dtype": str(np.dtype(dtype)),
        "carry_dtype": (None if carry_dtype is None
                        else str(np.dtype(carry_dtype))),
        "nscalars": int(nscalars),
        "gravitational_waves": bool(gravitational_waves),
        "proc_shape": [int(p) for p in proc_shape],
        "tableau": str(tableau),
    }
    blob = json.dumps(comp, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16], comp


def _emit(kind, **data):
    try:
        from pystella_tpu.obs import events as _events
        _events.emit(kind, **data)
    except Exception:  # noqa: BLE001 — telemetry must never break a build
        pass


class AutotuneStore:
    """The persistent winner table for ONE device kind.

    :arg root: table directory (default ``PYSTELLA_AUTOTUNE_DIR``,
        itself defaulting to ``bench_results/``; relative paths anchor
        at the repository root).
    :arg device_kind: defaults to the live process's first device's
        ``device_kind`` — which requires jax; pass it explicitly to
        stay jax-free (``show``/``gc`` on a machine without the
        hardware).
    """

    def __init__(self, root=None, device_kind=None):
        if root is None:
            # only the ENV-DEFAULT root anchors at the repo (the
            # ensure_compilation_cache rule); an explicit root resolves
            # like every other artifact path the caller controls
            root = _repo_anchored(
                str(_config.getenv("PYSTELLA_AUTOTUNE_DIR")))
        self.root = os.path.abspath(str(root))
        self.device_kind = (device_kind if device_kind is not None
                            else _device_kind())
        self.path = os.path.join(
            self.root, f"autotune_{_kind_slug(self.device_kind)}.json")

    # -- persistence -------------------------------------------------------

    def _load(self):
        try:
            with open(self.path) as f:
                table = json.load(f)
        except FileNotFoundError:
            return {"schema": SCHEMA_VERSION,
                    "device_kind": self.device_kind, "entries": {}}
        except (OSError, ValueError) as e:
            # a torn/corrupt table is a cache, not data: start fresh
            # but say so (the sweep that repopulates it is cheap next
            # to silently tuning from garbage)
            _emit("autotune_mismatch", path=self.path,
                  problems=[f"unreadable table: {type(e).__name__}: {e}"])
            return {"schema": SCHEMA_VERSION,
                    "device_kind": self.device_kind, "entries": {}}
        table.setdefault("entries", {})
        return table

    def _save(self, table):
        os.makedirs(self.root, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)

    def entries(self):
        """``{digest: entry}`` as persisted (no staleness filtering —
        use :meth:`lookup` for serving decisions)."""
        return dict(self._load()["entries"])

    # -- serving -----------------------------------------------------------

    def _mismatches(self, entry, live=None):
        """The staleness problems that refuse an entry: any
        version/flag component differing from the live process (the
        ``WarmstartStore.load`` rule, verbatim in spirit)."""
        live = live or _live_components()
        problems = []
        for name, val in live["versions"].items():
            have = (entry.get("versions") or {}).get(name)
            if have != val:
                problems.append(f"version {name}: table has {have!r}, "
                                f"process has {val!r}")
        if entry.get("flags") != live["flags"]:
            problems.append(
                f"scheduler flags: table has {entry.get('flags')!r}, "
                f"process has {live['flags']!r}")
        return problems

    def lookup(self, digest, components=None):
        """The winning config for a structural key, or ``None`` — with
        a ``autotune_mismatch`` event when an entry EXISTS but is
        version/flag-stale against the live process (refused, exactly
        like a stale warm-start artifact; the caller falls back to the
        ``choose_blocks`` heuristic)."""
        entry = self._load()["entries"].get(digest)
        if entry is None:
            return None
        problems = self._mismatches(entry)
        if problems:
            _emit("autotune_mismatch", digest=digest, path=self.path,
                  problems=problems,
                  key_kind=(entry.get("key") or {}).get("kind"))
            return None
        if components is not None and entry.get("key") != components:
            # a digest collision with differing structural components
            # would apply a blocking tuned for another kernel — refuse
            _emit("autotune_mismatch", digest=digest, path=self.path,
                  problems=["structural components differ from the "
                            "stored key"])
            return None
        return dict(entry)

    def record(self, digest, components, winner, measurements=None):
        """Persist a sweep winner. ``winner`` carries the tuned config
        (``bx``/``by``/``chunk``/``assemble`` + the measured
        ``ms_per_step``); ``measurements`` optionally keeps the ranked
        candidate table for forensics."""
        table = self._load()
        entry = {
            "key": components,
            **_live_components(),
            "device_kind": self.device_kind,
            "ts": time.time(),
            **winner,
        }
        if measurements is not None:
            entry["swept"] = measurements
        table["entries"][digest] = entry
        self._save(table)
        _emit("autotune_record", digest=digest, path=self.path,
              key_kind=components.get("kind"), **{
                  k: winner.get(k)
                  for k in ("bx", "by", "chunk", "assemble",
                            "ms_per_step")})
        return entry

    def gc(self, dry_run=False):
        """Remove version/flag-STALE entries (exactly the rule
        :meth:`lookup` refuses on; matching entries are never touched).
        Returns ``(kept, removed)`` digest->entry dicts."""
        table = self._load()
        live = _live_components()
        kept, removed = {}, {}
        for digest, entry in table["entries"].items():
            if self._mismatches(entry, live):
                removed[digest] = entry
            else:
                kept[digest] = entry
        if removed and not dry_run:
            table["entries"] = kept
            self._save(table)
            _emit("autotune_gc", path=self.path, removed=len(removed),
                  kept=len(kept))
        return kept, removed


def default_store():
    """The policy-gated store kernel builds consult: ``None`` when
    ``PYSTELLA_AUTOTUNE=0`` (the tier-1 suite pins it off so ambient
    builds stay hermetic; sweeps and drivers opt in explicitly)."""
    if not _config.get_bool("PYSTELLA_AUTOTUNE"):
        return None
    return AutotuneStore()


def consult(kind, local_shape, h, dtype, nscalars,
            gravitational_waves=False, proc_shape=(1, 1, 1),
            carry_dtype=None, store=None, tableau="LowStorageRK54"):
    """Table lookup for a stepper build: ``(entry, digest)`` with
    ``entry=None`` on miss/stale/policy-off. ``store`` may be an
    explicit :class:`AutotuneStore` (hermetic drivers/tests), ``False``
    to skip, or ``None`` for the env-gated default."""
    digest, comp = stepper_key(
        kind, local_shape, h, dtype, nscalars,
        gravitational_waves=gravitational_waves, proc_shape=proc_shape,
        carry_dtype=carry_dtype, tableau=tableau)
    if store is False:
        return None, digest
    if store is None:
        store = default_store()
    if store is None:
        return None, digest
    return store.lookup(digest, comp), digest


# ---------------------------------------------------------------------------
# sweep: candidate generation + the min-over-rounds paired estimator
# ---------------------------------------------------------------------------

def candidate_configs(local_shape, h, dtype, nscalars,
                      gravitational_waves=False, chunk_depths=(0, 4),
                      layouts=("concat",), max_blocks=4):
    """The sweep grid: for each chunk depth (0 = the pair tier) and
    output layout, the top ``max_blocks`` feasible ``(bx, by)``
    blockings of the WIDEST kernel that depth builds, straight from the
    ``choose_blocks`` VMEM model (``feasible_blocks``). Returns a list
    of ``{"bx", "by", "chunk", "assemble"}`` dicts, heuristic-preferred
    order first per depth."""
    from pystella_tpu.ops.pallas_stencil import feasible_blocks
    F = int(nscalars) + (6 if gravitational_waves else 0)
    itemsize = np.dtype(dtype).itemsize
    out = []
    for chunk in chunk_depths:
        if chunk:
            # chunk kernel: all four arrays windowed, no extras — the
            # same (win_halo, stages) the builder passes in
            # FusedScalarStepper._maybe_build_chunk
            n_win, n_extra, stages = 4 * F, 0, int(chunk)
            win_halo = (int(chunk) // 2) * int(h)
        else:
            # pair kernel: f/dfdt/kf windowed, kdfdt a blockwise extra.
            # stages=1, NOT 2: the builder's pair build uses the
            # default VMEM model, and the candidate set must be exactly
            # the builder's feasible set (else the heuristic's own
            # default blocking could never be measured)
            n_win, n_extra, stages = 3 * F, F, 1
            win_halo = int(h)
        blocks = feasible_blocks(
            n_win, local_shape, int(h), itemsize, n_extra, 4 * F,
            win_halo=win_halo, stages=stages)
        for layout in layouts:
            for bx, by in blocks[:int(max_blocks)]:
                out.append({"bx": bx, "by": by, "chunk": int(chunk),
                            "assemble": str(layout)})
    return out


def measure_candidates(build_and_step, configs, nsteps=4, rounds=3,
                       warmup=1):
    """Measure ``ms_per_step`` for each candidate with the
    min-over-rounds paired estimator. ``build_and_step(config)``
    returns a runner: a zero-arg callable that runs (and blocks on)
    ``nsteps`` steps of the already-built candidate and RETURNS the
    wall seconds of the stepping alone — build/compile AND any
    host-to-device staging stay outside the runner's own clock (a
    512^3 sweep would otherwise time ~GiB PCIe transfers into every
    candidate). Candidates INTERLEAVE inside each round (the pairing:
    shared-host drift hits every candidate of a round equally); per
    candidate the estimate is the MINIMUM over rounds of that round's
    per-step time — scheduler noise only ever adds time, so the
    minimum converges on the true cost while a single contaminated
    round cannot flip a ranking. Returns the configs with
    ``ms_per_step`` filled in, fastest first; failed candidates carry
    ``error`` instead and sort last."""
    from pystella_tpu.obs.scope import trace_scope
    runners, results = [], []
    for cfg in configs:
        rec = dict(cfg)
        try:
            runners.append(build_and_step(cfg))
        except Exception as e:  # noqa: BLE001 — an infeasible candidate
            # is data (the sweep table records WHY), not a sweep abort
            runners.append(None)
            rec["error"] = f"{type(e).__name__}: {e}"
        results.append(rec)
    for runner in runners:
        if runner is not None:
            for _ in range(max(0, int(warmup))):
                runner()  # compile + steady-state outside the estimate
    rounds_ms = [[] for _ in results]
    for _ in range(max(1, int(rounds))):
        for k, runner in enumerate(runners):
            if runner is None:
                continue
            with trace_scope("autotune_probe"):
                dt_s = runner()
            rounds_ms[k].append(dt_s * 1e3 / max(1, int(nsteps)))
    for rec, samples in zip(results, rounds_ms):
        if samples:
            rec["ms_per_step"] = float(min(samples))
            rec["rounds_ms_per_step"] = [float(s) for s in samples]
    results.sort(key=lambda r: r.get("ms_per_step", float("inf")))
    return results


def _sweep_state(grid_shape, dtype=np.float32, nscalars=2):
    """The deterministic host-side sweep state (one copy per sweep —
    at 512^3 each candidate closure holding its own would cost ~2 GiB
    of identical arrays apiece)."""
    rng = np.random.default_rng(7)
    return {
        "f": 1e-3 * rng.standard_normal(
            (nscalars,) + tuple(grid_shape)).astype(dtype),
        "dfdt": 1e-4 * rng.standard_normal(
            (nscalars,) + tuple(grid_shape)).astype(dtype),
    }


def _build_sweep_stepper(grid_shape, cfg, dtype=np.float32, h=2,
                         nscalars=2, interpret=None, autotune=False,
                         make_state=True):
    """One candidate FusedScalarStepper (the bench preheat system — the
    same potential the retired root-level ``bench_tune.py`` swept) with
    the candidate's blocking/chunk pinned and the autotune consult OFF
    by default (a sweep must measure its own candidates, not last
    quarter's winner). Drivers reuse it with an explicit store + empty
    ``cfg`` to build the TUNED stepper the table round-trip proofs
    dispatch."""
    import jax
    import pystella_tpu as ps
    decomp = ps.DomainDecomposition((1, 1, 1),
                                    devices=jax.devices()[:1])
    lattice = ps.Lattice(grid_shape, (5.0,) * 3, dtype=dtype)
    mphi, gsq = 1.20e-6, 2.5e-7

    def potential(f):
        return (mphi**2 / 2 * f[0]**2
                + gsq / 2 * f[0]**2 * f[1]**2) / mphi**2

    sector = ps.ScalarSector(nscalars, potential=potential)
    kwargs = dict(dtype=dtype, interpret=interpret, autotune=autotune,
                  # sweep candidates pin their layout; a tuned build
                  # (empty cfg) leaves it None so the table decides
                  assemble=cfg.get("assemble"))
    if cfg.get("chunk"):
        kwargs.update(chunk_stages=int(cfg["chunk"]),
                      chunk_bx=cfg.get("bx"), chunk_by=cfg.get("by"))
    else:
        kwargs.update(pair_bx=cfg.get("bx"), pair_by=cfg.get("by"))
    stepper = ps.FusedScalarStepper(sector, decomp, grid_shape,
                                    lattice.dx, h, **kwargs)
    if cfg.get("chunk") and stepper._chunk_call is None:
        raise ValueError("chunk kernel infeasible at this config")
    if not make_state:
        return stepper, None
    state0 = {k: decomp.shard(v) for k, v in
              _sweep_state(grid_shape, dtype, nscalars).items()}
    return stepper, state0


def sweep(grid_shape, store=None, nsteps=4, rounds=3,
          chunk_depths=(0, 4), layouts=("concat",), max_blocks=4,
          dtype=np.float32, h=2, nscalars=2, interpret=None, log=print):
    """Sweep the bench preheat system at ``grid_shape`` on the live
    backend, record the winner into ``store`` (default:
    :class:`AutotuneStore` for the live device kind), and return the
    ranked measurement list. The timed quantity is
    ``multi_step(nsteps)`` — the production hot loop, stage pairing or
    chunking across step boundaries included."""
    import jax

    store = store or AutotuneStore()
    configs = candidate_configs(grid_shape, h, dtype, nscalars,
                                chunk_depths=chunk_depths,
                                layouts=layouts, max_blocks=max_blocks)
    if not configs:
        raise ValueError(
            f"no feasible sweep candidates for lattice {grid_shape} "
            "(choose_blocks VMEM model admits nothing; see "
            "pystella_tpu.advise_shapes)")
    rhs_args = {"a": np.asarray(1.0, dtype), "hubble":
                np.asarray(0.5, dtype)}
    dt = float(0.1 * 5.0 / max(grid_shape))
    # ONE shared host state for every candidate (identical by seed):
    # multi_step donates its input, so each timed run replays from it
    host0 = _sweep_state(grid_shape, dtype, nscalars)

    def build_and_step(cfg):
        stepper, _ = _build_sweep_stepper(
            grid_shape, cfg, dtype=dtype, h=h, nscalars=nscalars,
            interpret=interpret, make_state=False)

        def run():
            # stage OUTSIDE the clock (donation consumes the buffers,
            # so each run needs fresh ones — but the transfer is not
            # what the table should record)
            fresh = {k: jax.device_put(v) for k, v in host0.items()}
            jax.block_until_ready(fresh)
            t0 = time.perf_counter()
            out = stepper.multi_step(fresh, nsteps, 0.0, dt, rhs_args)
            jax.block_until_ready(out)
            return time.perf_counter() - t0
        return run

    results = measure_candidates(build_and_step, configs,
                                 nsteps=nsteps, rounds=rounds)
    for rec in results:
        if "ms_per_step" in rec:
            log(f"  bx={rec['bx']:3d} by={rec['by']:4d} "
                f"chunk={rec['chunk']} {rec['assemble']:7s}: "
                f"{rec['ms_per_step']:8.3f} ms/step")
        else:
            log(f"  bx={rec['bx']:3d} by={rec['by']:4d} "
                f"chunk={rec['chunk']} {rec['assemble']:7s}: "
                f"FAILED {rec['error']}")
    best = next((r for r in results if "ms_per_step" in r), None)
    if best is None:
        raise RuntimeError("every sweep candidate failed to build/run")
    digest, comp = stepper_key(
        "fused_scalar", grid_shape, h, dtype, nscalars)
    sites = float(np.prod(grid_shape))
    winner = {k: best[k] for k in ("bx", "by", "chunk", "assemble",
                                   "ms_per_step")}
    winner["site_updates_per_s"] = sites * 1e3 / best["ms_per_step"]
    store.record(digest, comp, winner, measurements=[
        {k: r.get(k) for k in ("bx", "by", "chunk", "assemble",
                               "ms_per_step", "error")}
        for r in results])
    _emit("autotune_sweep", grid_shape=list(grid_shape),
          candidates=len(results), path=store.path, **winner)
    log(f"autotune: winner bx={best['bx']} by={best['by']} "
        f"chunk={best['chunk']} {best['assemble']} "
        f"({best['ms_per_step']:.3f} ms/step) -> {store.path}")
    return results


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _cmd_sweep(args):
    if args.dry_run:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n = 16 if args.dry_run and args.grid is None else (args.grid or 256)
    grid = (n, n, n)
    kwargs = {}
    if args.dry_run:
        kwargs.update(nsteps=2, rounds=2, max_blocks=2)
    store = AutotuneStore(root=args.dir) if args.dir else AutotuneStore()
    print(f"autotune sweep: {n}^3, device kind "
          f"{store.device_kind!r}, table {store.path}")
    sweep(grid, store=store,
          chunk_depths=tuple(int(c) for c in args.chunks.split(",")),
          layouts=tuple(args.layouts.split(",")), **kwargs)
    return 0


def _cmd_show(args):
    store = AutotuneStore(root=args.dir or None,
                          device_kind=args.device_kind)
    entries = store.entries()
    if not entries:
        print(f"no entries in {store.path}")
        return 0
    live = _live_components() if args.check else None
    print(f"{store.path}: {len(entries)} entr(ies)")
    for digest, e in sorted(entries.items()):
        key = e.get("key") or {}
        line = (f"  {digest}  {key.get('kind', '?'):13s} "
                f"{'x'.join(map(str, key.get('local_shape', [])))}"
                f" h={key.get('h')} {key.get('dtype')}"
                f" -> bx={e.get('bx')} by={e.get('by')}"
                f" chunk={e.get('chunk')} {e.get('assemble')}"
                f" ({e.get('ms_per_step', float('nan')):.3f} ms/step)")
        if live is not None:
            problems = store._mismatches(e, live)
            line += "  STALE" if problems else "  ok"
        print(line)
    return 0


def _cmd_gc(args):
    store = AutotuneStore(root=args.dir or None,
                          device_kind=args.device_kind)
    kept, removed = store.gc(dry_run=args.dry_run)
    verb = "would remove" if args.dry_run else "removed"
    print(f"{store.path}: kept {len(kept)}, {verb} {len(removed)} "
          "stale entr(ies)")
    return 0


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m pystella_tpu.ops.autotune",
        description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    ps_ = sub.add_parser("sweep", help="measure candidates, record the "
                                       "winner for this device kind")
    ps_.add_argument("--grid", type=int, default=None,
                     help="cube edge (default 256; 16 under --dry-run)")
    ps_.add_argument("--chunks", default="0,4",
                     help="comma-separated chunk depths (0 = pair tier)")
    ps_.add_argument("--layouts", default="concat",
                     help="comma-separated assemble layouts to sweep")
    ps_.add_argument("--dir", default=None,
                     help="table directory (default "
                          "$PYSTELLA_AUTOTUNE_DIR -> bench_results/)")
    ps_.add_argument("--dry-run", action="store_true",
                     help="CPU rehearsal: tiny grid, 2 rounds")

    pshow = sub.add_parser("show", help="print the table")
    pshow.add_argument("--dir", default=None)
    pshow.add_argument("--device-kind", default=None,
                       help="table to read (default: live device)")
    pshow.add_argument("--check", action="store_true",
                       help="mark entries stale vs the live process")

    pgc = sub.add_parser("gc", help="remove version/flag-stale entries")
    pgc.add_argument("--dir", default=None)
    pgc.add_argument("--device-kind", default=None)
    pgc.add_argument("--dry-run", action="store_true")

    args = p.parse_args(argv)
    return {"sweep": _cmd_sweep, "show": _cmd_show,
            "gc": _cmd_gc}[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())
