"""Elementwise map: compile symbolic assignment dicts to jitted functions.

TPU-native stand-in for the reference's loopy-based ``ElementWiseMap``
(/root/reference/pystella/elementwise.py:81-361). There, every elementwise
operation becomes a generated OpenCL kernel with tuned workgroup sizes; here
the "kernel generator" is XLA itself: expressions are traced via
:func:`pystella_tpu.field.evaluate` into one jit-compiled (and fused)
computation over the sharded lattice. There is no parallelization metadata
to manage — layout and fusion are the compiler's job.
"""

from __future__ import annotations

import jax

from pystella_tpu import field as _field

__all__ = ["ElementWiseMap"]


def _assignee_name(key):
    if isinstance(key, _field.Field):
        return key.name
    if isinstance(key, str):
        return key
    raise TypeError(f"assignees must be Field or str, got {type(key)}")


class ElementWiseMap:
    """Maps a dict of ``{assignee: expression}`` over the lattice.

    :arg map_instructions: dict whose keys are :class:`~pystella_tpu.Field`s
        (or strings) naming outputs and whose values are symbolic
        expressions (or callables ``env -> array``).
    :arg tmp_instructions: like ``map_instructions`` but for intermediate
        quantities usable by later expressions (the reference's temporaries,
        elementwise.py:173-193).

    Calling the map with keyword arrays/scalars evaluates all instructions
    and returns a dict of the outputs. The whole evaluation happens inside a
    single ``jax.jit``.
    """

    def __init__(self, map_instructions, tmp_instructions=None, **kwargs):
        self.map_instructions = [(_assignee_name(k), v)
                                 for k, v in dict(map_instructions).items()]
        self.tmp_instructions = [(_assignee_name(k), v)
                                 for k, v in dict(tmp_instructions or {}).items()]

        def run(env):
            env = dict(env)
            for name, expr in self.tmp_instructions:
                env[name] = self._eval(expr, env)
            return {name: self._eval(expr, env)
                    for name, expr in self.map_instructions}

        self._run = jax.jit(run)

    @staticmethod
    def _eval(expr, env):
        if callable(expr) and not isinstance(expr, _field.Expr):
            return expr(env)
        return _field.evaluate(expr, env)

    def __call__(self, **kwargs):
        return self._run(kwargs)
